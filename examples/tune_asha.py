"""Hyperparameter search with Tune: ASHA early stopping over a grid+random
space, TPE searcher, and experiment restore.

Run: JAX_PLATFORMS=cpu python examples/tune_asha.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()


def main():
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    def objective(config):
        # a bowl with its minimum at (lr=0.01, width=32); report a few
        # steps so ASHA can cut the bad trials early
        for step in range(10):
            score = ((config["lr"] - 0.01) ** 2
                     + (config["width"] - 32) ** 2 / 1024
                     + 1.0 / (step + 1))
            tune.report({"score": score, "training_iteration": step + 1})

    tuner = Tuner(
        objective,
        param_space={
            "lr": tune.loguniform(1e-4, 1e-1),
            "width": tune.choice([8, 16, 32, 64]),
        },
        tune_config=TuneConfig(
            num_samples=8,
            metric="score",
            mode="min",
            scheduler=ASHAScheduler(max_t=10, grace_period=2),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result()
    print("best config:", best.config, "score:",
          round(best.metrics["score"], 4))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
