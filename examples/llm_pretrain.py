"""LLM pretraining with JaxTrainer: mesh-sharded Llama on synthetic data.

Run (CPU mesh): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/llm_pretrain.py --preset llama-debug --steps 20
On a TPU host, drop the env vars and pick a real preset
(``--preset llama-1b``); the mesh config maps fsdp over all chips.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()

import jax
import numpy as np
import optax

from ray_tpu import models
from ray_tpu.parallel import MeshConfig
from ray_tpu.train import TrainLoopHelper


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama-debug")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    args = ap.parse_args()

    config = models.get_config(args.preset)
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), config),
        models.param_axes(config),
        lambda p, b: models.loss_and_metrics(p, b, config),
        optax.adamw(3e-4, weight_decay=0.01),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=args.tp, sp=args.sp),
    )
    print(f"mesh: {dict(helper.mesh.shape)}  "
          f"params: {config.num_params() / 1e6:.1f}M")

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = rng.integers(0, config.vocab_size,
                            (args.batch, args.seq + 1), dtype=np.int32)
        batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        metrics = helper.run_step(batch)
        if step % 5 == 0 or step == args.steps - 1:
            loss = float(jax.device_get(metrics["loss"]))
            print(f"step {step:4d}  loss {loss:.4f}")


if __name__ == "__main__":
    main()
