"""Streaming data pipeline: lazy plan -> optimizer -> overlapped execution
-> mesh-sharded jax.Array batches (the Ray Data role, TPU-first ingest).

Run (8-device CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/data_pipeline.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()


def main():
    import numpy as np

    import ray_tpu
    from ray_tpu import data

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    # Lazy plan: map stages fuse into one task per block (rule-based
    # optimizer); execution streams block REFS through the driver while
    # consumers overlap producers.
    ds = (data.range(4096, parallelism=8)
          .map_batches(lambda b: {"x": b["id"] * 2})
          .map_batches(lambda b: {"x": b["x"] + 1}))
    print("plan:", ds.stats() if hasattr(ds, "stats") else ds)

    total = 0
    for batch in ds.iter_batches(batch_size=512):
        total += int(np.asarray(batch["x"]).sum())
    print("sum over stream:", total)

    # groupby/aggregate runs as distributed shuffle tasks
    agg = (data.range(1000, parallelism=4)
           .map_batches(lambda b: {"k": b["id"] % 10, "v": b["id"]})
           .groupby("k").sum("v"))
    rows = {int(r["k"]): int(r["sum(v)"]) for r in agg.take_all()}
    print("groupby sums:", dict(sorted(rows.items())))

    # TPU ingest: shard a global batch over the ambient mesh's data axes
    import jax

    from ray_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1, tp=1, sp=1))
    with jax.set_mesh(mesh):
        it = ds.iterator().iter_jax_batches(batch_size=256, mesh=mesh)
        batch = next(iter(it))
        arr = batch["x"]
        print("sharded batch:", arr.shape, "on",
              len(arr.sharding.device_set), "devices")

    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
