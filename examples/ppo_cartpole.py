"""Two ways to train PPO on CartPole: actor-based and fully-jitted Anakin.

Run: JAX_PLATFORMS=cpu python examples/ppo_cartpole.py --mode anakin
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()


def run_actor_based(iters: int):
    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=256)
              .training(lr=3e-4, minibatch_size=256, num_epochs=8,
                        entropy_coeff=0.01))
    algo = config.build()
    for i in range(iters):
        result = algo.train()
        print(f"iter {i:3d}  return {result.get('episode_return_mean', 0):.1f}")
    algo.cleanup()
    ray_tpu.shutdown()


def run_anakin(iters: int):
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=64, rollout_len=64, lr=1e-3)
    for i in range(iters):
        metrics = algo.train()
        print(f"iter {i:3d}  return {metrics['episode_return_mean']:.1f}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["actors", "anakin"], default="anakin")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()
    if args.mode == "actors":
        run_actor_based(args.iters)
    else:
        run_anakin(args.iters)
