"""Serve a (tiny) LLM with dynamic batching over replica actors.

Run: JAX_PLATFORMS=cpu python examples/serve_llm.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()

import numpy as np

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=1)
class LLMReplica:
    """Loads a jitted model once; every request hits the compiled fn."""

    def __init__(self, preset="gpt2-debug"):
        import jax

        from ray_tpu import models

        self.config = models.get_config(preset)
        self.params = models.init_params(jax.random.PRNGKey(0), self.config)
        self.models = models

    def __call__(self, prompt_tokens):
        import jax.numpy as jnp

        prompt = jnp.asarray([prompt_tokens], jnp.int32)
        out = self.models.generate(self.params, prompt, self.config,
                                   max_new_tokens=8)
        return np.asarray(out)[0].tolist()


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    handle = serve.run(LLMReplica.bind())
    out = handle.remote([1, 2, 3, 4]).result(timeout_s=120)
    print("generated tokens:", out)
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
