"""Fine-tune an imported HuggingFace checkpoint, then sample from it.

End-to-end: transformers Llama weights -> ray_tpu param pytree
(``models/import_hf.py``, exact-parity mapping) -> a few training steps
with ``TrainLoopHelper`` (pjit over an fsdp mesh, scanned inner loop) ->
greedy generation through the KV-cache decode path.

Uses a tiny randomly initialized HF model so the example runs offline in
seconds; point ``load_hf_llama("<local checkpoint dir>")`` at real
weights on a machine that has them.

Run: JAX_PLATFORMS=cpu python examples/hf_finetune.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()

import jax
import numpy as np
import optax
import torch
from transformers import LlamaConfig, LlamaForCausalLM

from ray_tpu import models
from ray_tpu.parallel import MeshConfig
from ray_tpu.train import TrainLoopHelper

# 1. a "checkpoint" (tiny + random so the example is self-contained)
torch.manual_seed(0)
hf = LlamaForCausalLM(LlamaConfig(
    vocab_size=256, hidden_size=128, intermediate_size=192,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=128, rms_norm_eps=1e-5)).eval()

# 2. import: config + weights (exact logits parity with transformers)
config = models.config_from_hf(hf.config).replace(remat=False)
params = models.import_hf_llama(hf.state_dict(), config)
print(f"imported {config.num_params():,} params "
      f"(d={config.d_model}, L={config.n_layers})")

# 3. fine-tune on a toy corpus (learn to repeat a phrase)
phrase = np.tile(np.arange(17, 49, dtype=np.int32), 5)[:65]
batch = {"inputs": np.tile(phrase[:-1], (4, 1)),
         "targets": np.tile(phrase[1:], (4, 1))}
helper = TrainLoopHelper.create(
    lambda: params,
    models.param_axes(config),
    lambda p, b: models.loss_and_metrics(p, b, config),
    optax.adamw(1e-3),
    mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
)
for step in range(5):
    metrics = helper.run_steps(batch, 10)
    print(f"step {(step + 1) * 10:3d}  "
          f"loss {float(jax.device_get(metrics['loss'])):.4f}")

# 4. sample with the fine-tuned weights (KV-cache greedy decode)
tuned = jax.tree.map(jax.numpy.asarray, helper.state["params"])
out = models.generate(tuned, jax.numpy.asarray(phrase[None, :8]),
                      config, max_new_tokens=16)
print("prompt ", phrase[:8].tolist())
print("sampled", np.asarray(out)[0, 8:].tolist())
print("target ", phrase[8:24].tolist())
