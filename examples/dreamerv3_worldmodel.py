"""DreamerV3: learn a world model from replayed sequences, act from it.

The learner fits an RSSM world model (GRU + categorical latents) to
random-policy sequences of a goal-reading toy env, trains an
actor-critic purely on IMAGINED rollouts (no additional env steps), and
then the greedy policy solves the env — the model-based RL loop, with
all three phases (world-model fit, imagination, actor/critic update)
scanned into one jitted device program per ``update()``.

Run: JAX_PLATFORMS=cpu python examples/dreamerv3_worldmodel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.util.tpu_info import honor_jax_platform_env

honor_jax_platform_env()

import numpy as np

from ray_tpu.rllib import DreamerV3Learner

N_ACTIONS, NOISE, T = 4, 2, 8


def rollout(rng, batch):
    """Random-policy sequences: obs one-hot-encodes a per-episode goal
    action; acting the goal yields reward 1 with the NEXT observation."""
    goals = rng.integers(0, N_ACTIONS, size=batch)
    obs = np.zeros((batch, T, N_ACTIONS + NOISE), np.float32)
    for b in range(batch):
        obs[b, :, goals[b]] = 1.0
    obs[:, :, N_ACTIONS:] = 0.3 * rng.standard_normal(
        (batch, T, NOISE)).astype(np.float32)
    actions = rng.integers(0, N_ACTIONS, size=(batch, T)).astype(np.int32)
    rewards = np.zeros((batch, T), np.float32)
    rewards[:, 1:] = (actions[:, :-1] == goals[:, None]).astype(np.float32)
    return {"obs": obs, "actions": actions, "rewards": rewards,
            "continues": np.ones((batch, T), np.float32)}, goals


rng = np.random.default_rng(0)
learner = DreamerV3Learner(
    {"observation_dim": N_ACTIONS + NOISE, "action_dim": N_ACTIONS},
    {"deter": 64, "hidden": 64, "groups": 4, "classes": 4, "horizon": 5,
     "wm_lr": 3e-3, "actor_lr": 3e-3, "entropy_coef": 1e-2})

for i in range(250):
    batch, _ = rollout(rng, 16)
    m = learner.update(batch)
    if i % 50 == 0:
        print(f"update {i:3d}  wm_loss {m['wm_loss']:.3f}  "
              f"imagined_return {m['imag_return']:.2f}  "
              f"entropy {m['actor_entropy']:.2f}")

# evaluate the greedy policy (acts via posterior filtering of real obs)
batch, goals = rollout(rng, 64)
state = learner.policy_state(64)
prev_a = np.zeros(64, np.int64)
hits = 0
for t in range(T):
    state, a = learner.act(state, batch["obs"][:, t], prev_a, greedy=True)
    hits += int((np.asarray(a) == goals).sum())
    prev_a = np.asarray(a)
print(f"greedy hit rate {hits / (64 * T):.2f} (random would be "
      f"{1 / N_ACTIONS:.2f}) — learned entirely from imagination")
