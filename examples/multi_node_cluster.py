"""Multi-node cluster walkthrough: spread, transfer, streaming, failover.

Boots a GCS control-plane process plus two node daemons ON THIS MACHINE
(the reference's cluster_utils pattern) — the same code drives real
multi-host clusters by running `python -m ray_tpu.cluster.gcs_server` on
the head and `python -m ray_tpu.cluster.node_daemon --gcs HEAD:PORT` on
each worker host.

Run: python examples/multi_node_cluster.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import ray_tpu  # noqa: E402
from ray_tpu.cluster import Cluster  # noqa: E402


def main():
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    ray_tpu.init(address=cluster.address, cluster_authkey=cluster.authkey,
                 num_cpus=2)
    print(f"cluster: {len([n for n in ray_tpu.nodes() if n['Alive']])} nodes")

    # -- tasks spread across nodes by resources ------------------------
    @ray_tpu.remote(resources={"worker": 1}, max_retries=2)
    def square(x):
        time.sleep(0.2)
        return x * x

    print("squares:", ray_tpu.get([square.remote(i) for i in range(8)],
                                  timeout=120))

    # -- large objects move node-to-node on demand ---------------------
    @ray_tpu.remote(resources={"worker": 1})
    def make_shard(i):
        return np.full(1 << 16, float(i))

    @ray_tpu.remote(resources={"worker": 1})
    def reduce_shards(*shards):
        return float(sum(s.sum() for s in shards))

    total = ray_tpu.get(
        reduce_shards.remote(*[make_shard.remote(i) for i in range(4)]),
        timeout=120)
    print(f"reduced 4x512KiB shards across nodes: {total}")

    # -- streaming generator: consume while the producer runs ----------
    @ray_tpu.remote(num_returns="streaming")
    def token_stream(n):
        for i in range(n):
            yield f"token-{i}"
            time.sleep(0.2)

    print("stream:", [ray_tpu.get(r) for r in token_stream.remote(5)])

    # -- failover: kill a node, retryable work finishes elsewhere ------
    refs = [square.remote(100 + i) for i in range(4)]
    cluster.kill_node(0)
    print("after node kill:", ray_tpu.get(refs, timeout=120))

    ray_tpu.shutdown()
    cluster.shutdown()
    print("done")


main()
