import os
import sys

# Must happen before any jax import anywhere in the test session: run tests
# on a virtual 8-device CPU mesh so multi-chip sharding logic is exercised
# without TPU hardware (the driver separately dry-runs the multichip path).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize may have already imported jax and registered a
# TPU backend before this conftest runs; jax.config.update still wins as
# long as no device query has happened yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# `kill -USR1 <pytest pid>` dumps all thread stacks — the only way to see
# where the DRIVER side of a hung cluster test is parked (workers already
# register this in worker.py).
import faulthandler  # noqa: E402
import signal  # noqa: E402

try:
    faulthandler.register(signal.SIGUSR1, all_threads=True)
except (AttributeError, ValueError):
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running capacity/stress tests")
    config.addinivalue_line(
        "markers", "modern_jax: needs jax APIs absent from old sandboxes "
        "(jax.shard_map / jax.sharding.set_mesh / pallas CompilerParams)")


def _modern_jax_missing():
    """Feature-detect the jax APIs the models/ops/rllib/parallel suites
    need. Old sandbox jax (0.4.x) lacks all three; on a full jax this
    returns [] and the gate below is a no-op (pass counts unchanged)."""
    missing = []
    if not hasattr(jax, "shard_map"):
        missing.append("jax.shard_map")
    if not hasattr(jax.sharding, "set_mesh"):
        missing.append("jax.sharding.set_mesh")
    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            missing.append("pallas CompilerParams")
    except Exception:
        missing.append("jax.experimental.pallas.tpu")
    return missing


# Tests (by file -> function name, parametrizations included) that
# exercise the modern-jax APIs above. On an old jax they fail on the
# missing API, not on ray_tpu code — report them as SKIPS there so a
# sandbox run distinguishes "environment can't run this" from real
# regressions. Derived from the measured old-jax failure set (seed-
# identical); anything newly added that needs these APIs can either join
# this table or carry @pytest.mark.modern_jax directly.
_MODERN_JAX_TESTS = {
    "test_models.py": {
        "test_forward_shapes", "test_loss_decreases_under_sgd",
        "test_decode_matches_forward", "test_sharded_train_step_tp_fsdp",
        "test_sharded_train_step_ring_attention_sp",
        "test_remat_policies_grad_equivalent",
        "test_chunked_xent_matches_dense",
        "test_chunked_xent_pads_non_divisible_seq",
        "test_mistral_sliding_window_trains_and_decodes",
        "test_mistral_sp_halo_train_step",
        "test_gemma2_alternating_windows_exact",
        "test_gemma2_decode_matches_forward",
        "test_attn_windows_config_validation",
        "test_hf_llama_import_logits_parity",
        "test_hf_qwen2_import_logits_parity",
    },
    "test_ops.py": {
        "test_pallas_interpret_matches_naive", "test_pallas_interpret_gqa",
        "test_ring_attention_matches_full", "test_moe_shapes_and_gradient",
        "test_moe_full_capacity_matches_dense_topk",
        "test_pallas_fwd_lse_interpret_and_hybrid_grad",
        "test_pallas_bwd_kernels_match_naive_grads",
        "test_pallas_bwd_gqa_native_heads",
        "test_sliding_window_pallas_interpret_fwd_bwd",
        "test_sliding_window_sp_halo_matches_single_device",
        "test_softcap_fwd_bwd_all_impls_match_naive",
    },
    "test_rllib.py": {
        "test_ppo_learns_cartpole_local", "test_ppo_remote_env_runners",
        "test_impala_single_step", "test_algorithm_checkpoint_roundtrip",
        "test_ppo_postprocess_drops_invalid_rows",
        "test_learner_mesh_sharded_matches_single_device",
        "test_learner_padding_unbiased",
        "test_learner_group_grad_sync_matches_local",
        "test_impala_aggregation_tree",
        "test_learner_group_int8_grad_compression",
    },
    "test_rllib_offpolicy.py": {
        "test_offline_roundtrip_and_bc",
        "test_appo_single_step_and_adaptive_kl",
        "test_appo_learns_cartpole",
        "test_marwil_beats_bc_on_mixed_quality_data",
    },
    "test_multi_agent.py": {
        "test_multi_agent_ppo_learns_cooperative_match",
        "test_multi_agent_ppo_remote_runners_and_checkpoint",
    },
    "test_parallel.py": {
        "test_collective_ops_inside_shard_map",
        "test_ring_permute_rolls_shards", "test_constrain_inside_jit",
        "test_quantized_psum_matches_exact_within_quant_error",
    },
    "test_collective.py": {
        "test_xla_group_local_devices", "test_xla_group_full_verb_matrix",
        "test_xla_distributed_group_two_processes",
    },
    "test_train.py": {
        "test_train_loop_helper_llama_loss_decreases",
        "test_profile_steps_captures_trace",
        "test_run_step_rejects_indivisible_batch_loudly",
    },
}


def pytest_collection_modifyitems(config, items):
    missing = _modern_jax_missing()
    if not missing:
        return
    skip = pytest.mark.skip(
        reason="needs modern jax APIs: " + ", ".join(missing))
    for item in items:
        names = _MODERN_JAX_TESTS.get(item.fspath.basename, ())
        if (item.name.split("[")[0] in names
                or item.get_closest_marker("modern_jax")):
            item.add_marker(skip)


def poll_until(predicate, timeout=30.0, interval=0.2, desc="condition"):
    """Retry ``predicate`` until it returns a truthy value (returned).

    Deflake helper for cluster tests (round-5 flake notes): transient
    ``ConnectionError``/``TimeoutError``/``OSError`` raised by a poll —
    a GCS client mid-reconnect, an HTTP scrape racing server start — are
    retried instead of failing the test; any other exception propagates.
    Raises AssertionError with the last transient error on timeout.
    """
    import time as _time

    deadline = _time.monotonic() + timeout
    last_exc = None
    while _time.monotonic() < deadline:
        try:
            val = predicate()
            if val:
                return val
            last_exc = None
        except (ConnectionError, TimeoutError, OSError) as e:
            last_exc = e
        _time.sleep(interval)
    raise AssertionError(
        f"poll_until({desc}) timed out after {timeout}s"
        + (f"; last transient error: {last_exc!r}" if last_exc else ""))


@pytest.fixture(scope="session", autouse=True)
def _native_build_contract():
    """The native extension is either fully loaded or cleanly fallen
    back — never a silent half-state (r14 satellite): a .so that loads
    but lacks the pipe-engine symbols after the automatic rebuild is a
    broken build this suite refuses to paper over."""
    from ray_tpu import _native

    st = _native.native_status()
    assert not st.get("stale"), (
        f"native extension half-state {st}: the .so loaded but lacks the "
        f"pipe engine after a rebuild attempt — run `make -C native` and "
        f"check compiler output")
    # loaded implies every feature family is bound; not loaded means the
    # pure-Python fallbacks are active everywhere (a consistent state)
    if st["loaded"]:
        assert st["pipe"] and st["lz4"], st
    yield


@pytest.fixture
def rt():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def rt_module():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()
