import os
import sys

# Must happen before any jax import anywhere in the test session: run tests
# on a virtual 8-device CPU mesh so multi-chip sharding logic is exercised
# without TPU hardware (the driver separately dry-runs the multichip path).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize may have already imported jax and registered a
# TPU backend before this conftest runs; jax.config.update still wins as
# long as no device query has happened yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# `kill -USR1 <pytest pid>` dumps all thread stacks — the only way to see
# where the DRIVER side of a hung cluster test is parked (workers already
# register this in worker.py).
import faulthandler  # noqa: E402
import signal  # noqa: E402

try:
    faulthandler.register(signal.SIGUSR1, all_threads=True)
except (AttributeError, ValueError):
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running capacity/stress tests")


def poll_until(predicate, timeout=30.0, interval=0.2, desc="condition"):
    """Retry ``predicate`` until it returns a truthy value (returned).

    Deflake helper for cluster tests (round-5 flake notes): transient
    ``ConnectionError``/``TimeoutError``/``OSError`` raised by a poll —
    a GCS client mid-reconnect, an HTTP scrape racing server start — are
    retried instead of failing the test; any other exception propagates.
    Raises AssertionError with the last transient error on timeout.
    """
    import time as _time

    deadline = _time.monotonic() + timeout
    last_exc = None
    while _time.monotonic() < deadline:
        try:
            val = predicate()
            if val:
                return val
            last_exc = None
        except (ConnectionError, TimeoutError, OSError) as e:
            last_exc = e
        _time.sleep(interval)
    raise AssertionError(
        f"poll_until({desc}) timed out after {timeout}s"
        + (f"; last transient error: {last_exc!r}" if last_exc else ""))


@pytest.fixture
def rt():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def rt_module():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()
