"""Serving tier (ISSUE 12): paged KV block pool, prefix trie, COW,
dense-vs-paged numerics parity, chunked prefill, SLO admission,
deadlines, KV-aware routing, and the replica-death chaos case under the
replay generator (no leaked blocks)."""

import dataclasses
import time

import numpy as np
import pytest

from ray_tpu.serve.admission import (AdmissionController,
                                     DeadlineExceededError,
                                     RequestShedError, SLOConfig)
from ray_tpu.serve.kv_cache import BlockPool, KVCacheError, PrefixCache


# ---------------------------------------------------------------------------
# block pool
# ---------------------------------------------------------------------------

def test_block_pool_alloc_free_refcount():
    pool = BlockPool(8, 4)
    assert pool.free_count == 8 and pool.used_count == 0
    a = pool.alloc(3)
    assert len(a) == 3 and pool.free_count == 5
    assert all(pool.refcount(b) == 1 for b in a)
    # all-or-nothing: a too-big claim takes NOTHING
    assert pool.alloc(6) is None
    assert pool.free_count == 5
    # sharing: retain bumps, release drops, last ref frees
    pool.retain(a[0])
    assert pool.need_cow(a[0]) and not pool.need_cow(a[1])
    assert not pool.release(a[0])          # one ref left
    assert pool.release(a[0])              # freed
    assert pool.free_count == 6
    with pytest.raises(KVCacheError):
        pool.release(a[0])                 # double free is a bug
    with pytest.raises(KVCacheError):
        pool.retain(a[0])                  # retain of a free block too
    assert pool.release_all(a[1:]) == 2
    assert pool.free_count == 8
    assert pool.blocks_for_tokens(0) == 0
    assert pool.blocks_for_tokens(1) == 1
    assert pool.blocks_for_tokens(4) == 1
    assert pool.blocks_for_tokens(5) == 2


# ---------------------------------------------------------------------------
# prefix trie
# ---------------------------------------------------------------------------

def test_prefix_trie_hit_miss_and_cap():
    pool = BlockPool(16, 4)
    trie = PrefixCache(pool)
    prompt = list(range(10))               # 2 full blocks + 2 tail tokens
    blocks = pool.alloc(3)
    assert trie.match(prompt) == ([], 0, None)       # cold: miss
    assert trie.insert(prompt, blocks) == 2          # only FULL blocks
    assert len(trie) == 2
    # the trie holds its own refs; the request releases its copies
    pool.release_all(blocks)
    assert pool.refcount(blocks[0]) == 1 and pool.refcount(blocks[2]) == 0

    # longer prompt with the same head: both full blocks reused
    got, matched, cow = trie.match(list(range(8)) + [99, 98, 97])
    assert got == blocks[:2] and matched == 8 and cow is None
    assert pool.refcount(blocks[0]) == 2             # caller now holds one
    pool.release_all(got)

    # EXACT full-block prompt: capped at len-1 -> tail becomes COW source
    got, matched, cow = trie.match(list(range(8)))
    assert got == blocks[:1] and matched == 7 and cow == blocks[1]
    assert pool.refcount(blocks[1]) == 2             # retained for the copy
    pool.release_all(got)
    pool.release(cow)

    # diverging second block: only the first matches
    got, matched, cow = trie.match(list(range(4)) + [77, 77, 77, 77, 5])
    assert got == blocks[:1] and matched == 4 and cow is None
    pool.release_all(got)
    s = trie.stats()
    assert s["hits"] == 3 and s["misses"] == 1


def test_prefix_trie_eviction_lru_and_pinning():
    pool = BlockPool(4, 2)
    trie = PrefixCache(pool)
    a = pool.alloc(1)
    trie.insert([1, 2], a)
    time.sleep(0.01)
    b = pool.alloc(1)
    trie.insert([3, 4], b)
    pool.release_all(a + b)
    assert pool.free_count == 2            # trie pins both
    # a live sharer pins its chain against eviction — and the claimable
    # signal agrees (only the unshared leaf is evictable right now)
    got, _, _ = trie.match([1, 2, 9])
    assert got == a
    assert trie.evictable_count() == 1
    assert trie.evict(2) == 1              # only the unshared LRU leaf goes
    assert pool.refcount(b[0]) == 0 and pool.refcount(a[0]) == 2
    pool.release_all(got)
    assert trie.evict(2) == 1              # now reclaimable
    assert pool.free_count == 4 and len(trie) == 0
    # chains evict leaf-first: parent becomes reclaimable next round
    c = pool.alloc(2)
    trie.insert([5, 6, 7, 8], c)
    pool.release_all(c)
    assert trie.evict(4) == 2
    assert pool.free_count == 4


# ---------------------------------------------------------------------------
# engine: parity, prefix COW, chunked prefill
# ---------------------------------------------------------------------------

def _f32_cfg():
    from ray_tpu import models

    # f32: greedy parity across kernels (bf16 logit ties flip on 1-ULP
    # cross-kernel rounding differences — see test_serve.py's LLM test)
    return dataclasses.replace(models.get_config("llama-debug"),
                               dtype="float32", param_dtype="float32")


def _drain(eng, max_steps=500):
    for _ in range(max_steps):
        if not eng.step():
            return
    raise AssertionError("engine did not drain")


def _run_prompts(eng, prompts, max_new):
    outs = []
    for p in prompts:
        sink = []
        outs.append(sink)
        eng.submit(p, max_new, sink.append)
    _drain(eng)
    return [[t for t in o if t is not None] for o in outs]


def test_paged_dense_numerics_parity():
    """Same prompts, shared prefixes included: paged (with prefix reuse
    + chunked prefill) == dense == sequential generate, token-exact."""
    import jax

    from ray_tpu import models
    from ray_tpu.models import transformer as T
    from ray_tpu.serve.llm import LLMEngine

    cfg = _f32_cfg()
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(3)
    shared = rng.integers(0, 256, 12).tolist()
    prompts = [shared + rng.integers(0, 256, n).tolist()
               for n in (3, 9, 5, 17)]
    refs = []
    for p in prompts:
        g = T.generate(params, jax.numpy.asarray(
            np.asarray(p, np.int32)[None]), cfg, max_new_tokens=6)
        refs.append([int(x) for x in np.asarray(g[0, len(p):])])

    dense = LLMEngine(cfg, params, max_slots=4, max_len=64, paged=False)
    assert _run_prompts(dense, prompts, 6) == refs

    paged = LLMEngine(cfg, params, max_slots=4, max_len=64, paged=True,
                      block_size=4, prefill_chunk=4)
    assert _run_prompts(paged, prompts, 6) == refs
    # run the SAME prompts again: now the trie serves the shared prefix
    # (and the full-prompt repeats exercise the COW path) — still exact
    assert _run_prompts(paged, prompts, 6) == refs
    assert paged.prefix.stats()["hits"] >= 4
    assert paged.stats["prefix_hit_tokens"] >= 4 * 12


def test_prefix_cow_exact_repeat():
    """A prompt repeated EXACTLY forces the capped match: the tail block
    is copy-on-write'd, the original stays immutable for other sharers,
    and generation stays token-exact."""
    import jax

    from ray_tpu import models
    from ray_tpu.models import transformer as T
    from ray_tpu.serve.llm import LLMEngine

    cfg = _f32_cfg()
    params = models.init_params(jax.random.PRNGKey(1), cfg)
    prompt = np.random.default_rng(5).integers(0, 256, 8).tolist()
    g = T.generate(params, jax.numpy.asarray(
        np.asarray(prompt, np.int32)[None]), cfg, max_new_tokens=5)
    ref = [int(x) for x in np.asarray(g[0, len(prompt):])]

    eng = LLMEngine(cfg, params, max_slots=2, max_len=32, block_size=4,
                    prefill_chunk=4)
    assert _run_prompts(eng, [prompt], 5) == [ref]
    before = eng.pool.free_count
    assert _run_prompts(eng, [prompt], 5) == [ref]   # exact repeat: COW
    s = eng.prefix.stats()
    assert s["hits"] == 1 and s["hit_tokens"] == len(prompt) - 1
    assert eng.pool.free_count == before             # no leak either way


def test_chunked_prefill_does_not_stall_decode():
    """A decoding request keeps emitting ~every step while a long prompt
    prefills in chunks beside it (the whole point of chunked prefill)."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_f32_cfg(), max_slots=2, max_len=256, block_size=16,
                    prefill_chunk=16)
    first = []
    eng.submit([1, 2, 3], 40, first.append)
    for _ in range(10):
        eng.step()                       # first request is decoding now
    tokens_before = len(first)
    long_prompt = list(np.random.default_rng(0).integers(0, 256, 160))
    second = []
    eng.submit(long_prompt, 2, second.append)
    steps = 0
    while second.count(None) == 0:
        eng.step()
        steps += 1
        assert steps < 60, "long prompt starved the engine"
    # the 160-token prompt consumed ~160/16 steps, not 160
    assert steps <= 20
    # and the decoding request kept producing alongside the prefill
    emitted_during = len([t for t in first if t is not None]) \
        - tokens_before
    assert emitted_during >= steps - 2


# ---------------------------------------------------------------------------
# admission + deadlines
# ---------------------------------------------------------------------------

def test_admission_controller_gates():
    ac = AdmissionController(SLOConfig(ttft_s=1.0, max_queue_s=0.5,
                                       tpot_s=0.05))
    # cold controller (no step estimate): everything admits
    ac.check_admit(64, 10, 640, 8, 1, 0)
    ac.observe_step(0.2)
    # queue gate: 10 queued * 0.2s = 2s > 0.5s
    with pytest.raises(RequestShedError) as e:
        ac.check_admit(8, 10, 80, 8, 1, 0)
    assert e.value.reason == "queue"
    # ttft gate: own prefill alone projects over 1s
    with pytest.raises(RequestShedError) as e:
        ac.check_admit(80, 0, 0, 8, 1, 0)
    assert e.value.reason == "ttft"
    # tpot gate: decode already slower than target with live streams
    with pytest.raises(RequestShedError) as e:
        ac.check_admit(1, 0, 0, 8, 1, 4)
    assert e.value.reason == "tpot"
    # deadline gate: projection exceeds the request's own budget
    ac2 = AdmissionController(SLOConfig())
    ac2.observe_step(0.2)
    with pytest.raises(RequestShedError) as e:
        ac2.check_admit(80, 0, 0, 8, 1, 0, deadline_s=0.5)
    assert e.value.reason == "deadline"
    snap = ac.snapshot()
    assert snap["shed"] == 3 and snap["shed_by_reason"]["ttft"] == 1


def test_engine_sheds_and_enforces_queue_deadline():
    from ray_tpu.serve.llm import LLMEngine

    # ttft_s=0 arms an always-shed gate once a step time is measured
    eng = LLMEngine(_f32_cfg(), max_slots=1, max_len=64,
                    slo=SLOConfig(ttft_s=1e-9))
    out = []
    eng.submit([1, 2, 3], 2, out.append)   # cold: admitted
    _drain(eng)
    with pytest.raises(RequestShedError):
        eng.submit([1, 2, 3], 2, out.append)

    # deadline enforced ACROSS ADMISSION QUEUEING: with one slot busy on
    # a long generation, a queued request expires before ever running.
    # Both submits land before the first step (cold projection admits);
    # FIFO puts the long request in the slot and the deadlined one in
    # the queue, where it must expire — not run late.
    eng2 = LLMEngine(_f32_cfg(), max_slots=1, max_len=128)
    slow, fast = [], []
    eng2.submit([1, 2, 3], 60, slow.append)
    eng2.submit([4, 5, 6], 4, fast.append, deadline_s=0.05)
    deadline = time.monotonic() + 30
    while not fast and time.monotonic() < deadline:
        eng2.step()
    assert fast and isinstance(fast[0], DeadlineExceededError), fast[:1]
    assert eng2.stats["deadline_drops"] == 1
    _drain(eng2)
    # the expired request never claimed blocks; the finished one freed
    # everything back except what the trie adopted
    assert eng2.pool.free_count + len(eng2.prefix) == eng2.pool.num_blocks


def test_pool_pressure_rejects_impossible_and_keeps_stats_honest():
    """A request bigger than the WHOLE pool is rejected at submit (it
    could never be admitted — queueing it would pin the FIFO head and
    busy-spin the loop); a merely-queued request re-running its prefix
    match every step must not inflate the hit counters."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_f32_cfg(), max_slots=2, max_len=64, block_size=4,
                    num_blocks=8, prefill_chunk=4)
    with pytest.raises(ValueError, match="KV blocks"):
        eng.submit(list(range(30)), 8, lambda t: None)   # 10 > 8 blocks

    # fill the pool with one request, seed the trie, then queue a
    # prefix-hitting request that cannot claim yet
    done = []
    prompt = list(np.random.default_rng(0).integers(0, 256, 16))
    eng.submit(prompt, 8, done.append)                   # 6 of 8 blocks
    hog = []
    eng.submit(list(np.random.default_rng(1).integers(0, 256, 8)), 16,
               hog.append)                               # 6 blocks: waits
    waiter = []
    eng.submit(prompt[:12] + [9], 4, waiter.append)      # prefix of 1st
    for _ in range(6):
        eng.step()
    s = eng.prefix.stats()
    # the queued waiter's repeated failed claims count AT MOST once
    assert s["hits"] + s["misses"] <= 2, s
    _drain(eng)
    assert eng.pool.free_count + len(eng.prefix) == eng.pool.num_blocks


# ---------------------------------------------------------------------------
# block-leak audit under churn (cancel mid-stream)
# ---------------------------------------------------------------------------

def test_no_block_leak_under_cancel_churn():
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_f32_cfg(), max_slots=4, max_len=64, block_size=4,
                    prefill_chunk=4)
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 256, 8).tolist()
    reqs = []
    for i in range(12):
        sink = []
        p = shared + rng.integers(0, 256, int(rng.integers(1, 20))).tolist()
        reqs.append((eng.submit(p, 8, sink.append), sink))
    for step in range(8):
        eng.step()
        if step in (2, 4):               # cancel a batch mid-flight
            for r, _ in reqs[step::3]:
                eng.cancel(r)
    _drain(eng)
    # every non-trie block is back on the free list
    assert eng.pool.free_count + len(eng.prefix) == eng.pool.num_blocks
    # and the trie's blocks are exactly single-referenced
    trie_blocks = eng.pool.num_blocks - eng.pool.free_count
    assert trie_blocks == len(eng.prefix)
    # the ROUTING/AUTOSCALE signal reads the warm idle replica as fully
    # claimable (prefix retention is cache value, not pressure)
    assert eng.kv_state()["kv_claimable"] == eng.pool.num_blocks


# ---------------------------------------------------------------------------
# routing + autoscaling (controller-level)
# ---------------------------------------------------------------------------

def test_controller_kv_loads_and_autoscale():
    from ray_tpu.serve.controller import ServeController

    class _FakeReplica:
        def __init__(self, aid):
            class _Id:
                def __init__(self, b):
                    self._b = b

                def binary(self):
                    return self._b

            self._actor_id = _Id(aid)

    ctrl = ServeController.__new__(ServeController)
    ctrl._deployments = {}
    ctrl._version = 0
    ctrl._metrics = {}
    ctrl._deployments["llm"] = {
        "replicas": [_FakeReplica(b"a"), _FakeReplica(b"b")],
        "target": 2,
        "spec": {"config": {
            "autoscaling_config": {
                "min_replicas": 1, "max_replicas": 4,
                "target_ongoing_requests": 100.0,
                "upscale_factor": 1.5, "downscale_factor": 0.0,
                "target_kv_utilization": 0.5},
            "ray_actor_options": {"num_cpus": 2}}},
    }
    ctrl.report_replica_load("llm", b"a",
                             {"inflight": 3, "kv_free": 2, "kv_total": 32})
    ctrl.report_replica_load("llm", b"b",
                             {"inflight": 1, "kv_free": 4, "kv_total": 32})
    loads = ctrl.get_replica_loads("llm")
    assert loads[b"a"]["kv_free"] == 2 and "ts" in loads[b"a"]
    # ~92% average KV occupancy vs target 0.5 -> desired ~2*1.84 -> 4
    assert ctrl._desired_replicas("llm") == 4
    # v2 bridge: 2 missing replicas -> 2 bundles of the actor's resources
    bundles = ctrl.v2_demand()
    assert bundles == [{"CPU": 2.0}, {"CPU": 2.0}]
    # explicit num_cpus=0 advertises NO phantom CPU demand
    ctrl._deployments["llm"]["spec"]["config"]["ray_actor_options"] = {
        "num_cpus": 0, "resources": {"tpu_slot": 1}}
    assert ctrl.v2_demand() == [{"tpu_slot": 1.0}, {"tpu_slot": 1.0}]
    # death report prunes the corpse's load record
    ctrl._deployments["llm"]["spec"]["config"]["num_replicas"] = 2
    ctrl._kill = lambda r: None
    ctrl._make_replica = lambda spec: _FakeReplica(b"c")
    ctrl.report_replica_death("llm", b"a")
    assert b"a" not in ctrl.get_replica_loads("llm")


def test_handle_scores_fold_in_kv_and_exclude(monkeypatch):
    from ray_tpu.serve.handle import DeploymentHandle

    class _Id:
        def __init__(self, b):
            self._b = b

        def binary(self):
            return self._b

    class _Rep:
        def __init__(self, b):
            self._actor_id = _Id(b)

    h = DeploymentHandle("d")
    h._replicas = [_Rep(b"a"), _Rep(b"b")]
    h._depths = [1, 1]
    h._depth_ts = time.monotonic() + 3600     # pin the depth view
    h._delta = {0: 0, 1: 0}
    h._has_loads = True                       # replicas have reported
    h._route_state["kv_next"] = time.monotonic() + 3600  # pin the view
    h._route_state["kv_loads"] = {
        b"a": {"kv_free": 0, "kv_total": 10, "ts": time.time()},
        b"b": {"kv_free": 10, "kv_total": 10, "ts": time.time()}}
    scores = h._scores()
    assert scores[0] > scores[1]              # full replica penalized
    picks = {h._pick_replica() for _ in range(20)}
    assert picks == {1}
    # stale report -> no KV penalty
    h._route_state["kv_loads"][b"a"]["ts"] = time.time() - 3600
    assert h._scores()[0] == pytest.approx(1.0)
    # exclude bars the named replica while an alternative exists
    for _ in range(10):
        assert h._pick_replica(exclude=b"b") == 0
    # round-robin mode ignores scores
    monkeypatch.setenv("RTPU_SERVE_ROUTING", "rr")
    assert {h._pick_replica() for _ in range(4)} == {0, 1}
    # method-style clones SHARE routing state by reference: a fresh
    # clone per call must advance the same rr cursor (and keep the KV
    # TTL), not restart from the parent's snapshot every time
    clone_picks = set()
    for _ in range(4):
        c = h.options(method_name="kv_state")
        assert c._route_state is h._route_state
        clone_picks.add(c._pick_replica())
    assert clone_picks == {0, 1}


# ---------------------------------------------------------------------------
# serve-stack fault injection + chaos replay (quick tier)
# ---------------------------------------------------------------------------

@pytest.fixture
def rt_serve():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_replica_death_retry_excludes_dead_pick(rt_serve, monkeypatch):
    """The r9 death-report path folded into the load-aware picker: with
    the controller's death report suppressed (unreachable-controller
    fault) the routing table still lists the corpse — the retry must
    re-consult routing state WITH the dead pick excluded, not re-roll
    the same pick."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.handle import DeploymentHandle

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x + 100

    handle = serve.run(Echo.bind(), name="retry_app")
    assert handle.remote(1).result(timeout_s=60) == 101
    handle._refresh(force=True)
    victim = handle._replicas[0]
    ray_tpu.kill(victim)

    # fault injection: the death report and forced refresh are lost
    # (wedged controller), so the table keeps naming the dead replica
    monkeypatch.setattr(DeploymentHandle, "_replica_died",
                        lambda self, replica: None)
    # and the unlucky first pick lands ON the corpse — exactly the case
    # the exclude exists for
    orig = DeploymentHandle._pick_replica

    def biased(self, exclude=None):
        if exclude is None:
            return 0
        return orig(self, exclude=exclude)

    monkeypatch.setattr(DeploymentHandle, "_pick_replica", biased)
    assert handle.remote(7).result(timeout_s=60) == 107
    from ray_tpu import serve as _s

    _s.delete("Echo")


def test_replay_replica_death_no_block_leak(rt_serve):
    """Chaos case from ISSUE 12: kill a replica mid-replay. New requests
    re-route to the survivor (the replay keeps completing), the
    controller reconciles a replacement, and NO replica leaks KV blocks
    — every live engine's free count returns to total minus what its
    prefix trie legitimately pins."""
    import threading

    import ray_tpu
    from conftest import poll_until
    from experiments.serve_replay import TraceConfig, gen_trace, replay
    from ray_tpu import serve
    from ray_tpu.serve import LLMDeployment

    app = serve.deployment(
        LLMDeployment, num_replicas=2,
        ray_actor_options={"max_concurrency": 16, "num_cpus": 0},
    ).bind("llama-debug", max_slots=4, max_len=96, block_size=8,
           prefill_chunk=8, seed=0)
    handle = serve.run(app, name="llm_chaos")
    sh = handle.options(stream=True)
    for _ in range(4):  # warm both replicas' compiles out of the replay
        list(sh.remote([1, 2, 3], 2))
    handle._refresh(force=True)
    victim = handle._replicas[0]

    killer = threading.Timer(0.8, lambda: ray_tpu.kill(victim))
    killer.start()
    cfg = TraceConfig(n_requests=24, n_tenants=2,
                      shared_prefix_tokens=16, suffix_tokens_mean=6,
                      max_new_tokens=6, burst_rps=20.0, seed=1)
    stats = replay(lambda req: sh.remote(req.prompt, req.max_new),
                   gen_trace(cfg), time_scale=1.0)
    killer.cancel()
    # the tier keeps serving through the death: errors are bounded by
    # the streams that were IN FLIGHT on the victim (half-consumed
    # streams cannot be resumed); everything else completes
    assert stats.started == 24
    assert stats.completed >= 24 - 8, vars(stats)
    assert stats.completed + stats.errors + stats.shed \
        + stats.deadline == 24

    # controller reconciles back to 2 replicas
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    poll_until(
        lambda: ray_tpu.get(ctrl.list_deployments.remote())[
            "LLMDeployment"]["num_replicas"] == 2,
        timeout=60, desc="replacement replica reconciled")

    # zero leaked blocks on every LIVE replica: drain, then the
    # free-block count (the rtpu_serve_kv_blocks_free gauge's source)
    # must equal total minus the prefix trie's legitimate pins
    handle._refresh(force=True)

    def no_leaks():
        states = [ray_tpu.get(r.handle_request.remote("kv_state", (), {}),
                              timeout=30)
                  for r in handle._replicas]
        return all(
            s["inflight"] == 0 and s["queued"] == 0
            and s["kv_free"] + s["prefix"]["nodes"] == s["kv_total"]
            for s in states) and states

    states = poll_until(no_leaks, timeout=60,
                        desc="all replicas drained with zero leaked blocks")
    # prefix reuse actually happened during the replay on the survivor
    assert any(s["prefix"]["hits"] > 0 for s in states), states
    serve.delete("LLMDeployment")
