"""Disaggregated prefill/decode serving (ISSUE 13): KV block
gather/scatter, engine export/adopt parity, the DeviceChannel/store
transfer plane (demux, single-writer discipline, block-batch framing),
transfer-aware routing + cross-pool admission, structured error_type,
the streamed bounded-memory replay harness, and the deployed two-pool
application (round-trip, leaks, chaos at the transfer seam,
multi-node load reports)."""

import dataclasses
import os
import threading
import time

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# models: block gather/scatter
# ---------------------------------------------------------------------------

def test_gather_scatter_kv_blocks_roundtrip():
    import jax.numpy as jnp

    from ray_tpu.models import gather_kv_blocks, scatter_kv_blocks

    rng = np.random.default_rng(0)
    cache = {"k": jnp.asarray(rng.normal(size=(2, 8, 4, 2, 3))
                              .astype(np.float32)),
             "v": jnp.asarray(rng.normal(size=(2, 8, 4, 2, 3))
                              .astype(np.float32))}
    got = gather_kv_blocks(cache, [5, 1, 6])
    assert np.allclose(np.asarray(got["k"]),
                       np.asarray(cache["k"])[:, [5, 1, 6]])
    # scatter into different blocks of a zero pool; out-of-range pad ids
    # are dropped (the bucketing contract)
    dst = {"k": jnp.zeros((2, 8, 4, 2, 3), jnp.float32),
           "v": jnp.zeros((2, 8, 4, 2, 3), jnp.float32)}
    pad = {"k": jnp.concatenate(
               [got["k"], jnp.ones((2, 1, 4, 2, 3), jnp.float32)], 1),
           "v": jnp.concatenate(
               [got["v"], jnp.ones((2, 1, 4, 2, 3), jnp.float32)], 1)}
    out = scatter_kv_blocks(dst, [2, 0, 7, 8], pad)   # 8 = OOB -> dropped
    assert np.allclose(np.asarray(out["k"])[:, [2, 0, 7]],
                       np.asarray(got["k"]))
    untouched = [i for i in range(8) if i not in (2, 0, 7)]
    assert np.asarray(out["k"])[:, untouched].sum() == 0


# ---------------------------------------------------------------------------
# engine: prefill-only export + adopt = token-exact disaggregation
# ---------------------------------------------------------------------------

def _f32_cfg():
    from ray_tpu import models

    return dataclasses.replace(models.get_config("llama-debug"),
                               dtype="float32", param_dtype="float32")


def _drain(eng, max_steps=500):
    for _ in range(max_steps):
        if not eng.step():
            return
    raise AssertionError("engine did not drain")


def test_engine_export_adopt_parity_and_no_leaks():
    """prefill_only on engine P + adopt on engine D == sequential
    generate, token-exact, with every block returned on both pools."""
    import jax

    from ray_tpu import models
    from ray_tpu.models import transformer as T
    from ray_tpu.serve.llm import KVExport, LLMEngine

    cfg = _f32_cfg()
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 256, n).tolist() for n in (13, 5, 21)]
    refs = []
    for p in prompts:
        g = T.generate(params, jax.numpy.asarray(
            np.asarray(p, np.int32)[None]), cfg, max_new_tokens=6)
        refs.append([int(x) for x in np.asarray(g[0, len(p):])])

    P = LLMEngine(cfg, params, max_slots=4, max_len=64, block_size=4,
                  prefill_chunk=4, role="prefill")
    D = LLMEngine(cfg, params, max_slots=4, max_len=64, block_size=4,
                  prefill_chunk=4, role="decode")
    exports = []
    for p in prompts:
        sink = []
        P.submit(p, 6, sink.append, prefill_only=True)
        _drain(P)
        (e,) = [x for x in sink if isinstance(x, KVExport)]
        assert sink[-1] is None
        exports.append(e)
    outs = []
    for p, e in zip(prompts, exports):
        sink = []
        outs.append(sink)
        D.adopt(p, e.kv, e.token, 6, sink.append)
    _drain(D)
    got = [[t for t in o if t is not None] for o in outs]
    assert got == refs
    # the export's first token IS the stream's first token
    assert all(o[0] == e.token for o, e in zip(got, exports))
    for eng in (P, D):
        assert eng.pool.free_count + len(eng.prefix) == eng.pool.num_blocks
        assert eng.kv_state()["role"] in ("prefill", "decode")
    assert P.stats["exported"] == 3 and D.stats["adopted"] == 3


def test_adopt_rejects_bad_geometry():
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_f32_cfg(), max_slots=2, max_len=64, block_size=4,
                    role="decode")
    kv = {"k": np.zeros((2, 2, 4, 2, 16), np.float32),
          "v": np.zeros((2, 2, 4, 2, 16), np.float32)}
    with pytest.raises(ValueError, match="blocks"):
        eng.adopt(list(range(13)), kv, 7, 4, lambda t: None)  # needs 4
    with pytest.raises(ValueError, match="block_size"):
        bad = {"k": np.zeros((2, 4, 8, 2, 16), np.float32),
               "v": np.zeros((2, 4, 8, 2, 16), np.float32)}
        eng.adopt(list(range(13)), bad, 7, 4, lambda t: None)
    # nothing was claimed by the rejected adopts
    assert eng.pool.free_count == eng.pool.num_blocks


# ---------------------------------------------------------------------------
# transfer plane: pack/unpack, ring demux, single-writer under threads
# ---------------------------------------------------------------------------

def _fake_export(seed, n_blocks=3, bs=4):
    from ray_tpu.serve.llm import KVExport

    rng = np.random.default_rng(seed)
    kv = {"k": rng.normal(size=(2, n_blocks, bs, 2, 8))
          .astype(np.float32),
          "v": rng.normal(size=(2, n_blocks, bs, 2, 8))
          .astype(np.float32)}
    return KVExport(token=seed, prompt_len=n_blocks * bs - 1,
                    block_size=bs, kv=kv)


def test_pack_unpack_blocks_are_contiguous_records():
    from ray_tpu.serve.kv_transfer import pack_export, unpack_payload

    e = _fake_export(7)
    meta, arr = pack_export(e)
    assert arr.flags["C_CONTIGUOUS"] and arr.shape[0] == 3
    # one block == one contiguous record (what chunk alignment frames)
    assert meta["n_blocks"] == 3 and meta["token"] == 7
    kv = unpack_payload(meta, arr)
    assert np.array_equal(kv["k"], e.kv["k"])
    assert np.array_equal(kv["v"], e.kv["v"])


def test_kv_channel_out_of_order_demux_and_concurrent_writers(tmp_path):
    """12 payloads shipped from 8 threads (the deployed replica's
    concurrency shape) and fetched out of order by 4 threads: every
    request gets ITS payload — the per-channel writer lock keeps the
    single-writer ring sound, the request-id demux parks strays."""
    from ray_tpu.serve.kv_transfer import KVReceiver, KVSender

    e = _fake_export(1)
    snd = KVSender("srcT", max_payload_bytes=e.nbytes)
    rcv = KVReceiver()
    descs = {}
    dlock = threading.Lock()

    def ship(i):
        d = snd.ship(_fake_export(i), req_id=f"r{i}", dst_id="dstT",
                     same_host=True, timeout=30.0)
        with dlock:
            descs[i] = d

    shippers = [threading.Thread(target=ship, args=(i,))
                for i in range(12)]
    for t in shippers:
        t.start()
    got = {}
    glock = threading.Lock()
    errs = []

    def fetch(i):
        # wait for this request's descriptor, then fetch
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with dlock:
                d = descs.get(i)
            if d is not None:
                break
            time.sleep(0.01)
        try:
            meta, kv = rcv.fetch(d, timeout=30.0)
            with glock:
                got[i] = (meta, kv)
        except BaseException as ex:  # noqa: BLE001 - surfaced below
            errs.append((i, ex))

    fetchers = [threading.Thread(target=fetch, args=(i,))
                for i in reversed(range(12))]
    for t in fetchers:
        t.start()
    for t in shippers + fetchers:
        t.join(timeout=60)
    assert not errs, errs
    assert len(got) == 12
    for i in range(12):
        meta, kv = got[i]
        ref = _fake_export(i)
        assert meta["token"] == i
        assert np.array_equal(kv["k"], ref.kv["k"])
    snd.close()
    rcv.close()


def test_kv_channel_overflow_falls_back_to_store():
    """A wedged decode side (nobody reads the ring) must not stall
    prefill: the ship times out on the full ring and degrades to the
    store path."""
    import ray_tpu
    from ray_tpu.serve.kv_transfer import KVReceiver, KVSender

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        e = _fake_export(2)
        snd = KVSender("srcO", max_payload_bytes=e.nbytes, slots=2)
        descs = [snd.ship(_fake_export(i), req_id=f"o{i}", dst_id="dstO",
                          same_host=True, timeout=0.2) for i in range(4)]
        kinds = [d["kind"] for d in descs]
        assert kinds[0] == "channel" and "ref" in kinds, kinds
        # and the store-path descriptor still fetches correctly
        rcv = KVReceiver()
        i = kinds.index("ref")
        meta, kv = rcv.fetch(descs[i], timeout=30)
        assert np.array_equal(kv["k"], _fake_export(i).kv["k"])
        snd.close()
        rcv.close()
    finally:
        ray_tpu.shutdown()


def test_pull_chunks_align_frames_whole_records():
    """Block-batch framing on the chunked-pull path: records start
    AFTER the serialized header (align_base), align rounds the chunk
    size down to whole records and anchors every boundary on a record
    edge, the tail still completes, and the assembled bytes are
    exact."""
    from ray_tpu.cluster.adapter import pull_chunks

    record = 48_000                       # "block" stride
    header = 1234                         # serialized pickle/pad prefix
    src = os.urandom(header + record * 21)
    offsets = []

    def call(method, oid_b, off, ln, timeout=None):
        offsets.append((off, ln))
        return src[off:off + ln]

    class W:
        def __init__(self, n):
            self.buf = bytearray(n)

        def write(self, off, data):
            self.buf[off:off + len(data)] = data

    w = W(len(src))
    assert pull_chunks(call, b"o" * 16, len(src), w, chunk=200_000,
                       parallel=3, align=record, align_base=header)
    assert bytes(w.buf) == src
    for off, ln in offsets:
        if off:                           # chunks start on RECORD edges
            assert (off - header) % record == 0
            if off + ln < len(src):
                assert ln % record == 0   # every interior chunk whole
        else:                             # first chunk: header + records
            assert (ln - header) % record == 0 or off + ln == len(src)

    # no hint (align=1): plain fixed-size chunking still exact
    offsets.clear()
    w2 = W(len(src))
    assert pull_chunks(call, b"o" * 16, len(src), w2, chunk=200_000,
                       parallel=2)
    assert bytes(w2.buf) == src


# ---------------------------------------------------------------------------
# structured errors (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def test_task_error_carries_error_type_across_pickling():
    import cloudpickle

    from ray_tpu.core.exceptions import TaskError
    from ray_tpu.serve.admission import (DeadlineExceededError,
                                         RequestShedError)

    e = TaskError(RequestShedError("request shed (ttft): x",
                                   reason="ttft"), "tb", "t")
    e2 = cloudpickle.loads(cloudpickle.dumps(e))
    assert e2.error_type == "shed"
    assert isinstance(e2.cause, RequestShedError)
    assert e2.cause.reason == "ttft"
    assert TaskError(DeadlineExceededError("late")).error_type \
        == "deadline"

    class Unpicklable(Exception):
        def __reduce__(self):
            raise RuntimeError("nope")

    e3 = cloudpickle.loads(cloudpickle.dumps(TaskError(Unpicklable("b"))))
    assert e3.error_type == "Unpicklable" and "b" in str(e3.cause)


def test_replay_classifier_uses_error_type_not_strings():
    from experiments.serve_replay import classify_error
    from ray_tpu.core.exceptions import TaskError
    from ray_tpu.serve.admission import (DeadlineExceededError,
                                         RequestShedError)

    assert classify_error(RequestShedError("x")) == "shed"
    assert classify_error(DeadlineExceededError("x")) == "deadline"
    assert classify_error(
        TaskError(RequestShedError("x"), "", "")) == "shed"
    assert classify_error(
        TaskError(DeadlineExceededError("x"), "", "")) == "deadline"
    # a wrapper whose MESSAGE merely mentions the words is NOT a shed
    assert classify_error(
        RuntimeError("request shed (ttft) DeadlineExceededError")) \
        == "error"
    assert classify_error(TaskError(ValueError("boom"), "", "")) \
        == "error"


# ---------------------------------------------------------------------------
# replay harness: streamed trace, bounded stats
# ---------------------------------------------------------------------------

def test_trace_streams_and_matches_materialized():
    from experiments.serve_replay import TraceConfig, gen_trace, iter_trace

    cfg = TraceConfig(n_requests=64, seed=5, long_every=8,
                      long_prompt_tokens=99)
    streamed = list(iter_trace(cfg))
    assert gen_trace(cfg) == streamed          # same determinism
    longs = [r for i, r in enumerate(streamed) if (i + 1) % 8 == 0]
    assert all(len(r.prompt) == cfg.shared_prefix_tokens + 99
               for r in longs)
    shorts = [r for i, r in enumerate(streamed) if (i + 1) % 8]
    assert max(len(r.prompt) for r in shorts) \
        < cfg.shared_prefix_tokens + 99


def test_replay_bounded_reservoirs_and_classification():
    from experiments.serve_replay import (Request, TraceConfig,
                                          _Reservoir, iter_trace, replay)
    from ray_tpu.serve.admission import RequestShedError

    r = _Reservoir(cap=100, seed=1)
    for i in range(10_000):
        r.add(float(i))
    assert len(r.xs) == 100 and r.n == 10_000
    assert 0 < r.percentile(0.5) < 10_000

    def stream(req: Request):
        if req.tenant == 0:
            raise RequestShedError("no")
        yield 1
        yield 2

    cfg = TraceConfig(n_requests=40, n_tenants=2, seed=3,
                      burst_rps=10_000.0)
    stats = replay(stream, iter_trace(cfg), time_scale=0.0,
                   max_clients=8)
    assert stats.started == 40
    assert stats.completed + stats.shed == 40 and stats.shed > 0
    assert stats.errors == 0
    assert stats.tokens == 2 * stats.completed


# ---------------------------------------------------------------------------
# router: budget admission + transfer-aware decode picking (no runtime)
# ---------------------------------------------------------------------------

class _Id:
    def __init__(self, b):
        self._b = b

    def binary(self):
        return self._b


class _Rep:
    def __init__(self, b):
        self._actor_id = _Id(b)


def _handle_with(replicas):
    from ray_tpu.serve.handle import DeploymentHandle

    h = DeploymentHandle("d")
    h._replicas = replicas
    h._version = 0
    return h


def test_disagg_budget_check_sheds_on_decode_kv():
    from ray_tpu.serve.admission import RequestShedError
    from ray_tpu.serve.disagg import DisaggHandle

    dh = DisaggHandle(_handle_with([_Rep(b"p")]),
                      _handle_with([_Rep(b"d")]))
    # seed the decode handle's own TTL'd view (the shared routing-state
    # seam _pool_loads now delegates to)
    dh.decode._route_state.update(
        kv_loads={b"d": {"kv_free": 2, "kv_total": 32,
                         "block_size": 16, "inflight": 0,
                         "ts": time.time()}},
        kv_next=time.monotonic() + 3600)
    with pytest.raises(RequestShedError) as ei:
        dh._budget_check(40, 8)           # needs 48 tokens > 2*16
    assert ei.value.reason == "decode_kv"
    assert ei.value.error_type == "shed"
    dh._budget_check(24, 8)               # 32 tokens fits exactly


def test_disagg_decode_pick_prefers_same_node_and_capacity():
    from ray_tpu.serve.disagg import DisaggHandle

    reps = [_Rep(b"a"), _Rep(b"b")]
    dh = DisaggHandle(_handle_with([_Rep(b"p")]), _handle_with(reps))
    now = time.time()
    dh.decode._route_state.update(
        kv_loads={
            b"a": {"kv_free": 0, "kv_total": 32, "inflight": 4,
                   "node": "n1", "ts": now},
            b"b": {"kv_free": 32, "kv_total": 32, "inflight": 0,
                   "node": "n2", "ts": now}},
        kv_next=time.monotonic() + 3600)
    picks = {dh._pick_decode("n2")._actor_id.binary()
             for _ in range(20)}
    assert picks == {b"b"}                # free + same-node wins
    # exclusion bars the named replica
    assert dh._pick_decode("n2", exclude=b"b")._actor_id.binary() == b"a"


# ---------------------------------------------------------------------------
# deployed two-pool application
# ---------------------------------------------------------------------------

@pytest.fixture
def rt_serve():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_disagg_deployed_roundtrip_channel_path_no_leaks(rt_serve):
    """One prefill + one decode replica on one host: requests flow
    prefill -> DeviceChannel ring -> decode, streams are token-stable
    across a repeat (decode-side trie adoption), per-pool stats count
    exports/adoptions, and both pools drain to zero leaked blocks."""
    import ray_tpu
    from ray_tpu import serve

    h = serve.deploy_disagg(
        "llama-debug", name="dsrv", prefill_replicas=1,
        decode_replicas=1, max_slots=4, max_len=96, block_size=8,
        prefill_chunk=8, seed=0)
    try:
        prompt = np.random.default_rng(0).integers(0, 256, 20).tolist()
        toks = list(h.stream(prompt, 6))
        assert len(toks) == 6
        assert list(h.stream(prompt, 6)) == toks   # deterministic repeat

        states = h.kv_states()
        assert [s["role"] for s in states["prefill"]] == ["prefill"]
        assert [s["role"] for s in states["decode"]] == ["decode"]
        for pool in states.values():
            for s in pool:
                assert s["inflight"] == 0 and s["queued"] == 0
                assert s["kv_free"] + s["prefix"]["nodes"] \
                    == s["kv_total"], s

        # per-pool engine stats: the prefill pool exported, the decode
        # pool adopted, and the transfer rode the same-host channel
        # (same node id -> ship() picked the ring)
        h.prefill._refresh(force=True)
        h.decode._refresh(force=True)
        pstats = ray_tpu.get(h.prefill._replicas[0].handle_request
                             .remote("stats", (), {}), timeout=60)
        dstats = ray_tpu.get(h.decode._replicas[0].handle_request
                             .remote("stats", (), {}), timeout=60)
        assert pstats["exported"] >= 2 and dstats["adopted"] >= 2
        # rings are session-named: the runtime shutdown sweep
        # (rtpu-chan-<session>-*) reclaims them even though replicas
        # are killed, never asked to clean up (r16 drive regression)
        import glob as _glob

        sess = ray_tpu.get_runtime_context().get_session_id()
        assert any(f"rtpu-chan-{sess}-kvx-" in p
                   for p in _glob.glob("/dev/shm/rtpu-chan-*kvx*"))
        # per-pool load reports reach the controller with roles + nodes
        from conftest import poll_until

        def role_reports():
            loads = {}
            for hd in (h.prefill, h.decode):
                loads.update(hd._pool_loads_fresh()
                             if hasattr(hd, "_pool_loads_fresh")
                             else {})
            p = h._pool_loads(h.prefill)
            d = h._pool_loads(h.decode)
            return (p and d
                    and all(v.get("role") == "prefill"
                            for v in p.values())
                    and all(v.get("role") == "decode"
                            for v in d.values()))

        poll_until(role_reports, timeout=30,
                   desc="per-pool load reports at controller")
    finally:
        h.shutdown()


def test_disagg_chaos_prefill_killed_mid_transfer_no_leaks(rt_serve,
                                                          tmp_path):
    """Failpoint at the KV-transfer seam (serve.kv_transfer): SIGKILL a
    prefill replica exactly when it would ship blocks. The router
    re-routes to the surviving prefill replica (the caller sees a
    complete stream), the decode pool adopts nothing partial, the
    controller reconciles a replacement, and ZERO KV blocks or parked
    ring payloads leak on any live replica."""
    import ray_tpu
    from conftest import poll_until
    from ray_tpu import serve
    from ray_tpu.util import failpoints

    h = serve.deploy_disagg(
        "llama-debug", name="dchaos", prefill_replicas=2,
        decode_replicas=1, max_slots=4, max_len=96, block_size=8,
        prefill_chunk=8, seed=0)
    try:
        prompt = np.random.default_rng(1).integers(0, 256, 24).tolist()
        ref = list(h.stream(prompt, 5))          # warm both paths
        failpoints.arm("serve.kv_transfer=kill"
                       f"@once={tmp_path / 'kvkill.tok'}")
        got = [list(h.stream(prompt, 5)) for _ in range(6)]
        assert all(g == ref for g in got), (ref, got)

        # the dead prefill replica was replaced
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        poll_until(
            lambda: ray_tpu.get(ctrl.list_deployments.remote())[
                "dchaos-prefill"]["num_replicas"] == 2,
            timeout=60, desc="prefill replacement reconciled")

        # zero leaks on every LIVE replica of BOTH pools
        def no_leaks():
            states = h.kv_states()
            return all(
                s["inflight"] == 0 and s["queued"] == 0
                and s["kv_free"] + s["prefix"]["nodes"] == s["kv_total"]
                for pool in states.values() for s in pool) and states

        poll_until(no_leaks, timeout=60,
                   desc="all pools drained, zero leaked KV blocks")
    finally:
        failpoints.disarm()
        h.shutdown()


# ---------------------------------------------------------------------------
# multi-node: proxy-driven load-aware routing + per-pool reports (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multinode_proxy_routing_and_pool_reports():
    """Two extra node daemons; a deployed app spread across >= 2 nodes,
    driven through the HTTP proxy with load-aware routing (both
    replicas serve), and per-pool load reports from BOTH nodes reach
    the head controller with distinct node ids."""
    import http.client
    import json as _json

    import ray_tpu
    from conftest import poll_until
    from ray_tpu import serve
    from ray_tpu.cluster import Cluster

    c = Cluster()
    try:
        c.add_node(num_cpus=2)
        c.add_node(num_cpus=2)
        ray_tpu.init(address=c.address, cluster_authkey=c.authkey,
                     num_cpus=2)

        class Where:
            def __init__(self):
                self._n = 0

            def __call__(self, x=None):
                self._n += 1
                import ray_tpu as rt

                return rt.get_runtime_context().get_node_id()

            def load_state(self):
                import ray_tpu as rt

                return {"inflight": self._n, "kv_free": 8,
                        "kv_total": 8, "role": "proxy-pool",
                        "node": rt.get_runtime_context().get_node_id()}

        app = serve.deployment(
            Where, num_replicas=2,
            ray_actor_options={"scheduling_strategy": "SPREAD",
                               "num_cpus": 1}).bind()
        handle = serve.run(app, name="where_app",
                           route_prefix="where_app")
        proxy = serve.start_http_proxy(port=0)
        try:
            served_nodes = set()
            for _ in range(12):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", proxy.port, timeout=60)
                body = _json.dumps(1)
                conn.request("POST", "/where_app", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200, resp.status
                served_nodes.add(
                    _json.loads(resp.read())["result"])
                conn.close()
            # load-aware routing spread the burst over both replicas —
            # which the SPREAD strategy put on different nodes
            assert len(served_nodes) >= 2, served_nodes

            # per-pool load reports reach the HEAD controller, tagged
            # with the replicas' (distinct) node ids
            ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")

            def reports():
                loads = ray_tpu.get(
                    ctrl.get_replica_loads.remote("Where"), timeout=10)
                nodes = {v.get("node") for v in loads.values()}
                return (len(loads) >= 2 and len(nodes) >= 2
                        and all(v.get("role") == "proxy-pool"
                                for v in loads.values())) and loads

            poll_until(reports, timeout=60,
                       desc="per-pool load reports from both nodes")
        finally:
            proxy.stop()
            serve.shutdown()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


# ---------------------------------------------------------------------------
# elastic drain (r20): live-session migration, no re-prefill
# ---------------------------------------------------------------------------

def test_disagg_drain_migrates_live_session_token_exact(rt_serve):
    """Preemption drain: with a live decode stream in flight,
    drain_decode_replica ships the session's KV blocks to the surviving
    decode replica and the handle splices the continuation — the caller
    sees the EXACT token sequence of an undisturbed run, the prefill
    pool never re-prefills, and the drain/migration land on the event
    plane (acceptance criterion (c) of the elasticity issue)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import state

    h = serve.deploy_disagg(
        "llama-debug", name="ddrain", prefill_replicas=1,
        decode_replicas=2, max_slots=4, max_len=2048, block_size=8,
        prefill_chunk=8, seed=0)
    try:
        from conftest import poll_until

        prompt = np.random.default_rng(7).integers(0, 256, 20).tolist()
        # reference: undisturbed 40-token stream, consumed to completion
        # so its session retires (greedy sampling makes the drained
        # run's first 40 tokens comparable)
        ref = list(h.stream(prompt, 40))
        assert len(ref) == 40

        # drained run: a deliberately huge budget keeps the session
        # in flight for the whole drain dance
        g = h.stream(prompt, 1200)
        got = [next(g) for _ in range(5)]        # stream provably live

        # the live session sits on exactly one decode replica (the
        # reference session has retired): that replica is the victim
        h.decode._refresh(force=True)
        reps = list(h.decode._replicas)
        assert len(reps) == 2
        by_hex = {r._actor_id.binary().hex(): r for r in reps}

        def one_victim():
            stats = {hx: ray_tpu.get(
                r.handle_request.remote("stats", (), {}), timeout=60)
                for hx, r in by_hex.items()}
            v = [hx for hx, s in stats.items() if s["inflight"] >= 1]
            return v if len(v) == 1 else None

        victim = poll_until(one_victim, timeout=30,
                            desc="exactly one live decode session")[0]

        report = h.drain_decode_replica(victim, timeout_s=60.0)
        assert report["sessions"] == 1, report
        assert report["migrated"] == 1 and report["failed"] == 0, report

        # token-exact continuation across the splice — the destination
        # adopted the shipped KV against the fed-token transcript; any
        # re-prefill drift or handoff-token duplication breaks this
        while len(got) < 40:
            got.append(next(g))
        assert got == ref, (got, ref)
        g.close()

        # the victim exported the live session; the survivor adopted it
        vstats = ray_tpu.get(
            by_hex[victim].handle_request.remote("stats", (), {}),
            timeout=60)
        assert vstats["migrated_out"] == 1
        # no re-prefill: the prefill pool served exactly the two
        # original streams
        h.prefill._refresh(force=True)
        pstats = ray_tpu.get(
            h.prefill._replicas[0].handle_request.remote(
                "stats", (), {}), timeout=60)
        assert pstats["exported"] == 2, pstats

        # event-plane records: one drain, one migrated session bound for
        # a SURVIVING replica with real KV cargo (replica rings ship to
        # the head asynchronously: poll)
        def drain_events():
            evs = state.list_events(limit=100000)
            drains = [e for e in evs if e.get("name") == "serve_drain"]
            migs = [e for e in evs
                    if e.get("name") == "serve_session_migrated"]
            return (drains, migs) if drains and migs else None

        drains, migs = poll_until(drain_events, timeout=30,
                                  desc="drain events reach the head")
        assert int(drains[-1]["sessions"]) >= 1
        assert len(migs) == 1
        assert migs[0]["dst"] != victim
        assert int(migs[0]["kv_tokens"]) >= len(prompt)
    finally:
        h.shutdown()


def test_drain_decode_replica_argument_errors(rt_serve):
    """Victim addressing: unknown actor id is a loud error; an unknown
    node id is a no-op report (the shape a stale preemption notice
    arrives in); draining needs a surviving peer."""
    import pytest as _pytest

    from ray_tpu import serve

    h = serve.deploy_disagg(
        "llama-debug", name="ddrain2", prefill_replicas=1,
        decode_replicas=1, max_slots=2, max_len=64, block_size=8,
        prefill_chunk=8, seed=0)
    try:
        with _pytest.raises(ValueError):
            h.drain_decode_replica("feedfacefeedface")
        assert h.drain_decode_replica(node_id="no-such-node") == {
            "sessions": 0, "migrated": 0, "failed": 0, "finished": 0}
        # sole decode replica: no surviving peer to migrate to
        h.decode._refresh(force=True)
        only = h.decode._replicas[0]._actor_id.binary().hex()
        with _pytest.raises(RuntimeError):
            h.drain_decode_replica(only)
    finally:
        h.shutdown()
