"""Train library: session/report, JaxTrainer fit, restart, checkpoints,
pjit train-step helper."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import ray_tpu
from ray_tpu import models
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.train import (
    Checkpoint, FailureConfig, JaxTrainer, RunConfig, ScalingConfig,
    TrainLoopHelper, load_pytree, save_pytree,
)
from ray_tpu.train.train_state import create_train_state, state_shardings


@pytest.fixture
def rt_train(tmp_path):
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield str(tmp_path)
    ray_tpu.shutdown()


def test_jax_trainer_reports_and_checkpoints(rt_train):
    storage = rt_train

    def loop(config):
        import ray_tpu.train as train

        for step in range(3):
            ckpt = None
            if step == 2:
                import tempfile, pickle

                d = tempfile.mkdtemp()
                with open(os.path.join(d, "model.pkl"), "wb") as f:
                    pickle.dump({"w": step * config["lr"]}, f)
                ckpt = Checkpoint(d)
            train.report({"step": step, "loss": 1.0 / (step + 1)},
                         checkpoint=ckpt)

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.5},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t1", storage_path=storage),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    assert result.metrics["loss"] == pytest.approx(1 / 3)
    assert result.checkpoint is not None
    # rank dirs inside the checkpoint
    ranks = sorted(os.listdir(result.checkpoint.path))
    assert "rank_0" in ranks and "rank_1" in ranks


def test_jax_trainer_worker_error_raises(rt_train):
    def loop(config):
        raise RuntimeError("boom")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=rt_train),
    )
    from ray_tpu.train import TrainingFailedError

    with pytest.raises(TrainingFailedError):
        trainer.fit()


def test_jax_trainer_restart_resumes_from_checkpoint(rt_train):
    marker = os.path.join(rt_train, "fail_once")

    def loop(config):
        import ray_tpu.train as train

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            import pickle

            rank_dir = os.path.join(ckpt.path, "rank_0")
            with open(os.path.join(rank_dir, "state.pkl"), "rb") as f:
                start = pickle.load(f)["step"] + 1
        for step in range(start, 4):
            import pickle, tempfile

            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"step": step}, f)
            train.report({"step": step, "resumed_from": start},
                         checkpoint=Checkpoint(d))
            if step == 1 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("injected failure after step 1")

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=rt_train,
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3
    assert result.metrics["resumed_from"] == 2  # resumed after step-1 ckpt


def test_jax_trainer_dataset_ingest(rt_train):
    import ray_tpu.data as rdata

    ds = rdata.from_items([{"x": float(i)} for i in range(40)],
                          parallelism=4)

    def loop(config):
        import ray_tpu.train as train

        it = train.get_dataset_shard("train")
        total = 0.0
        count = 0
        for batch in it.iter_batches(batch_size=5):
            total += float(batch["x"].sum())
            count += len(batch["x"])
        train.report({"total": total, "count": count})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=rt_train),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank 0 saw a proper split; both ranks together cover everything —
    # check via the count being half the rows (round-robin 4 blocks / 2)
    assert result.metrics["count"] == 20


def test_save_load_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones((4,), np.int32)}}
    save_pytree(tree, str(tmp_path))
    back = load_pytree(str(tmp_path))
    np.testing.assert_array_equal(back["a"], tree["a"])
    np.testing.assert_array_equal(back["b"]["c"], tree["b"]["c"])


def test_train_loop_helper_llama_loss_decreases():
    c = models.llama_debug()
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, c.vocab_size)
    batch = {"tokens": np.asarray(toks)}

    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.adamw(3e-3),
        mesh_config=MeshConfig(dp=2, fsdp=2, tp=2),
    )
    losses = [float(helper.run_step(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert int(jax.device_get(helper.state["step"])) == 6


def test_state_shardings_cover_opt_state():
    c = models.llama_debug()
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    params = models.init_params(jax.random.PRNGKey(0), c)
    opt = optax.adam(1e-3)
    state = create_train_state(params, opt)
    sh = state_shardings(state, models.param_axes(c), mesh)
    # moments follow params; counts replicate
    flat_state = jax.tree.leaves(state)
    flat_sh = jax.tree.leaves(sh)
    assert len(flat_state) == len(flat_sh)


def test_session_checkpoint_seq_resumes_past_existing(tmp_path):
    """A fresh session in a trial dir with pre-crash checkpoints must number
    new ones AFTER them, or name-sorted "latest" resumes stale state
    (ADVICE r1)."""
    from ray_tpu.train.session import _Session, TrainContext

    (tmp_path / "checkpoint_000003").mkdir()
    (tmp_path / "checkpoint_000011").mkdir()
    ctx = TrainContext(trial_dir=str(tmp_path))
    s = _Session(lambda: None, ctx)
    assert s._checkpoint_seq == 12
    # empty dir starts at zero
    s2 = _Session(lambda: None, TrainContext(trial_dir=str(tmp_path / "new")))
    assert s2._checkpoint_seq == 0


def test_async_checkpoint_snapshot_semantics(tmp_path):
    """save_pytree_async snapshots device values at CALL time — mutating
    (donating) the arrays afterwards must not corrupt the write — and
    errors surface at wait()."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import load_pytree, save_pytree_async

    tree = {"w": jnp.arange(1000, dtype=jnp.float32)}
    h = save_pytree_async(tree, str(tmp_path / "ck"))
    # overwrite the source immediately (donation pattern)
    tree["w"] = tree["w"] * 0 - 1.0
    h.wait(timeout=60)
    assert h.done()
    back = load_pytree(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(back["w"]),
                                  np.arange(1000, dtype=np.float32))

    bad = save_pytree_async({"x": jnp.zeros(3)},
                            "/proc/definitely/not/writable")
    with pytest.raises(BaseException):
        bad.wait(timeout=60)


def test_profile_steps_captures_trace(tmp_path):
    """TrainLoopHelper.profile_steps writes an XLA trace and still returns
    step metrics."""
    import jax
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper

    c = models.llama_debug()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.sgd(1e-2),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )
    toks = np.zeros((8, 17), np.int32)
    logdir = tmp_path / "trace"
    m = helper.profile_steps({"tokens": toks}, 2, str(logdir))
    assert np.isfinite(float(jax.device_get(m["loss"])))
    produced = list(logdir.rglob("*"))
    assert produced, "no trace files written"


def test_run_step_rejects_indivisible_batch_loudly():
    import jax
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper

    c = models.llama_debug()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.sgd(1e-2),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )
    with pytest.raises(ValueError, match="does not divide"):
        helper.run_step({"tokens": np.zeros((3, 17), np.int32)})


def test_xla_compiler_options_knob(monkeypatch):
    """RTPU_XLA_COMPILER_OPTIONS parses to per-jit compiler options (the
    axon-safe alternative to TPU flags in XLA_FLAGS) and a jitted step
    still runs with a benign option set."""
    import jax
    import numpy as np
    import optax

    from ray_tpu.train.train_state import _compiler_options, make_train_step

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS", "")
    assert _compiler_options() is None

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "xla_llvm_disable_expensive_passes=true a=1,b=2")
    assert _compiler_options() == {
        "xla_llvm_disable_expensive_passes": True, "a": 1, "b": 2}

    # quoted values opt out of coercion: string-typed options whose value
    # looks numeric/bool stay strings (ADVICE r5)
    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "a='123' b=\"true\" c=123")
    assert _compiler_options() == {"a": "123", "b": "true", "c": 123}

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS", "not-kv")
    with pytest.raises(ValueError):
        _compiler_options()

    # end-to-end: a CPU-valid option compiles and runs
    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "xla_llvm_disable_expensive_passes=true")
    step = make_train_step(
        lambda p, b: ((p["w"] * b["x"]).sum() ** 2, {}),
        optax.sgd(0.1))
    state = {"step": jnp.zeros((), jnp.int32),
             "params": {"w": jnp.ones((4,))},
             "opt_state": optax.sgd(0.1).init({"w": jnp.ones((4,))})}
    out, _ = step(state, {"x": jnp.asarray(np.ones(4, np.float32))})
    assert int(out["step"]) == 1


# ---------------------------------------------------------------------------
# Elastic membership (r20): crash-atomic checkpoints, reform convergence,
# enriched death errors, end-to-end elastic resume
# ---------------------------------------------------------------------------

def test_save_pytree_crash_atomic_markers(tmp_path):
    """A completed save leaves no ``.tmp-`` litter and carries the
    ``.metadata.json`` completeness marker; a dir missing the marker or
    holding temp litter reads as torn (resume must skip it)."""
    from ray_tpu.train.trainer import _is_torn_save_dir

    d = tmp_path / "rank_0"
    save_pytree({"w": np.ones((3,), np.float32)}, str(d))
    entries = os.listdir(d)
    assert not any(e.startswith(".tmp-") for e in entries)
    assert ".metadata.json" in entries
    assert not _is_torn_save_dir(str(d))
    # user-set metadata survives the save's marker merge
    from ray_tpu.train.checkpoint import Checkpoint as Ckpt

    Ckpt(str(d)).update_metadata({"step": 7})
    save_pytree({"w": np.zeros((3,), np.float32)}, str(d))
    assert Ckpt(str(d)).get_metadata()["step"] == 7
    # kill-before-marker shape: payloads present, marker missing
    os.remove(d / ".metadata.json")
    assert _is_torn_save_dir(str(d))
    # kill-mid-rename shape: temp litter next to a marker
    save_pytree({"w": np.ones((3,), np.float32)}, str(d))
    (d / ".tmp-state_pytree.npz").write_bytes(b"partial")
    assert _is_torn_save_dir(str(d))
    # non-pytree checkpoints (user-managed files) carry no contract
    u = tmp_path / "user"
    u.mkdir()
    (u / "model.pkl").write_bytes(b"x")
    assert not _is_torn_save_dir(str(u))


def test_latest_checkpoint_world_size_stamp_and_torn_dirs(tmp_path):
    """Resume-point selection: all-ranks-ok judged against each
    checkpoint's own ``.world_size`` stamp (elastic runs change size
    between checkpoints), torn rank dirs and unreadable stamps skipped."""
    from ray_tpu.train.trainer import _latest_checkpoint

    def mk(name, ws=None, oks=(), ranks=(), torn_rank=None):
        d = tmp_path / name
        d.mkdir()
        if ws is not None:
            (d / ".world_size").write_text(str(ws))
        for r in oks:
            (d / f".rank_{r}.ok").write_text("")
        for r in ranks:
            (d / f"rank_{r}").mkdir()
        if torn_rank is not None:
            rd = d / f"rank_{torn_rank}"
            np.savez(rd / "state_pytree.npz")  # payload, no marker
        return str(d)

    assert _latest_checkpoint(str(tmp_path), 2) is None
    # complete at the stamped (shrunken) world size 1 — even though the
    # caller's requested size is 2
    c0 = mk("checkpoint_000000", ws=2, oks=(0, 1), ranks=(0, 1))
    c1 = mk("checkpoint_000001", ws=1, oks=(0,), ranks=(0,))
    assert _latest_checkpoint(str(tmp_path), 2) == c1
    # missing a rank marker for its stamp: skipped, falls back to c1
    mk("checkpoint_000002", ws=2, oks=(0,), ranks=(0, 1))
    assert _latest_checkpoint(str(tmp_path), 2) == c1
    # newest is complete -> wins
    c3 = mk("checkpoint_000003", ws=2, oks=(0, 1), ranks=(0, 1))
    assert _latest_checkpoint(str(tmp_path), 2) == c3
    # a torn rank dir (killed mid save_pytree) disqualifies the dir
    mk("checkpoint_000004", ws=1, oks=(0,), ranks=(0,), torn_rank=0)
    assert _latest_checkpoint(str(tmp_path), 2) == c3
    # unreadable stamp: do not trust the dir
    c5 = mk("checkpoint_000005", oks=(0, 1), ranks=(0, 1))
    (tmp_path / "checkpoint_000005" / ".world_size").write_text("junk")
    assert _latest_checkpoint(str(tmp_path), 2) == c3
    # pre-elastic dirs (no stamp) judged against the caller's size
    os.remove(tmp_path / "checkpoint_000005" / ".world_size")
    assert _latest_checkpoint(str(tmp_path), 2) == c5
    assert c0  # silence unused warning


def _stub_executor(monkeypatch, probes, fail_first_starts=0):
    """BackendExecutor with placement/spawn stubbed: ``probes`` feeds
    successive _placeable_world_size() answers; the first
    ``fail_first_starts`` start() calls die (double preemption: a node
    lost while the NEW group places)."""
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import BackendExecutor

    ex = BackendExecutor(BackendConfig(),
                         ScalingConfig(num_workers=4, min_workers=1))
    ex._spec = {"train_fn": lambda: None, "loop_config": {},
                "trial_dir": "/tmp/x", "experiment_name": "x",
                "datasets": {}}
    calls = {"starts": [], "launches": [], "shutdowns": 0}
    it = iter(probes)
    monkeypatch.setattr(ex, "_placeable_world_size", lambda: next(it))
    monkeypatch.setattr(ex, "shutdown",
                        lambda: calls.__setitem__(
                            "shutdowns", calls["shutdowns"] + 1))

    def fake_start(num_workers=None):
        calls["starts"].append(num_workers)
        if len(calls["starts"]) <= fail_first_starts:
            raise ConnectionError("node lost during placement")
        ex._world_size = num_workers

    monkeypatch.setattr(ex, "start", fake_start)
    monkeypatch.setattr(ex, "_launch_sessions",
                        lambda ckpt: calls["launches"].append(ckpt))
    return ex, calls


def test_reform_double_preemption_converges(monkeypatch):
    """A second preemption DURING re-form fails that attempt; the next
    attempt re-probes (shrunken) capacity and lands — no livelock, and
    the world epoch reflects every fencing attempt."""
    ex, calls = _stub_executor(monkeypatch, probes=[3, 2],
                               fail_first_starts=1)
    assert ex.reform("/ckpt/5", reason="shrink") == 2
    assert calls["starts"] == [3, 2]          # re-probe, not retry-at-3
    assert calls["launches"] == ["/ckpt/5"]   # sessions resume from ckpt
    assert ex.world_epoch == 2                # one bump per fence
    assert ex.world_size == 2


def test_reform_floor_and_attempt_bound(monkeypatch):
    """Capacity below min_workers raises ElasticWorldSizeError (the
    group-restart fallback owns it); persistent churn exhausts the
    attempt bound instead of livelocking."""
    from ray_tpu.train.backend_executor import (
        ElasticWorldSizeError, TrainingWorkerError)

    ex, _ = _stub_executor(monkeypatch, probes=[0])
    with pytest.raises(ElasticWorldSizeError):
        ex.reform(None)
    ex2, calls2 = _stub_executor(monkeypatch, probes=[3, 3, 3],
                                 fail_first_starts=3)
    with pytest.raises(TrainingWorkerError) as ei:
        ex2.reform(None, attempts=3)
    assert not isinstance(ei.value, ElasticWorldSizeError)
    assert len(calls2["starts"]) == 3
    # reform before start_training is a caller bug, not a retry case
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import BackendExecutor

    with pytest.raises(TrainingWorkerError):
        BackendExecutor(BackendConfig(),
                        ScalingConfig(num_workers=2)).reform(None)


def test_maybe_expand_only_when_capacity_returns(monkeypatch):
    ex, calls = _stub_executor(monkeypatch, probes=[2, 4])
    ex._world_size = 2
    assert ex.maybe_expand("/ckpt/1") is None      # probe says 2: no-op
    assert calls["starts"] == []
    assert ex.maybe_expand("/ckpt/2") == 4         # capacity returned
    assert calls["starts"] == [4]
    assert calls["launches"] == ["/ckpt/2"]
    ex._world_size = 4
    assert ex.maybe_expand("/ckpt/3") is None      # at requested size


class _FakeWorkers:
    """worker_group stand-in: each worker's next_result.remote() hands
    back a sentinel the monkeypatched ray_tpu.get resolves."""

    class _W:
        def __init__(self, outcome):
            class _M:
                def __init__(self, outcome):
                    self._o = outcome

                def remote(self, timeout):
                    return self._o

            self.next_result = _M(outcome)

    def __init__(self, outcomes):
        self.workers = [self._W(o) for o in outcomes]


def _fake_get(monkeypatch):
    def get(ref, **kw):
        if isinstance(ref, BaseException):
            raise ref
        return ref

    monkeypatch.setattr(ray_tpu, "get", get)


def test_get_next_results_names_dead_ranks_and_node_events(monkeypatch):
    """A dead rank surfaces as WorkerDeathError carrying WHICH ranks
    died and the node events recorded since the last drain — not a bare
    'inconsistent worker states'."""
    from ray_tpu.core.exceptions import ActorDiedError
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import (
        BackendExecutor, WorkerDeathError)

    ex = BackendExecutor(BackendConfig(), ScalingConfig(num_workers=2))
    ex.worker_group = _FakeWorkers([
        ("result", {"step": 1}, None),
        ActorDiedError("actor's node died"),
    ])
    ex._node_events.append({"event": "down", "node_id": "deadbeef",
                            "cause": "heartbeat_timeout"})
    _fake_get(monkeypatch)
    with pytest.raises(WorkerDeathError) as ei:
        ex.get_next_results()
    e = ei.value
    assert sorted(e.dead_ranks) == [1]
    assert isinstance(e.dead_ranks[1], ActorDiedError)
    assert e.node_events and e.node_events[0]["event"] == "down"
    msg = str(e)
    assert "rank(s) [1]" in msg and "heartbeat_timeout" in msg
    # the drain is a drain: a second failure reports only fresh events
    assert ex.drain_node_events() == []


def test_get_next_results_lockstep_protocol_error(monkeypatch):
    """Some ranks done while others still report() is a training-loop
    bug (mismatched per-rank report counts) — raised as
    TrainingProtocolError, never retried as a death."""
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import (
        BackendExecutor, TrainingProtocolError, WorkerDeathError)

    ex = BackendExecutor(BackendConfig(), ScalingConfig(num_workers=2))
    ex.worker_group = _FakeWorkers([
        ("done", None, None),
        ("result", {"step": 3}, None),
    ])
    _fake_get(monkeypatch)
    with pytest.raises(TrainingProtocolError) as ei:
        ex.get_next_results()
    assert not isinstance(ei.value, WorkerDeathError)
    assert "rank(s) [0]" in str(ei.value)
    # a user exception propagates UNCHANGED (group-restart budget owns it)
    ex.worker_group = _FakeWorkers([ValueError("loop bug"),
                                    ("result", {}, None)])
    with pytest.raises(ValueError, match="loop bug"):
        ex.get_next_results()


def test_jax_trainer_elastic_rank_death_resumes_without_burning_budget(
        rt_train):
    """End-to-end elastic path on the local runtime: rank 0 SIGKILLs its
    own process mid-run. With min_workers set the trainer fences,
    re-forms, and resumes from the last all-ranks-ok checkpoint WITHOUT
    consuming a max_failures attempt (max_failures=0 here, so any
    group-restart would have failed the run), bumping world_epoch and
    emitting train_world_epoch."""
    marker = os.path.join(rt_train, "killed_once")

    def loop(config):
        import pickle, signal, tempfile

        import ray_tpu.train as train

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "rank_0", "state.pkl"),
                      "rb") as f:
                start = pickle.load(f)["step"] + 1
        for step in range(start, 4):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"step": step}, f)
            train.report({"step": step, "epoch": ctx.world_epoch,
                          "resumed": ctx.resumed_from or ""},
                         checkpoint=Checkpoint(d))
            if (step == 1 and ctx.world_rank == 0
                    and not os.path.exists(config["marker"])):
                open(config["marker"], "w").close()
                os.kill(os.getpid(), signal.SIGKILL)

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=2, min_workers=1),
        run_config=RunConfig(storage_path=rt_train,
                             failure_config=FailureConfig(max_failures=0)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3
    assert result.metrics["epoch"] >= 1          # post-reform session
    assert result.metrics["resumed"]             # resumed from a ckpt
    from ray_tpu.util import state

    evs = [e for e in state.list_events(limit=10000)
           if e.get("name") == "train_world_epoch"]
    assert evs, "reform must emit train_world_epoch"
    assert evs[-1].get("reason") == "shrink"
    assert int(evs[-1].get("epoch", 0)) >= 1
