"""Train library: session/report, JaxTrainer fit, restart, checkpoints,
pjit train-step helper."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import ray_tpu
from ray_tpu import models
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.train import (
    Checkpoint, FailureConfig, JaxTrainer, RunConfig, ScalingConfig,
    TrainLoopHelper, load_pytree, save_pytree,
)
from ray_tpu.train.train_state import create_train_state, state_shardings


@pytest.fixture
def rt_train(tmp_path):
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield str(tmp_path)
    ray_tpu.shutdown()


def test_jax_trainer_reports_and_checkpoints(rt_train):
    storage = rt_train

    def loop(config):
        import ray_tpu.train as train

        for step in range(3):
            ckpt = None
            if step == 2:
                import tempfile, pickle

                d = tempfile.mkdtemp()
                with open(os.path.join(d, "model.pkl"), "wb") as f:
                    pickle.dump({"w": step * config["lr"]}, f)
                ckpt = Checkpoint(d)
            train.report({"step": step, "loss": 1.0 / (step + 1)},
                         checkpoint=ckpt)

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.5},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t1", storage_path=storage),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    assert result.metrics["loss"] == pytest.approx(1 / 3)
    assert result.checkpoint is not None
    # rank dirs inside the checkpoint
    ranks = sorted(os.listdir(result.checkpoint.path))
    assert "rank_0" in ranks and "rank_1" in ranks


def test_jax_trainer_worker_error_raises(rt_train):
    def loop(config):
        raise RuntimeError("boom")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=rt_train),
    )
    from ray_tpu.train import TrainingFailedError

    with pytest.raises(TrainingFailedError):
        trainer.fit()


def test_jax_trainer_restart_resumes_from_checkpoint(rt_train):
    marker = os.path.join(rt_train, "fail_once")

    def loop(config):
        import ray_tpu.train as train

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            import pickle

            rank_dir = os.path.join(ckpt.path, "rank_0")
            with open(os.path.join(rank_dir, "state.pkl"), "rb") as f:
                start = pickle.load(f)["step"] + 1
        for step in range(start, 4):
            import pickle, tempfile

            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"step": step}, f)
            train.report({"step": step, "resumed_from": start},
                         checkpoint=Checkpoint(d))
            if step == 1 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("injected failure after step 1")

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=rt_train,
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3
    assert result.metrics["resumed_from"] == 2  # resumed after step-1 ckpt


def test_jax_trainer_dataset_ingest(rt_train):
    import ray_tpu.data as rdata

    ds = rdata.from_items([{"x": float(i)} for i in range(40)],
                          parallelism=4)

    def loop(config):
        import ray_tpu.train as train

        it = train.get_dataset_shard("train")
        total = 0.0
        count = 0
        for batch in it.iter_batches(batch_size=5):
            total += float(batch["x"].sum())
            count += len(batch["x"])
        train.report({"total": total, "count": count})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=rt_train),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank 0 saw a proper split; both ranks together cover everything —
    # check via the count being half the rows (round-robin 4 blocks / 2)
    assert result.metrics["count"] == 20


def test_save_load_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones((4,), np.int32)}}
    save_pytree(tree, str(tmp_path))
    back = load_pytree(str(tmp_path))
    np.testing.assert_array_equal(back["a"], tree["a"])
    np.testing.assert_array_equal(back["b"]["c"], tree["b"]["c"])


def test_train_loop_helper_llama_loss_decreases():
    c = models.llama_debug()
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, c.vocab_size)
    batch = {"tokens": np.asarray(toks)}

    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.adamw(3e-3),
        mesh_config=MeshConfig(dp=2, fsdp=2, tp=2),
    )
    losses = [float(helper.run_step(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert int(jax.device_get(helper.state["step"])) == 6


def test_state_shardings_cover_opt_state():
    c = models.llama_debug()
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    params = models.init_params(jax.random.PRNGKey(0), c)
    opt = optax.adam(1e-3)
    state = create_train_state(params, opt)
    sh = state_shardings(state, models.param_axes(c), mesh)
    # moments follow params; counts replicate
    flat_state = jax.tree.leaves(state)
    flat_sh = jax.tree.leaves(sh)
    assert len(flat_state) == len(flat_sh)


def test_session_checkpoint_seq_resumes_past_existing(tmp_path):
    """A fresh session in a trial dir with pre-crash checkpoints must number
    new ones AFTER them, or name-sorted "latest" resumes stale state
    (ADVICE r1)."""
    from ray_tpu.train.session import _Session, TrainContext

    (tmp_path / "checkpoint_000003").mkdir()
    (tmp_path / "checkpoint_000011").mkdir()
    ctx = TrainContext(trial_dir=str(tmp_path))
    s = _Session(lambda: None, ctx)
    assert s._checkpoint_seq == 12
    # empty dir starts at zero
    s2 = _Session(lambda: None, TrainContext(trial_dir=str(tmp_path / "new")))
    assert s2._checkpoint_seq == 0


def test_async_checkpoint_snapshot_semantics(tmp_path):
    """save_pytree_async snapshots device values at CALL time — mutating
    (donating) the arrays afterwards must not corrupt the write — and
    errors surface at wait()."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import load_pytree, save_pytree_async

    tree = {"w": jnp.arange(1000, dtype=jnp.float32)}
    h = save_pytree_async(tree, str(tmp_path / "ck"))
    # overwrite the source immediately (donation pattern)
    tree["w"] = tree["w"] * 0 - 1.0
    h.wait(timeout=60)
    assert h.done()
    back = load_pytree(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(back["w"]),
                                  np.arange(1000, dtype=np.float32))

    bad = save_pytree_async({"x": jnp.zeros(3)},
                            "/proc/definitely/not/writable")
    with pytest.raises(BaseException):
        bad.wait(timeout=60)


def test_profile_steps_captures_trace(tmp_path):
    """TrainLoopHelper.profile_steps writes an XLA trace and still returns
    step metrics."""
    import jax
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper

    c = models.llama_debug()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.sgd(1e-2),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )
    toks = np.zeros((8, 17), np.int32)
    logdir = tmp_path / "trace"
    m = helper.profile_steps({"tokens": toks}, 2, str(logdir))
    assert np.isfinite(float(jax.device_get(m["loss"])))
    produced = list(logdir.rglob("*"))
    assert produced, "no trace files written"


def test_run_step_rejects_indivisible_batch_loudly():
    import jax
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper

    c = models.llama_debug()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), c),
        models.param_axes(c),
        lambda p, b: models.loss_and_metrics(p, b, c),
        optax.sgd(1e-2),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )
    with pytest.raises(ValueError, match="does not divide"):
        helper.run_step({"tokens": np.zeros((3, 17), np.int32)})


def test_xla_compiler_options_knob(monkeypatch):
    """RTPU_XLA_COMPILER_OPTIONS parses to per-jit compiler options (the
    axon-safe alternative to TPU flags in XLA_FLAGS) and a jitted step
    still runs with a benign option set."""
    import jax
    import numpy as np
    import optax

    from ray_tpu.train.train_state import _compiler_options, make_train_step

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS", "")
    assert _compiler_options() is None

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "xla_llvm_disable_expensive_passes=true a=1,b=2")
    assert _compiler_options() == {
        "xla_llvm_disable_expensive_passes": True, "a": 1, "b": 2}

    # quoted values opt out of coercion: string-typed options whose value
    # looks numeric/bool stay strings (ADVICE r5)
    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "a='123' b=\"true\" c=123")
    assert _compiler_options() == {"a": "123", "b": "true", "c": 123}

    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS", "not-kv")
    with pytest.raises(ValueError):
        _compiler_options()

    # end-to-end: a CPU-valid option compiles and runs
    monkeypatch.setenv("RTPU_XLA_COMPILER_OPTIONS",
                       "xla_llvm_disable_expensive_passes=true")
    step = make_train_step(
        lambda p, b: ((p["w"] * b["x"]).sum() ** 2, {}),
        optax.sgd(0.1))
    state = {"step": jnp.zeros((), jnp.int32),
             "params": {"w": jnp.ones((4,))},
             "opt_state": optax.sgd(0.1).init({"w": jnp.ones((4,))})}
    out, _ = step(state, {"x": jnp.asarray(np.ones(4, np.float32))})
    assert int(out["step"]) == 1
