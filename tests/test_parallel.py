"""Mesh, sharding rules, and in-jit collective ops on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import (
    MeshConfig, make_mesh, mesh_shape_for, DEFAULT_RULES, logical_to_spec,
    shard_params, constrain,
)
from ray_tpu.parallel import ops as pops


def test_mesh_resolve_fills_unknown_axis():
    cfg = MeshConfig(dp=2, fsdp=-1, tp=2)
    sizes = cfg.resolve(8)
    assert sizes["fsdp"] == 2 and sizes["dp"] == 2 and sizes["tp"] == 2


def test_mesh_resolve_rejects_bad_product():
    with pytest.raises(ValueError):
        MeshConfig(dp=3, fsdp=1).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, fsdp=-1).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 2
    assert mesh.shape["tp"] == 2 and mesh.shape["sp"] == 1
    assert mesh.devices.size == 8


def test_logical_to_spec():
    assert logical_to_spec(["batch", "seq", "embed"]) == P(
        ("dcn", "dp", "fsdp"), "sp", "fsdp")
    assert logical_to_spec(["embed", "heads", None]) == P("fsdp", "tp", None)


def test_shard_params_places_on_mesh():
    mesh = make_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
    params = {"w": np.ones((16, 8), np.float32)}
    axes = {"w": ("embed", "heads")}
    sharded = shard_params(params, axes, mesh)
    s = sharded["w"].sharding
    assert isinstance(s, NamedSharding)
    assert s.spec == P("fsdp", "tp")


def test_collective_ops_inside_shard_map():
    from jax import shard_map

    mesh = make_mesh(MeshConfig(dp=8, fsdp=1))
    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def body(xs):
        v = xs[0, 0]
        total = pops.allreduce_sum(v, "dp")
        mx = pops.allreduce_max(v, "dp")
        return jnp.stack([total, mx]).reshape(1, 2)

    fn = jax.jit(shard_map(body, mesh=mesh,
                           in_specs=P(("dp",)), out_specs=P("dp"),
                           check_vma=False))
    out = np.asarray(fn(x))
    assert np.all(out[:, 0] == 28.0)
    assert np.all(out[:, 1] == 7.0)


def test_ring_permute_rolls_shards():
    from jax import shard_map

    mesh = make_mesh(MeshConfig(dp=8, fsdp=1))
    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def body(xs):
        return pops.ring_permute(xs, "dp", shift=1)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"), check_vma=False))
    out = np.asarray(fn(x)).ravel()
    assert list(out) == [7.0, 0, 1, 2, 3, 4, 5, 6]


def test_constrain_inside_jit():
    mesh = make_mesh(MeshConfig(dp=2, fsdp=4))

    @jax.jit
    def f(x):
        return constrain(x * 2, ["batch", None])

    with mesh:
        out = f(np.ones((8, 4), np.float32))
    assert np.all(np.asarray(out) == 2.0)


def test_quantized_psum_matches_exact_within_quant_error():
    """int8-on-the-wire psum (EQuARX role) for the dcn gradient sync:
    must equal the exact psum within blockwise max-abs/127 error, and be
    exact for values that are representable."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.ops import quantized_pmean, quantized_psum

    mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=1, dcn=4))
    rng = np.random.default_rng(5)
    x = rng.standard_normal((8, 37)).astype(np.float32)  # odd size -> pad

    spec = P(("dcn", "fsdp"))
    with jax.set_mesh(mesh):
        out = shard_map(
            lambda s: quantized_psum(s, "dcn"),
            mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False)(jnp.asarray(x))
        mean = shard_map(
            lambda s: quantized_pmean(s, "dcn"),
            mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False)(jnp.asarray(x))
    # exact references: each (dcn, fsdp) shard is one row of x; psum over
    # dcn sums rows {r, r+2, r+4, r+6} for fsdp residue r... compute via
    # reshape: device order is (dcn, fsdp) row-major over the 8 rows
    rows = x.reshape(4, 2, 37)  # [dcn, fsdp, cols]
    want = rows.sum(axis=0)     # psum over dcn per fsdp shard
    got = np.asarray(out).reshape(4, 2, 37)
    tol = 4 * np.abs(rows).max() / 127 + 1e-6  # 4 shards' quant error
    for d in range(4):
        np.testing.assert_allclose(got[d], want, atol=tol)
    got_mean = np.asarray(mean).reshape(4, 2, 37)
    np.testing.assert_allclose(got_mean[0], want / 4, atol=tol / 4)
