"""Pipeline parallelism: GPipe schedule correctness vs sequential, grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

if not hasattr(jax, "shard_map"):
    # old jax (sandbox 0.4.x): no top-level jax.shard_map — skip the whole
    # module at collection instead of erroring on the import below
    pytest.skip("this jax has no top-level jax.shard_map",
                allow_module_level=True)

from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.train.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
)

PP = 4
LAYERS = 8  # 2 per stage
DIM = 16


def _layer_fn(lp, h):
    return jnp.tanh(h @ lp["w"] + lp["b"])


def _make_params(key):
    ks = jax.random.split(key, LAYERS)
    return {
        "w": jnp.stack([jax.random.normal(k, (DIM, DIM)) * 0.3 for k in ks]),
        "b": jnp.zeros((LAYERS, DIM)),
    }


def _sequential(params, x):
    def body(h, lp):
        return _layer_fn(lp, h), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.fixture
def pp_mesh():
    devs = np.array(jax.devices()[:PP])
    return Mesh(devs, ("pp",))


def test_pipeline_matches_sequential(pp_mesh):
    params = _make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, DIM))
    micro = split_microbatches(x, 4)

    ref = _sequential(params, x)

    fn = shard_map(
        lambda p, m: pipeline_apply(_layer_fn, p, m, axis="pp"),
        mesh=pp_mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P()),
        out_specs=P(),
        check_vma=False,
    )
    out = merge_microbatches(fn(params, micro))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential(pp_mesh):
    params = _make_params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (8, DIM))
    micro = split_microbatches(x, 4)

    def seq_loss(p):
        return jnp.sum(_sequential(p, x) ** 2)

    def pp_loss(p):
        fn = shard_map(
            lambda pp_, m: pipeline_apply(_layer_fn, pp_, m, axis="pp"),
            mesh=pp_mesh,
            in_specs=({"w": P("pp"), "b": P("pp")}, P()),
            out_specs=P(),
            check_vma=False,
        )
        return jnp.sum(merge_microbatches(fn(p, micro)) ** 2)

    g_ref = jax.grad(seq_loss)(params)
    g_pp = jax.jit(jax.grad(pp_loss))(params)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_ref[k]),
                                   atol=1e-4, rtol=1e-4)


def test_microbatch_split_merge_roundtrip():
    x = jnp.arange(24).reshape(12, 2)
    micro = split_microbatches(x, 3)
    assert micro.shape == (3, 4, 2)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(micro)),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        split_microbatches(x, 5)


def test_transformer_pipelined_forward_matches_scan():
    """The pp>1 pipelined transformer (partial-auto shard_map over the pp
    axis composing with fsdp/tp GSPMD) must match the pp=1 scanned forward
    loss exactly in float32."""
    import numpy as np
    import optax

    import jax
    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.train import TrainLoopHelper

    config = models.llama_debug().replace(pp_microbatches=2, remat=False,
                                          dtype="float32")
    rng = np.random.default_rng(0)
    toks = rng.integers(0, config.vocab_size, size=(4, 65), dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    losses = {}
    for name, mc in (("scan", MeshConfig(dp=1, fsdp=-1, tp=2, sp=2, pp=1)),
                     ("pp", MeshConfig(dp=1, fsdp=-1, tp=2, sp=1, pp=2))):
        mesh = make_mesh(mc, devices=jax.devices()[:8])
        helper = TrainLoopHelper.create(
            lambda: models.init_params(jax.random.PRNGKey(0), config),
            models.param_axes(config),
            lambda p, b: models.loss_and_metrics(p, b, config),
            optax.adamw(1e-3),
            mesh=mesh,
        )
        losses[name] = float(jax.device_get(helper.run_step(batch)["loss"]))
    assert abs(losses["scan"] - losses["pp"]) < 1e-4, losses
