"""Static architecture-invariant checks (CI/tooling satellite, ISSUE 3).

These greps encode invariants from CLAUDE.md that a reviewer can't see
break in a diff hunk:

- ONE receiver thread demuxes each worker pipe — a second ``conn.recv()``
  call site races the demux and corrupts the reply routing.
- Differentiating raw attention kernels OOMs real HBM: training attention
  must go through ``ray_tpu.ops.flash_attention`` (memory-efficient VJP),
  never ``flash_attention_pallas``/``blockwise_attention`` directly.
"""

import re
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


def _code_lines(path: Path):
    """Source lines with comments stripped (keeps strings; good enough for
    call-site greps)."""
    for n, line in enumerate(path.read_text().splitlines(), 1):
        yield n, line.split("#", 1)[0]


def test_single_receiver_per_worker_pipe():
    """CLAUDE.md invariant: one receiver thread per worker demuxes the
    pipe (replies vs execs) — never add a second ``conn.recv()`` site."""
    worker = ROOT / "ray_tpu" / "core" / "worker.py"
    sites = [(n, line) for n, line in _code_lines(worker)
             if re.search(r"\bconn\.recv\(\)", line)]
    assert len(sites) == 1, (
        f"worker.py has {len(sites)} conn.recv() call sites {sites}; the "
        "one-receiver-thread invariant (CLAUDE.md 'Architecture "
        "invariants') allows only _recv_loop to read the pipe — route new "
        "message kinds through it instead of adding a reader")

    runtime = ROOT / "ray_tpu" / "core" / "runtime.py"
    sites = [(n, line) for n, line in _code_lines(runtime)
             if re.search(r"\bconn\.recv(_bytes)?\(\)", line)]
    # allowed: the _accept_loop "hello" handshake (before the reader
    # exists) and the per-worker _reader_loop itself (recv_bytes + loads,
    # so the pipe byte counters see the framed size)
    assert len(sites) <= 2, (
        f"runtime.py has {len(sites)} conn.recv() call sites {sites}; "
        "only the _accept_loop handshake and _reader_loop may read a "
        "worker pipe (CLAUDE.md one-receiver-thread invariant)")


def test_no_raw_attention_kernels_outside_ops():
    """CLAUDE.md invariant: ALL training attention routes through
    ``ray_tpu.ops.flash_attention`` (it carries the memory-efficient
    custom VJP); calling the raw kernels from a differentiated path saves
    every probability block as a residual (~50 GB at llama-250M scale)."""
    offenders = []
    for path in sorted((ROOT / "ray_tpu").rglob("*.py")):
        rel = path.relative_to(ROOT)
        if rel.parts[:2] == ("ray_tpu", "ops"):
            continue  # the kernels' home (impl + dispatch) is exempt
        for n, line in _code_lines(path):
            if re.search(r"\b(flash_attention_pallas|blockwise_attention)"
                         r"\s*\(", line):
                offenders.append(f"{rel}:{n}: {line.strip()}")
    assert not offenders, (
        "direct raw-attention kernel call(s) outside ray_tpu/ops:\n  "
        + "\n  ".join(offenders)
        + "\nroute attention through ray_tpu.ops.flash_attention — the "
        "raw kernels have no memory-efficient VJP and OOM real HBM when "
        "differentiated (CLAUDE.md 'Architecture invariants')")


def test_core_metrics_only_via_metric_defs():
    """ISSUE 4 satellite: ``util/metric_defs.py`` is the single source of
    truth for built-in metrics — core/cluster modules must not create
    ad-hoc ``Counter(``/``Gauge(``/``Histogram(`` instances (they'd skip
    the help/prefix/uniqueness invariants and the generated README
    table). User-facing metric creation stays in util/metrics.py."""
    offenders = []
    for sub in ("core", "cluster"):
        for path in sorted((ROOT / "ray_tpu" / sub).rglob("*.py")):
            rel = path.relative_to(ROOT)
            for n, line in _code_lines(path):
                if re.search(r"\b(Counter|Gauge|Histogram)\s*\(", line):
                    offenders.append(f"{rel}:{n}: {line.strip()}")
    assert not offenders, (
        "ad-hoc metric construction in core/cluster modules:\n  "
        + "\n  ".join(offenders)
        + "\ndefine the metric in ray_tpu/util/metric_defs.py and fetch "
        "it with metric_defs.get(name) instead")


def test_serialization_stays_cloudpickle_first():
    """CLAUDE.md invariant: ``serialization.serialize`` must try
    cloudpickle FIRST (plain pickle serializes ``__main__`` functions by
    reference and breaks workers)."""
    src = (ROOT / "ray_tpu" / "core" / "serialization.py").read_text()
    cp = src.find("cloudpickle.dumps")
    assert cp != -1, "serialization.py no longer uses cloudpickle.dumps?"


def test_cluster_plane_blocking_waits_have_deadlines():
    """Chaos-plane invariant (ISSUE 5): a wedged peer must surface a
    timeout, never park a thread forever. In ``cluster/`` that means

    - blocking pipe reads (``.recv()``) live ONLY in rpc.py's dedicated
      reader machinery (``_recv_framed`` + the polled handshake) — every
      caller waits on an Event with a deadline instead;
    - no bare ``<event>.wait()`` without a timeout argument.
    """
    cluster = ROOT / "ray_tpu" / "cluster"
    recv_sites = {}
    for path in sorted(cluster.rglob("*.py")):
        for n, line in _code_lines(path):
            if re.search(r"\.recv\(\)", line):
                recv_sites.setdefault(path.name, []).append(n)
    assert set(recv_sites) <= {"rpc.py"}, (
        f"blocking .recv() outside rpc.py: {recv_sites}; cluster-plane "
        "reads go through rpc.py's reader thread + deadline-capable "
        "call() (RTPU_RPC_DEFAULT_TIMEOUT_S), never a raw recv loop")
    assert len(recv_sites.get("rpc.py", [])) <= 2, (
        f"rpc.py grew new blocking .recv() sites: {recv_sites['rpc.py']}; "
        "only _recv_framed and the polled _client_handshake may block on "
        "a socket read")

    bare_waits = []
    for path in sorted(cluster.rglob("*.py")):
        for n, line in _code_lines(path):
            # subprocess reaps after an explicit kill (cluster_utils
            # shutdown paths) are not peer waits; events/conditions are
            if re.search(r"\b(ev|event|_stop|cv|cond)\w*\.wait\(\s*\)",
                         line):
                bare_waits.append(f"{path.name}:{n}: {line.strip()}")
    assert not bare_waits, (
        "un-deadlined event waits in cluster/:\n  "
        + "\n  ".join(bare_waits)
        + "\npass a timeout (and loop) so a wedged peer cannot park the "
        "thread forever")
