"""Architecture invariants, enforced by graftlint (ISSUE 6).

This file used to be a pile of regex greps; it is now a thin runner over
``ray_tpu.devtools.graftlint`` — one test per rule family, each failing
with ``path:line RULE message`` findings. The AST rules are alias-aware
and multi-line-safe where the greps were not, and every rule carries
positive/negative fixtures under ``tests/graftlint_fixtures/``
(self-checked in test_graftlint.py).

What the families guard (CLAUDE.md "Architecture invariants"):

- ``locks``       unguarded shared-state writes, lock-order inversions,
                  and blocking calls held under driver/GCS locks — the
                  static twin of util/contention.py's runtime profiler.
- ``jax``         memory-safe attention VJPs, honest TPU timing
                  barriers, JAX_PLATFORMS hygiene, and the 1.9 s/worker
                  module-scope-jax-import tax.
- ``layering``    data/train/tune/serve/rllib build ONLY on the public
                  task/actor/object API (the portability seam).
- ``invariants``  one-receiver-thread pipes, cloudpickle-first
                  serialization, metric_defs-only metrics,
                  deadline-capable cluster waits.
- ``failpoints``  the chaos-plane site catalog stays unique, literal,
                  and documented.
- ``meta``        every inline suppression names a real rule and
                  carries a reason (no silent baselines).
- ``protocol``    the wire vocabularies (worker pipe casts/reqs/frame
                  kinds, GCS + peer RPC methods, pubsub topics) agree
                  three ways: senders, dispatch arms, and the
                  checked-in core/protocol.py catalog (ISSUE 15).
- ``lifecycle``   session-scoped resources are reclaimable: shm rings
                  session-named for the shutdown sweep, BlockPool
                  claims rolled back on failure exits, manual spans
                  finished or handed off.
- ``lockgraph``   the merged whole-program held->acquired lock graph
                  is acyclic (3+-cycles and cross-module cycles the
                  per-class inversion rule cannot see).
"""

from pathlib import Path

import pytest

from ray_tpu.devtools import graftlint

ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def tree_findings():
    """One full-tree lint shared by every family test AND by
    test_graftlint.py (the analysis pass dominates the cost; rules are
    cheap) — see tests/_graftlint_tree.py."""
    from _graftlint_tree import tree_findings as shared

    findings = shared()
    by_family = {fam: [] for fam in graftlint.FAMILIES}
    rule_family = {r.name: r.family for r in graftlint.all_rules()}
    for f in findings:
        by_family.setdefault(rule_family.get(f.rule, "meta"), []).append(f)
    return by_family


def _assert_clean(by_family, family, hint):
    findings = by_family[family]
    rendered = "\n  ".join(f.render() for f in findings)
    assert not findings, (
        f"graftlint family '{family}' found violations:\n  {rendered}\n"
        f"{hint}")


def test_lock_discipline(tree_findings):
    """Unguarded writes to lock-managed attributes, inverted lock
    orders, and blocking calls (sleep/recv/rpc-call/wait) under a lock.
    r8 proved the driver control plane is ~1-2 ms of GIL-serialized CPU
    per task under ONE lock — blocking it blocks everyone."""
    _assert_clean(
        tree_findings, "locks",
        "take the lock (or use the _locked-suffix caller-holds-lock "
        "convention); judged-intentional lock-free sites need "
        "'# graftlint: disable=... -- reason'")


def test_jax_tpu_discipline(tree_findings):
    """Raw attention kernels outside ops/ (no memory-efficient VJP —
    ~50 GB of residuals at llama-250M scale), block_until_ready as a
    timing barrier (acks early through the axon tunnel), JAX_PLATFORMS
    leaking into worker envs (chip fights), and module-scope jax imports
    in zygote-imported core/cluster modules (~1.9 s per worker boot)."""
    _assert_clean(
        tree_findings, "jax",
        "route attention through ray_tpu.ops.flash_attention; time with "
        "a data-dependent device_get; set explicit per-worker platforms")


def test_layering_seam(tree_findings):
    """ML libraries import only the public task/actor/object API, util/,
    and each other — the seam that keeps them portable (CLAUDE.md)."""
    _assert_clean(
        tree_findings, "layering",
        "use the ray_tpu top-level API or add a public accessor to "
        "ray_tpu.util (e.g. util.state.actor_queue_depths)")


def test_ported_invariants(tree_findings):
    """AST ports of the old regex greps: single pipe receiver thread,
    cloudpickle-first serialize, metric_defs-only metric creation in
    core/cluster, deadline-capable cluster-plane waits."""
    _assert_clean(
        tree_findings, "invariants",
        "see the rule messages — each names the CLAUDE.md invariant and "
        "the compliant pattern")


def test_failpoint_site_catalog(tree_findings):
    """Every failpoints.hit() site: unique literal name, documented in
    util/failpoints.py's Sites list; no stale documented sites."""
    _assert_clean(
        tree_findings, "failpoints",
        "add new sites to the Sites block of util/failpoints.py; "
        "suffix names when instrumenting a second call site")


def test_suppression_hygiene(tree_findings):
    """Inline disables must name real rules and carry reasons — the
    no-silent-baseline rule that keeps the other families honest."""
    _assert_clean(
        tree_findings, "meta",
        "write '# graftlint: disable=<rule> -- <why this is safe>'")


def test_wire_protocol_sync(tree_findings):
    """Whole-program protocol drift (ISSUE 15): every pipe cast/req/
    frame kind, GCS/peer RPC literal, and pubsub topic has a sender, a
    dispatch arm, and a core/protocol.py catalog entry. A send without
    a handler is a silently dropped message; a handler without a sender
    is dead protocol (the r14 native migration left two)."""
    _assert_clean(
        tree_findings, "protocol",
        "update ray_tpu/core/protocol.py in the same change as the "
        "sender/handler — the catalog is the wire-protocol review "
        "surface")


def test_resource_lifecycle(tree_findings):
    """Acquire/release symmetry for session-scoped resources: shm
    rings created with session-derived names (the rtpu-chan-<session>-*
    sweep must be able to reclaim them), pool.alloc claims released on
    every failure exit, manual spans finished or handed off."""
    _assert_clean(
        tree_findings, "lifecycle",
        "pair every acquire with a release on each exit path; see the "
        "rule messages for the compliant in-tree pattern")


def test_global_lock_order(tree_findings):
    """The merged held->acquired lock graph over all modules is
    acyclic — catches 3+-cycles inside one class and cross-module
    cycles through shared module-level locks, which the per-class
    inversion rule structurally cannot see."""
    _assert_clean(
        tree_findings, "lockgraph",
        "pick one global acquisition order (each edge in the reported "
        "cycle carries its witness file:line)")
