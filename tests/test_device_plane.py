"""Device plane (ISSUE 19): compiled-program registry, planted-retrace
detection with exact signature diffs, version-gated snapshots, federation
stores, compile-storm alerting, and cost-model-driven MFU attribution.

Runs on the conftest 8-device virtual CPU mesh; the real-model parity
test needs modern jax and skips on the old sandbox."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.util import device_plane, events

from conftest import poll_until  # noqa: F401  (cluster-side tests import it)


@pytest.fixture
def plane():
    """Fresh device-plane + events state; restores env arming after."""
    saved_dp = os.environ.pop("RTPU_DEVICE_PLANE", None)
    saved_ev = os.environ.pop("RTPU_EVENTS", None)
    device_plane._reset_for_tests()
    events._reset_for_tests()
    yield device_plane
    for key, val in (("RTPU_DEVICE_PLANE", saved_dp),
                     ("RTPU_EVENTS", saved_ev)):
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val
    device_plane._reset_for_tests()
    events._reset_for_tests()


# ---------------------------------------------------------------------------
# registry: compiles, calls, cost analysis, arming
# ---------------------------------------------------------------------------

def test_plane_on_by_default_and_kill_switch(plane):
    assert device_plane.device_plane_enabled()  # no env -> ON

    os.environ["RTPU_DEVICE_PLANE"] = "0"
    device_plane._reset_for_tests()
    assert not device_plane.device_plane_enabled()
    f = device_plane.registered_jit(lambda x: x * 2.0, name="off::f")
    assert float(f(jnp.float32(3.0))) == 6.0  # pure passthrough
    assert device_plane.registry().rows() == []  # nothing registered
    assert device_plane.snapshot(min_version=0) is None


def test_registered_jit_records_compile_cost_and_donation(plane):
    f = device_plane.registered_jit(
        lambda a, b: a @ b, name="test::mm", component="test", steps=4,
        donate_argnums=(0,))
    out = f(jnp.ones((32, 32)), jnp.ones((32, 32)))
    jax.block_until_ready(out)
    f(jnp.ones((32, 32)), jnp.ones((32, 32)))  # warm call

    row = device_plane.registry().program("test::mm")
    assert row["compiles"] == 1 and row["retraces"] == 0
    assert row["calls"] == 2
    assert row["component"] == "test"
    assert row["donate"] == [0]
    assert row["compile_s_total"] > 0
    assert row["sigs"] == [{"args[0]": "float32[32,32]",
                            "args[1]": "float32[32,32]"}]
    # static cost analysis: a 32^3 matmul is 2*32^3 = 65536 flops
    assert row["cost"] and row["cost"]["flops"] >= 65536
    # steps=4 declares a scanned program: per-step flops divide by 4
    assert device_plane.program_flops_per_step("test::mm") == \
        pytest.approx(row["cost"]["flops"] / 4)

    # the compile landed in the builtin metrics, labeled by program
    from ray_tpu.util import metric_defs
    samples = dict(metric_defs.get("rtpu_jit_compiles_total")._samples())
    vals = [v for tags, v in samples.items()
            if dict(tags).get("program") == "test::mm"]
    assert vals and vals[0] >= 1


def test_planted_retrace_emits_one_event_with_exact_diff(plane):
    """THE acceptance check: a planted retrace yields exactly one
    jit_recompile event naming the differing shape."""
    f = device_plane.registered_jit(lambda x: (x * 2.0).sum(),
                                    name="test::double", component="test")
    f(jnp.zeros((4, 8), jnp.float32))
    assert [e["name"] for e in events.drain_ring()] == []  # first compile
    f(jnp.zeros((4, 8), jnp.float32))                      # warm call
    f(jnp.zeros((8, 8), jnp.float32))                      # planted retrace

    evs = [e for e in events.drain_ring() if e["name"] == "jit_recompile"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["program"] == "test::double"
    assert ev["severity"] == "warning"
    assert ev["diff"] == {"changed": {"args[0]": {"was": "float32[4,8]",
                                                  "now": "float32[8,8]"}}}
    row = device_plane.registry().program("test::double")
    assert row["compiles"] == 2 and row["retraces"] == 1
    assert row["calls"] == 3


def test_static_arg_retrace_diff_names_the_python_value(plane):
    f = device_plane.registered_jit(
        lambda x, flag: x + 1 if flag else x - 1, name="test::static",
        component="test", static_argnames=("flag",))
    f(jnp.zeros((4,)), flag=True)
    f(jnp.zeros((4,)), flag=False)  # static-arg half of a retrace diff
    evs = [e for e in events.drain_ring() if e["name"] == "jit_recompile"]
    assert len(evs) == 1
    assert evs[0]["diff"]["changed"] == {
        "kwargs['flag']": {"was": "py:bool:True", "now": "py:bool:False"}}


def test_sig_history_bounded_and_known_sig_not_a_retrace(plane):
    f = device_plane.registered_jit(lambda x: x.sum(), name="test::hist")
    for n in range(device_plane.MAX_SIGS + 4):
        f(jnp.zeros((n + 1,)))
    row = device_plane.registry().program("test::hist")
    assert row["compiles"] == device_plane.MAX_SIGS + 4
    assert row["retraces"] == device_plane.MAX_SIGS + 3
    assert len(row["sigs"]) == device_plane.MAX_SIGS  # bounded history
    events.drain_ring()
    # replaying an already-cached signature is a plain call, not a retrace
    f(jnp.zeros((2,)))
    assert device_plane.registry().program("test::hist")["retraces"] == \
        device_plane.MAX_SIGS + 3
    assert events.drain_ring() == []


def test_signature_diff_unit():
    old = {"a": "float32[4]", "b": "int32[2]", "gone": "float32[1]"}
    new = {"a": "float32[8]", "b": "int32[2]", "fresh": "bool[3]"}
    assert device_plane.signature_diff(old, new) == {
        "changed": {"a": {"was": "float32[4]", "now": "float32[8]"}},
        "added": {"fresh": "bool[3]"},
        "removed": {"gone": "float32[1]"},
    }
    assert device_plane.signature_diff({"a": "x"}, {"a": "x"}) == {}


# ---------------------------------------------------------------------------
# snapshots, census, federation stores
# ---------------------------------------------------------------------------

def test_snapshot_version_gating(plane):
    # an empty registry never ships (zygote workers without jax)
    assert device_plane.snapshot(min_version=0) is None

    f = device_plane.registered_jit(lambda x: x + 1, name="test::snap")
    f(jnp.zeros((4,)))
    snap = device_plane.snapshot(min_version=0)
    assert snap is not None and snap["version"] > 0
    assert snap["pid"] == os.getpid()
    assert [r["program"] for r in snap["programs"]] == ["test::snap"]

    # nothing changed since: gated off. Warm calls don't bump the
    # version either — a busy-but-stable registry stops re-shipping.
    assert device_plane.snapshot(min_version=snap["version"]) is None
    f(jnp.zeros((4,)))
    assert device_plane.snapshot(min_version=snap["version"]) is None
    # a fresh compile bumps it past the cursor again
    f(jnp.zeros((8,)))
    assert device_plane.snapshot(min_version=snap["version"]) is not None


def test_live_buffer_census_groups_by_shape_dtype(plane):
    held = [jnp.ones((1031, 257), jnp.float32) for _ in range(3)]
    jax.block_until_ready(held)
    census = device_plane.live_buffer_census()
    assert census is not None
    assert census["buffers"] >= 3
    mine = [g for g in census["groups"]
            if g["shape"] == [1031, 257] and g["dtype"] == "float32"]
    assert mine, "held buffers missing from the census groups"
    assert mine[0]["count"] >= 3
    assert mine[0]["bytes"] >= 3 * 1031 * 257 * 4
    assert census["bytes"] >= mine[0]["bytes"]
    del held


def test_device_store_replaces_by_origin_and_evicts(plane):
    ds = device_plane.DeviceStore()
    ds.ingest("w1", {"worker_id": "w1", "component": "worker"},
              {"pid": 1, "version": 1, "programs": []})
    ds.ingest("w1", {"worker_id": "w1", "component": "worker"},
              {"pid": 1, "version": 2, "programs": []})
    out = ds.export()
    assert len(out) == 1  # snapshot-replace, not append
    assert out[0]["version"] == 2 and out[0]["worker_id"] == "w1"

    ds.MAX_ORIGINS = 2
    ds.ingest("w2", {"worker_id": "w2"}, {"pid": 2, "version": 1,
                                          "programs": []})
    ds.ingest("w3", {"worker_id": "w3"}, {"pid": 3, "version": 1,
                                          "programs": []})
    assert {e["worker_id"] for e in ds.export()} == {"w2", "w3"}


def test_merge_report_labels_totals_and_ordering(plane):
    entries = [
        {"pid": 1, "node_id": "n1", "component": "driver",
         "programs": [{"program": "a", "compiles": 2, "retraces": 1,
                       "compile_s_total": 1.0}],
         "hbm": {"bytes_in_use": 10, "bytes_limit": 100}},
        {"pid": 2, "node_id": "n2", "worker_id": "w2",
         "component": "worker",
         "programs": [{"program": "b", "compiles": 1, "retraces": 0,
                       "compile_s_total": 2.0}],
         "live_buffers": {"buffers": 3, "bytes": 64, "groups": []}},
    ]
    rep = device_plane.merge_report(entries)
    assert rep["totals"] == {"processes": 2, "programs": 2, "compiles": 3,
                             "retraces": 1, "live_buffer_bytes": 64,
                             "hbm": {"bytes_in_use": 10,
                                     "bytes_limit": 100}}
    # flat program rows carry their origin labels, heaviest compiler first
    assert [r["program"] for r in rep["programs"]] == ["b", "a"]
    assert rep["programs"][0]["node_id"] == "n2"
    assert rep["programs"][0]["component"] == "worker"
    assert rep["programs"][1]["node_id"] == "n1"
    procs = {p.get("node_id"): p for p in rep["processes"]}
    assert procs["n1"]["hbm"]["bytes_in_use"] == 10
    assert procs["n2"]["live_buffers"]["buffers"] == 3


# ---------------------------------------------------------------------------
# compile-storm + HBM alerts (synthetic watchdog ticks)
# ---------------------------------------------------------------------------

def _shipped_rule(name):
    from ray_tpu.util import alerts

    return [r for r in alerts.DEFAULT_RULES if r["name"] == name]


def test_compile_storm_alert_raises_and_clears_with_hysteresis(plane):
    from ray_tpu.util import alerts

    wd = alerts.Watchdog(rules=_shipped_rule("jit_compile_storm"),
                         sample_fn=lambda: {})

    def view(total):  # cumulative retrace counter, summed over programs
        return {"rtpu_jit_retraces_total": [((), float(total))]}

    assert wd.evaluate_once(view(0)) == []   # first tick: no window yet
    assert wd.evaluate_once(view(3)) == []   # +3 retraces: breach tick 1
    active = wd.evaluate_once(view(6))       # +3 again: FOR_TICKS met
    assert [a["alert"] for a in active] == ["jit_compile_storm"]
    assert [e["name"] for e in events.drain_ring()] == ["alert_raised"]
    assert wd.evaluate_once(view(6)) != []   # quiet tick 1: still active
    assert wd.evaluate_once(view(6)) == []   # quiet tick 2: cleared
    assert [e["name"] for e in events.drain_ring()] == ["alert_cleared"]


def test_hbm_occupancy_alert_is_a_ratio_over_the_limit(plane):
    from ray_tpu.util import alerts

    wd = alerts.Watchdog(rules=_shipped_rule("hbm_occupancy"),
                         sample_fn=lambda: {})

    def view(used):
        return {"rtpu_tpu_hbm_used_bytes": [((), float(used))],
                "rtpu_tpu_hbm_limit_bytes": [((), 100.0)]}

    assert wd.evaluate_once(view(95)) == []  # breach tick 1
    active = wd.evaluate_once(view(95))      # tick 2: raises at >92%
    assert [a["alert"] for a in active] == ["hbm_occupancy"]
    wd.evaluate_once(view(50))
    assert wd.evaluate_once(view(50)) == []  # two healthy ticks clear


# ---------------------------------------------------------------------------
# cost-model-driven MFU attribution
# ---------------------------------------------------------------------------

def test_mfu_parity_cost_model_vs_hand_formula(plane):
    """Registry cost-analysis flops agree with the analytic 6N formula
    within 5% on a pure-matmul train step (fwd 2N + bwd 4N per token —
    exact for a matmul chain once dx is taken through the input)."""
    d, layers, tokens = 128, 8, 256
    key = jax.random.PRNGKey(0)
    params = [jax.random.normal(jax.random.fold_in(key, i), (d, d)) * 0.02
              for i in range(layers)]
    x = jax.random.normal(jax.random.fold_in(key, 99), (tokens, d))

    def loss_fn(ws, xs):
        h = xs
        for w in ws:
            h = h @ w
        return jnp.sum(h * h)

    step = device_plane.registered_jit(
        lambda ws, xs: jax.grad(loss_fn, argnums=(0, 1))(ws, xs),
        name="test::mlp_step", component="train")
    jax.block_until_ready(step(params, x))

    fps = device_plane.program_flops_per_step("test::mlp_step")
    assert fps is not None
    hand = 6 * layers * d * d * tokens
    assert fps == pytest.approx(hand, rel=0.05)

    # telemetry closes the loop: record_step(program=...) pulls flops
    # from the registry; with a spec-sheet peak override equal to the
    # hand formula's rate, the cost-model MFU must land within 5% of 1.
    from ray_tpu.train.telemetry import StepTelemetry

    st = StepTelemetry()
    dt = 0.01
    st.peak_flops = hand / dt
    st.record_step(dt, program="test::mlp_step")
    snap = st.snapshot()
    assert snap["mfu"] == pytest.approx(1.0, rel=0.05)
    assert snap["flops_per_s"] == pytest.approx(fps / dt, rel=1e-6)


@pytest.mark.modern_jax
def test_mfu_parity_debug_model(plane):
    """Cost-analysis flops vs the hand matmul count on the real debug
    model (remat=False, so XLA executes exactly the analytic flops)."""
    from ray_tpu import models

    c = models.llama_debug()
    params = models.init_params(jax.random.PRNGKey(0), c)
    B, T = 4, 33
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                              c.vocab_size)
    batch = {"tokens": np.asarray(toks)}

    def loss(p):
        return models.loss_and_metrics(p, batch, c)[0]

    step = device_plane.registered_jit(lambda p: jax.grad(loss)(p),
                                       name="test::debug_step",
                                       component="train")
    jax.block_until_ready(step(params))
    fps = device_plane.program_flops_per_step("test::debug_step")
    assert fps is not None

    # exact matmul count/token: projections + attention quadratic +
    # swiglu mlp per layer, plus the lm head; bwd doubles every matmul
    L = T - 1  # loss_and_metrics trains on tokens[:, :-1]
    d, f, hd = c.d_model, c.ff, c.hdim
    attn_p = d * hd * c.n_heads + 2 * d * hd * c.kv_heads \
        + hd * c.n_heads * d
    per_layer_fwd = 2 * (attn_p + 3 * d * f) + 4 * L * d
    fwd_per_token = c.n_layers * per_layer_fwd + 2 * d * c.vocab_size
    hand = 3 * fwd_per_token * B * L
    assert fps == pytest.approx(hand, rel=0.05)


# ---------------------------------------------------------------------------
# eager dispatcher hook (ops::flash_attention)
# ---------------------------------------------------------------------------

def test_tracked_call_registers_novel_signatures_only(plane):
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        return calls["n"]

    args = (jnp.zeros((2, 4, 8, 16)),)
    assert device_plane.tracked_call("test::eager", "ops", run, args,
                                     statics={"impl": "xla"}) == 1
    assert device_plane.tracked_call("test::eager", "ops", run, args,
                                     statics={"impl": "xla"}) == 2
    row = device_plane.registry().program("test::eager")
    assert row["compiles"] == 1 and row["calls"] == 2
    # a novel STATIC counts as a fresh implicit compile (and a retrace)
    device_plane.tracked_call("test::eager", "ops", run, args,
                              statics={"impl": "pallas"})
    row = device_plane.registry().program("test::eager")
    assert row["compiles"] == 2 and row["retraces"] == 1


# ---------------------------------------------------------------------------
# lifetime: the wrapper must never root its owner
# ---------------------------------------------------------------------------

def test_registered_jit_of_bound_method_does_not_pin_owner(plane):
    """Regression: storing the C++ PjitFunction's bound ``_cache_size``
    method on the wrapper made the owner <-> jit reference cycle
    uncollectable — a closed serve engine (and every arena weight view
    it aliased) survived ``del`` + ``gc.collect()`` forever, stranding
    shm. The wrapper must stay fully gc-traversable."""
    import gc
    import weakref

    class Owner:
        def step(self, x):
            return x * 2.0

    o = Owner()
    o.fn = device_plane.registered_jit(o.step, name="test::owner_step",
                                       component="test")
    assert float(o.fn(jnp.ones((4,)))[0]) == 2.0
    ref = weakref.ref(o)
    del o
    gc.collect()
    gc.collect()
    assert ref() is None
