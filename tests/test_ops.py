"""Kernel correctness: blockwise/pallas/ring attention vs naive reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    blockwise_attention,
    naive_attention,
    ring_attention,
    rms_norm,
    rotary_embedding,
    apply_rotary,
    moe_layer_dense,
)
from ray_tpu.ops.flash_pallas import flash_attention_pallas


def _rand_qkv(key, b=2, lq=128, lk=128, h=4, hk=None, d=32, dtype=jnp.float32):
    hk = h if hk is None else hk
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, lq, h, d), dtype)
    k = jax.random.normal(k2, (b, lk, hk, d), dtype)
    v = jax.random.normal(k3, (b, lk, hk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_naive(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    ref = naive_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, q_block=32, kv_block=64)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_blockwise_gqa():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), h=8, hk=2)
    ref = naive_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, q_block=32, kv_block=32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_interpret_matches_naive(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b=1, lq=256, lk=256, h=2, d=64)
    ref = naive_attention(q, k, v, causal=causal)
    out = flash_attention_pallas(
        q, k, v, causal=causal, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_pallas_interpret_gqa():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b=1, lq=128, lk=128, h=4, hk=2, d=64)
    ref = naive_attention(q, k, v, causal=True)
    out = flash_attention_pallas(
        q, k, v, causal=True, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), b=2, lq=64, lk=64, h=2, d=16)
    ref = naive_attention(q, k, v, causal=causal)

    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_rms_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
    w = jnp.ones((8,)) * 2.0
    out = rms_norm(x, w)
    expect = x / np.sqrt(np.mean(np.square(np.asarray(x)), -1, keepdims=True) + 1e-6) * 2.0
    np.testing.assert_allclose(out, expect, atol=1e-5)


@pytest.mark.parametrize("kind", ["rms", "layer"])
def test_norm_custom_vjp_matches_autodiff(kind):
    """The bf16-residual custom VJPs must match plain autodiff exactly.

    Reference grads come from differentiating the raw f32 math (no custom
    VJP) — the analytic backward in ops/layers.py must agree for both dx
    and dw, in f32 (tight tol) and bf16 inputs (cast tol).
    """
    from ray_tpu.ops import layer_norm
    from ray_tpu.ops.layers import _layer_norm_fwd_math, _rms_norm_fwd_math

    if kind == "rms":
        fn = lambda x, w: rms_norm(x, w)
        raw = lambda x, w: _rms_norm_fwd_math(x, w, 1e-6)
    else:
        bias = jnp.full((32,), 0.25)
        fn = lambda x, w: layer_norm(x, w, bias.astype(x.dtype))
        raw = lambda x, w: _layer_norm_fwd_math(x, w, bias.astype(x.dtype),
                                                1e-5)

    for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 2e-2)):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 32), dtype)
        w = (1.0 + 0.1 * jax.random.normal(
            jax.random.PRNGKey(1), (32,))).astype(dtype)
        g = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 32))

        def loss(f):
            return lambda x_, w_: (f(x_, w_).astype(jnp.float32) * g).sum()

        val, grads = jax.value_and_grad(loss(fn), argnums=(0, 1))(x, w)
        val_r, grads_r = jax.value_and_grad(loss(raw), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(val, val_r, rtol=tol)
        for a, b in zip(grads, grads_r):
            np.testing.assert_allclose(
                np.asarray(a, dtype="float32"),
                np.asarray(b, dtype="float32"), atol=tol, rtol=tol)


@pytest.mark.parametrize("op", ["rms", "layer", "rotary"])
def test_vjp_residuals_are_input_dtype(op):
    """The custom VJPs must not stash f32 intermediates: residuals of a
    bf16 op stay bf16 (plus tiny tables). This is the property that lets
    no-remat training fit HBM — a regression here only surfaces as an
    on-chip OOM during a scarce tunnel window."""
    from ray_tpu.ops import layer_norm

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 4, 32), jnp.bfloat16)
    w = jnp.ones((32,), jnp.bfloat16)
    if op == "rms":
        _, vjp_fn = jax.vjp(rms_norm, x, w)
    elif op == "layer":
        _, vjp_fn = jax.vjp(lambda x_, w_: layer_norm(x_, w_, w), x, w)
    else:
        cos, sin = rotary_embedding(jnp.arange(8), 32)
        _, vjp_fn = jax.vjp(lambda x_: apply_rotary(x_, cos, sin), x)
    leaves = jax.tree_util.tree_leaves(vjp_fn)
    f32_big = [l for l in leaves
               if hasattr(l, "dtype") and l.dtype == jnp.float32
               and getattr(l, "size", 0) >= x.size]
    assert not f32_big, f"f32 residuals leaked: {[l.shape for l in f32_big]}"


def test_rotary_custom_vjp_matches_autodiff():
    """apply_rotary's rotate-the-cotangent backward vs plain autodiff."""
    from ray_tpu.ops.layers import _rotate

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 32))
    cos, sin = rotary_embedding(jnp.arange(16), 32)
    g = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 4, 32))

    def loss(f):
        return lambda x_: (f(x_, cos, sin).astype(jnp.float32) * g).sum()

    dx = jax.grad(loss(apply_rotary))(x)
    dx_ref = jax.grad(loss(lambda x_, c, s: _rotate(x_, c, s, +1.0)))(x)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               atol=1e-5, rtol=1e-5)


def test_rotary_norm_preserving():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 4, 32))
    cos, sin = rotary_embedding(jnp.arange(16), 32)
    y = apply_rotary(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(y), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        atol=1e-4, rtol=1e-4,
    )
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x[:, 0]), atol=1e-5)


def test_moe_shapes_and_gradient():
    key = jax.random.PRNGKey(5)
    b, l, d, e, f = 2, 8, 16, 4, 32
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (b, l, d))
    router_w = jax.random.normal(ks[1], (d, e)) * 0.1
    w_gate = jax.random.normal(ks[2], (e, d, f)) * 0.1
    w_up = jax.random.normal(ks[3], (e, d, f)) * 0.1
    w_down = jax.random.normal(ks[4], (e, f, d)) * 0.1

    def loss(params):
        out, aux = moe_layer_dense(x, *params, k=2, capacity_factor=2.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    val, grads = jax.value_and_grad(loss)((router_w, w_gate, w_up, w_down))
    assert np.isfinite(float(val))
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))


def test_moe_full_capacity_matches_dense_topk():
    # With capacity >= tokens, no drops: output = sum of top-k expert outputs
    key = jax.random.PRNGKey(6)
    b, l, d, e, f = 1, 4, 8, 2, 16
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (b, l, d))
    router_w = jax.random.normal(ks[1], (d, e))
    w_gate = jax.random.normal(ks[2], (e, d, f)) * 0.2
    w_up = jax.random.normal(ks[3], (e, d, f)) * 0.2
    w_down = jax.random.normal(ks[4], (e, f, d)) * 0.2

    out, _ = moe_layer_dense(x, router_w, w_gate, w_up, w_down, k=e,
                             capacity_factor=float(e * b * l))
    # dense reference: softmax-weighted sum over ALL experts (k=e)
    xt = np.asarray(x).reshape(-1, d)
    probs = jax.nn.softmax(xt @ np.asarray(router_w), axis=-1)
    expect = np.zeros_like(xt)
    for ei in range(e):
        gate = np.asarray(jax.nn.silu(xt @ np.asarray(w_gate[ei])))
        h = gate * (xt @ np.asarray(w_up[ei]))
        expect += probs[:, ei:ei + 1] * (h @ np.asarray(w_down[ei]))
    np.testing.assert_allclose(np.asarray(out).reshape(-1, d), expect, atol=1e-4)


# ---------------------------------------------------------------------------
# Memory-efficient custom VJP (flash_attention): grads vs naive autodiff
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_vjp_matches_naive_grads(causal):
    from ray_tpu.ops import flash_attention

    q, k, v = _rand_qkv(jax.random.PRNGKey(4), b=2, lq=128, lk=128, h=4, d=32)
    tang = jax.random.normal(jax.random.PRNGKey(5), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=causal) * tang).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, impl="xla",
                                q_block=32, kv_block=64) * tang).sum()

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(g, r, atol=5e-5, rtol=5e-5)


def test_flash_attention_vjp_gqa_grads():
    """GQA: kv grads must sum over the head group (handled by repeat's AD)."""
    from ray_tpu.ops import flash_attention

    q, k, v = _rand_qkv(jax.random.PRNGKey(6), b=1, lq=64, lk=64, h=8, hk=2,
                        d=16)
    tang = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=True) * tang).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, impl="xla",
                                q_block=32, kv_block=32) * tang).sum()

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref, got):
        assert r.shape == g.shape
        np.testing.assert_allclose(g, r, atol=5e-5, rtol=5e-5)


def test_pallas_fwd_lse_interpret_and_hybrid_grad():
    """Pallas forward's lse must agree with the blockwise forward's, and the
    pallas-fwd/xla-bwd hybrid VJP must match naive grads (interpret mode)."""
    from ray_tpu.ops.attention import _mha_fwd_blockwise
    from ray_tpu.ops.flash_pallas import flash_attention_pallas_fwd

    q, k, v = _rand_qkv(jax.random.PRNGKey(8), b=1, lq=256, lk=256, h=2, d=64)
    out_p, lse_p = flash_attention_pallas_fwd(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
    out_b, lse_b = _mha_fwd_blockwise(q, k, v, True, 64 ** -0.5, 128, 128)
    np.testing.assert_allclose(out_p, out_b, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(lse_p, lse_b, atol=2e-5, rtol=2e-5)


def test_flash_attention_vjp_memory_shape():
    """The residuals of the custom VJP are O(L): differentiate a long-ish
    sequence that would need a huge p-residual under plain autodiff."""
    from ray_tpu.ops import flash_attention

    # 2048^2 * 4 heads * f32 p-residual would be 64 MiB *per layer*; with
    # the VJP residuals are q,k,v,out,lse ~= 4 MiB. Just proving it runs
    # and produces finite grads at this length on CPU is the regression.
    q, k, v = _rand_qkv(jax.random.PRNGKey(9), b=1, lq=2048, lk=2048, h=2,
                        d=32)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True, impl="xla").sum()

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_bwd_kernels_match_naive_grads(causal):
    """FA2-style dKV/dQ pallas kernels (interpret mode) vs naive autodiff."""
    from ray_tpu.ops.attention import _mha_fwd_blockwise
    from ray_tpu.ops.flash_pallas import flash_attention_pallas_bwd

    q, k, v = _rand_qkv(jax.random.PRNGKey(10), b=1, lq=256, lk=256, h=2,
                        d=64)
    tang = jax.random.normal(jax.random.PRNGKey(11), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=causal) * tang).sum()

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    out, lse = _mha_fwd_blockwise(q, k, v, causal, 64 ** -0.5, 128, 128)
    got = flash_attention_pallas_bwd(
        q, k, v, out, lse, tang, causal=causal,
        block_q=128, block_k=128, interpret=True)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(g, r, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_bwd_gqa_native_heads(causal):
    """GQA backward at NATIVE kv-head count (no group expand, ADVICE r2
    #5): dk/dv come back [B, Lk, Hk, D] and match naive autodiff."""
    from ray_tpu.ops.attention import _mha_fwd_blockwise, _repeat_kv
    from ray_tpu.ops.flash_pallas import flash_attention_pallas_bwd

    h, hk = 4, 2
    q, _, _ = _rand_qkv(jax.random.PRNGKey(12), b=1, lq=256, lk=256, h=h,
                        d=64)
    _, k, v = _rand_qkv(jax.random.PRNGKey(13), b=1, lq=256, lk=256, h=hk,
                        d=64)
    tang = jax.random.normal(jax.random.PRNGKey(14), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=causal) * tang).sum()

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    out, lse = _mha_fwd_blockwise(q, _repeat_kv(k, h), _repeat_kv(v, h),
                                  causal, 64 ** -0.5, 128, 128)
    got = flash_attention_pallas_bwd(
        q, k, v, out, lse, tang, causal=causal,
        block_q=128, block_k=128, interpret=True)
    assert got[1].shape == k.shape and got[2].shape == v.shape
    for r, g in zip(ref, got):
        np.testing.assert_allclose(g, r, atol=5e-5, rtol=5e-5)


# ---------------------------------------------------------------------------
# Sliding-window (local) attention
# ---------------------------------------------------------------------------

def _dense_window_reference(q, k, v, window):
    """Materialized softmax with an explicit band mask — independent of the
    naive_attention implementation under test."""
    import numpy as np

    qf = np.asarray(q, np.float32)
    kf = np.asarray(k, np.float32)
    vf = np.asarray(v, np.float32)
    h, hk = qf.shape[2], kf.shape[2]
    if hk != h:
        kf = np.repeat(kf, h // hk, axis=2)
        vf = np.repeat(vf, h // hk, axis=2)
    scores = np.einsum("bqhd,bkhd->bhqk", qf, kf) * qf.shape[-1] ** -0.5
    lq, lk = qf.shape[1], kf.shape[1]
    qpos, kpos = np.arange(lq)[:, None], np.arange(lk)[None, :]
    mask = (qpos >= kpos) & (qpos - kpos < window)
    scores = np.where(mask[None, None], scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, vf)


def test_sliding_window_fwd_all_impls():
    import numpy as np

    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 64, 4, 16)).astype(np.float32)
    k = rng.standard_normal((2, 64, 2, 16)).astype(np.float32)
    v = rng.standard_normal((2, 64, 2, 16)).astype(np.float32)
    ref = _dense_window_reference(q, k, v, window=24)
    for impl in ("naive", "xla"):
        out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=True, impl=impl, q_block=16,
                              kv_block=16, window=24)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5,
                                   rtol=2e-4, err_msg=impl)


def test_sliding_window_grads_match_naive():
    """The custom-VJP blockwise backward must match autodiff through the
    naive masked softmax."""
    import numpy as np

    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 48, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 48, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 48, 2, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((1, 48, 2, 8)), jnp.float32)

    def loss(impl):
        def f(q, k, v):
            o = flash_attention(q, k, v, causal=True, impl=impl,
                                q_block=16, kv_block=16, window=20)
            return (o * w).sum()

        return jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)

    ln, gn = loss("naive")
    lx, gx = loss("xla")
    np.testing.assert_allclose(float(ln), float(lx), rtol=1e-5)
    for a, b in zip(gn, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-4)


def test_sliding_window_requires_causal():
    from ray_tpu.ops.attention import flash_attention

    q = jnp.zeros((1, 16, 2, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=8)


def test_sliding_window_kv_slicing_long_seq():
    """seq >> window: the live-kv-block slicing path (static count,
    dynamic start) must stay exact vs the dense reference, fwd AND bwd."""
    import numpy as np

    from ray_tpu.ops.attention import _n_live_kv_blocks, flash_attention

    # nk=8, n_live=4 -> the slice is active (not the full-scan fallback)
    assert _n_live_kv_blocks(8, 16, 16, 24) == 4

    rng = np.random.default_rng(2)
    q = rng.standard_normal((2, 128, 4, 16)).astype(np.float32)
    k = rng.standard_normal((2, 128, 2, 16)).astype(np.float32)
    v = rng.standard_normal((2, 128, 2, 16)).astype(np.float32)
    ref = _dense_window_reference(q, k, v, window=24)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True, impl="xla", q_block=16, kv_block=16,
                          window=24)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-4)

    w = jnp.asarray(rng.standard_normal((2, 128, 4, 16)), jnp.float32)

    def loss(impl):
        def f(qq, kk, vv):
            o = flash_attention(qq, kk, vv, causal=True, impl=impl,
                                q_block=16, kv_block=16, window=24)
            return (o * w).sum()

        return jax.value_and_grad(f, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    ln, gn = loss("naive")
    lx, gx = loss("xla")
    np.testing.assert_allclose(float(ln), float(lx), rtol=1e-5)
    for a, b in zip(gn, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_sliding_window_pallas_interpret_fwd_bwd():
    """The Pallas kernels' banded liveness predicates + masks (interpret
    mode) must match the dense reference and the blockwise-XLA grads."""
    import numpy as np

    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.ops.flash_pallas import (flash_attention_pallas_bwd,
                                          flash_attention_pallas_fwd)

    rng = np.random.default_rng(3)
    # GQA shapes; seq 256, window 48, blocks 64 -> interior blocks get
    # skipped by the window liveness predicate
    q = jnp.asarray(rng.standard_normal((1, 256, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 256, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 256, 2, 16)), jnp.float32)
    ref = _dense_window_reference(q, k, v, window=48)
    out, lse = flash_attention_pallas_fwd(
        q, k, v, causal=True, block_q=64, block_k=64, window=48,
        interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-4)

    # backward: pallas dkv/dq kernels vs the naive-autodiff grads
    dout = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    dq, dk, dv = flash_attention_pallas_bwd(
        q, k, v, out, lse, dout, causal=True, block_q=64, block_k=64,
        window=48, interpret=True)

    def f(qq, kk, vv):
        o = flash_attention(qq, kk, vv, causal=True, impl="naive", window=48)
        return (o * dout).sum()

    gn = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip((dq, dk, dv), gn):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5, rtol=5e-4)


@pytest.mark.parametrize("window", [24, 40, 64, 120])
def test_sliding_window_sp_halo_matches_single_device(window):
    """Halo-exchange SP sliding-window attention must match the
    single-device windowed reference, fwd AND grads, differentiated
    through shard_map. Lloc = 32, so the windows cover: one hop
    (24 <= Lloc), two hops (40, 64 > Lloc: multi-hop chained ppermutes),
    and the sp-1 clamp (120 spans >= all shards — all-gather shape,
    band mask still exact)."""
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.ops.ring_attention import sliding_window_attention_sp
    from ray_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
    rng = np.random.default_rng(4)
    # global seq 128 over sp=4 -> Lloc 32
    q = jnp.asarray(rng.standard_normal((2, 128, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 128, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 128, 2, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((2, 128, 4, 16)), jnp.float32)

    def ref_loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="naive",
                            window=window)
        return (o * w).sum()

    ln, gn = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    spec = P(None, "sp", None, None)
    with jax.set_mesh(mesh):
        fn = shard_map(
            lambda q, k, v: sliding_window_attention_sp(
                q, k, v, axis="sp", window=window, q_block=16,
                kv_block=16),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)

        def sp_loss(q, k, v):
            return (fn(q, k, v) * w).sum()

        ls, gs = jax.jit(jax.value_and_grad(
            sp_loss, argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(float(ln), float(ls), rtol=1e-4)
    for a, b in zip(gn, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


# ---------------------------------------------------------------------------
# Attention-logit soft-capping (Gemma-2)
# ---------------------------------------------------------------------------

def test_softcap_fwd_bwd_all_impls_match_naive():
    """cap*tanh(s/cap) logits: value AND grads must agree across naive,
    blockwise-XLA custom VJP, and the Pallas kernels (interpret mode),
    with and without a sliding window."""
    import numpy as np

    from ray_tpu.ops.attention import _mha, naive_attention
    from ray_tpu.ops.flash_pallas import (flash_attention_pallas_bwd,
                                          flash_attention_pallas_fwd)

    rng = np.random.default_rng(2)
    B, S, HQ, HKV, D = 1, 64, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((B, S, HQ, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, HKV, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, HKV, D)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((B, S, HQ, D)), jnp.float32)
    cap = 5.0  # small: scores genuinely bend

    for window in (None, 24):
        def loss_naive(q, k, v):
            o = naive_attention(q, k, v, causal=True, window=window,
                                softcap=cap)
            return (o * w).sum()

        def loss_xla(q, k, v):
            o = _mha(q, k, v, True, D ** -0.5, 16, 16, False, window, cap)
            return (o * w).sum()

        vn, gn = jax.value_and_grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
        vx, gx = jax.value_and_grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(vx, vn, rtol=1e-4)
        for a, b in zip(gx, gn):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-3)

        o_p, lse = flash_attention_pallas_fwd(
            q, k, v, causal=True, block_q=16, block_k=16, window=window,
            softcap=cap, interpret=True)
        o_n = naive_attention(q, k, v, causal=True, window=window,
                              softcap=cap)
        np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_n),
                                   atol=1e-4, rtol=1e-3)
        dq, dk, dv = flash_attention_pallas_bwd(
            q, k, v, o_p, lse, w, causal=True, block_q=16, block_k=16,
            window=window, softcap=cap, interpret=True)
        for a, b in zip((dq, dk, dv), gn):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-3)


def test_softcap_changes_output():
    import numpy as np

    from ray_tpu.ops.attention import naive_attention

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 32, 2, 8)), jnp.float32)
    o1 = naive_attention(q, q, q, causal=True, softcap=5.0)
    o0 = naive_attention(q, q, q, causal=True)
    assert float(jnp.abs(o1 - o0).max()) > 1e-4
