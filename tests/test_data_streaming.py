"""Streaming exchange engine: backpressure, eager reclamation, spill +
restore, out-of-core sort/groupby (ISSUE r6 tentpole acceptance)."""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def rt_stream():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_exchange_bounded_blocks_in_flight(rt_stream, monkeypatch):
    """The scheduler's blocks-in-flight never exceeds the configured bound
    (plus one partition task's worth of headroom) — the backpressure that
    keeps an exchange's store footprint flat."""
    monkeypatch.setenv("RTPU_DATA_EXCHANGE_INFLIGHT", "8")
    from ray_tpu.data import streaming

    ds = rdata.range(2000, parallelism=20).random_shuffle(num_blocks=4)
    out = sorted(r["id"] for r in ds.take_all())
    assert out == list(range(2000))
    stats = streaming._LAST_EXCHANGE_STATS
    assert stats["kind"] == "random_shuffle"
    assert stats["parts"] == 20
    # bound: the window plus at most one not-yet-forwarded partition task
    assert stats["max_in_flight_seen"] <= 8 + stats["partitions"], stats
    assert stats["blocks"] == 20 * stats["partitions"]


def test_exchange_frees_consumed_intermediates(rt_stream):
    """Exchange inputs the executor owns (lazy source blocks) and the
    partition blocks are freed as they are consumed: after a shuffle the
    store holds roughly the OUTPUT, not input + partitions + output."""
    before = ray_tpu.object_store_memory()["used_bytes"]
    n = 200_000  # 1.6 MB of int64 per full copy
    ds = rdata.range(n, parallelism=8, lazy=True).random_shuffle(
        num_blocks=4)
    refs = list(ds.iter_block_refs())
    used = ray_tpu.object_store_memory()["used_bytes"] - before
    # correctness first
    ids = []
    for r in refs:
        ids.extend(ray_tpu.get(r)["id"].tolist())
    assert sorted(ids) == list(range(n))
    # the store grew by ~one dataset copy (outputs), not 3x: inputs and
    # partition blocks were freed. Generous 2x margin for inline overhead
    # and alignment (CLAUDE.md margins rule).
    dataset_bytes = n * 8
    assert used < 2 * dataset_bytes, (used, dataset_bytes)
    ray_tpu.free(refs)


def test_optimizer_collapses_repartition_into_shuffle():
    from ray_tpu.data import Optimizer, plan_summary
    from ray_tpu.data.execution import ShuffleOp

    plan = [ShuffleOp("repartition", "repartition", {"num_blocks": 6}),
            ShuffleOp("random_shuffle", "random_shuffle", {"seed": None})]
    out = Optimizer().optimize(plan)
    assert plan_summary(out) == ["shuffle:random_shuffle"]
    assert out[0].args["num_blocks"] == 6

    # SEEDED shuffle never collapses (deterministic output depends on the
    # repartitioned block boundaries)
    seeded = [ShuffleOp("repartition", "repartition", {"num_blocks": 6}),
              ShuffleOp("random_shuffle", "random_shuffle", {"seed": 3})]
    assert len(Optimizer().optimize(seeded)) == 2


def test_lazy_range_reexecutes(rt_stream):
    """A lazy dataset regenerates its source per execution (plans stay
    re-runnable), and its blocks flow through exchanges correctly."""
    ds = rdata.range(100, parallelism=4, lazy=True)
    assert ds.count() == 100
    assert ds.count() == 100  # second execution regenerates
    assert sorted(r["id"] for r in ds.random_shuffle().take_all()) == \
        list(range(100))
    assert "lazy source" in repr(ds)


def test_sort_string_keys_streaming(rt_stream):
    """The run-merge path is dtype-generic: string keys sort too (both
    directions)."""
    names = [f"name-{i:04d}" for i in np.random.default_rng(3).permutation(
        200)]
    ds = rdata.from_items([{"s": s} for s in names], parallelism=5)
    out = [r["s"] for r in ds.sort("s").take_all()]
    assert out == sorted(names)
    outd = [r["s"] for r in ds.sort("s", descending=True).take_all()]
    assert outd == sorted(names, reverse=True)


def test_groupby_custom_aggregate_streaming(rt_stream):
    ds = rdata.from_items([{"k": i % 4, "v": float(i)} for i in range(40)],
                          parallelism=4)
    out = ds.groupby("k").aggregate("span", lambda b: float(
        b["v"].max() - b["v"].min())).take_all()
    assert len(out) == 4
    assert all(r["span"] == 36.0 for r in out), out


@pytest.mark.slow
def test_out_of_core_sort_and_groupby_bounded_rss(monkeypatch):
    """ISSUE r6 acceptance: sort + groupby over a dataset LARGER than
    spill_threshold complete with bounded RSS (every process's RSS growth
    stays below the total dataset size), and the exchange + spill metrics
    are visible in a live /metrics scrape DURING the run."""
    import urllib.request

    # fresh runtime with a deliberately tiny store: ~8 MB arena, spill
    # past 12 MB — the 65 MB dataset cannot exist in shm
    ray_tpu.shutdown()
    monkeypatch.setenv("RTPU_STORE_CAPACITY", str(8 << 20))
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(12 << 20))
    monkeypatch.setenv("RTPU_DATA_EXCHANGE_RUN_BYTES", str(2 << 20))
    monkeypatch.setenv("RTPU_DATA_EXCHANGE_TARGET_ROWS", "200000")
    monkeypatch.setenv("RTPU_STORE_PREFAULT_BYTES", "0")
    ray_tpu.init(num_cpus=4)
    from ray_tpu.core.runtime import _get_runtime
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    port = dash.port

    n_blocks, rows_per = 24, 280_000
    n_rows = n_blocks * rows_per
    dataset_bytes = n_rows * 24  # key + g + pay, 8 B each
    assert dataset_bytes > (12 << 20) * 10  # far past the spill threshold

    # warm the pool BEFORE baselining RSS: a worker's first task pays the
    # one-time numpy/import footprint, which must not read as exchange
    # memory (reducer actors stay fresh per exchange — their import cost
    # is part of the margin the assertion leaves)
    rdata.range(10_000, parallelism=4).random_shuffle(num_blocks=2) \
        .take_all()

    def gen():
        rng = np.random.default_rng(0)
        for i in range(n_blocks):
            key = rng.integers(0, 1 << 40, size=rows_per)
            yield {"key": key, "g": key % 7,
                   "pay": np.full(rows_per, float(i))}

    expected_key_sum = sum(
        int(b["key"].sum()) for b in gen())

    # RSS sampler: driver + every worker (reducer actors included)
    stop = threading.Event()
    rss = {}  # pid -> [base_kb, peak_kb]

    def _vmrss(pid):
        try:
            with open(f"/proc/{pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except OSError:
            return None

    def sample_rss():
        while not stop.wait(0.1):
            pids = [os.getpid()]
            try:
                pids += [ws.proc.pid
                         for ws in list(_get_runtime().workers.values())]
            except Exception:
                pass
            for pid in pids:
                kb = _vmrss(pid)
                if kb is None:
                    continue
                ent = rss.setdefault(pid, [kb, kb])
                ent[1] = max(ent[1], kb)

    # live scrape: the engine gauges must be observable MID-RUN
    seen = {"inflight": 0.0, "last": ""}

    def scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.wait(0.2):
            try:
                txt = urllib.request.urlopen(url, timeout=2).read().decode()
            except Exception:
                continue
            seen["last"] = txt
            for line in txt.splitlines():
                if line.startswith("rtpu_data_exchange_blocks_in_flight "):
                    seen["inflight"] = max(seen["inflight"],
                                           float(line.split()[1]))

    threads = [threading.Thread(target=sample_rss, daemon=True),
               threading.Thread(target=scrape, daemon=True)]
    for t in threads:
        t.start()
    try:
        from ray_tpu.data.dataset import Dataset

        # ---- out-of-core SORT ----
        ds = Dataset(gen).sort("key", num_blocks=8)
        rows_seen = 0
        key_sum = 0
        last = None
        for ref in ds.iter_block_refs():
            block = ray_tpu.get(ref)
            keys = block["key"]
            if len(keys) == 0:
                continue
            assert np.all(keys[1:] >= keys[:-1]), "block not sorted"
            if last is not None:
                assert keys[0] >= last, "global order broken across blocks"
            last = keys[-1]
            rows_seen += len(keys)
            key_sum += int(keys.sum())
            ray_tpu.free(ref)  # consume-and-release keeps the store flat
        assert rows_seen == n_rows
        assert key_sum == expected_key_sum

        # ---- out-of-core GROUPBY (combinable aggregation) ----
        gds = Dataset(gen).groupby("g")
        counts = {r["g"]: r["count()"] for r in gds.count().take_all()}
        assert sorted(counts) == list(range(7))
        assert sum(counts.values()) == n_rows
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        txt = seen["last"]
        stop_dashboard()
        ray_tpu.shutdown()

    # exchange metrics were visible in a mid-run scrape
    assert seen["inflight"] > 0, "blocks-in-flight never observed mid-run"
    assert "rtpu_data_exchange_bytes_total" in txt
    assert "data_exchange_reducer_queue_depth" in txt

    # the dataset actually spilled (driver put the source blocks, so the
    # driver-side spill counter must have moved), and spilled bytes were
    # read back (restore or direct spill reads) to produce the output
    def metric(name):
        for line in txt.splitlines():
            if line.startswith(name + " ") or (
                    line.startswith(name + "{")):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    assert metric("rtpu_object_store_spilled_bytes_total") > dataset_bytes / 4
    assert (metric("rtpu_object_store_restored_bytes_total")
            + metric("rtpu_object_store_spill_read_bytes_total")) > 0

    # bounded RSS: no process ever grew by even one dataset's worth —
    # nothing materialized the exchange (driver included)
    offenders = {pid: (peak - base) for pid, (base, peak) in rss.items()
                 if (peak - base) * 1024 >= dataset_bytes}
    assert not offenders, (offenders, dataset_bytes)
