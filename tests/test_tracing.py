"""Trace plane (ISSUE 7): span ring, cross-process collection into the
head TraceStore, mid-session arming, critical-path analysis, Perfetto
export, and tpu_watch single-instance hygiene.

The multi-NODE collection path (heartbeat -> GCS trace store) is covered
in test_cluster.py; the serve request chain in test_serve.py.
"""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import state, tracing, trace_store


def _cleanup_tracing():
    os.environ.pop("RTPU_TRACING", None)
    os.environ.pop("RTPU_TRACE_FILE", None)
    tracing._reset_for_tests()


@pytest.fixture
def clean_tracing():
    _cleanup_tracing()
    yield
    _cleanup_tracing()


def _wait_for(pred, timeout=45.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# recording plane (no runtime needed)
# ---------------------------------------------------------------------------


def test_disabled_span_is_noop(clean_tracing):
    with tracing.span("demo.test::off") as tp:
        assert tp is None
    assert tracing.manual_span("demo.test::off") is None
    tracing.record_span("demo.test::off", 1, 2)
    assert tracing.ring_stats()["len"] == 0


def test_ring_bounds_and_drop_counter(clean_tracing, monkeypatch):
    monkeypatch.setenv("RTPU_TRACING", "1")
    monkeypatch.setenv("RTPU_TRACE_RING", "16")
    tracing._reset_for_tests()
    end = time.time_ns()
    for i in range(40):
        tracing.record_span("demo.test::fill", end - 1000, end, {"i": i})
    st = tracing.ring_stats()
    assert st["len"] == 16
    assert st["dropped"] == 24
    batch = tracing.drain_ring()
    assert len(batch) == 16
    # drained exactly once: the ring is empty now
    assert tracing.ring_stats()["len"] == 0
    # newest survive a bounded ring
    assert batch[-1]["attributes"]["i"] == 39


def test_span_nesting_and_manual_parentage(clean_tracing, monkeypatch):
    monkeypatch.setenv("RTPU_TRACING", "1")
    tracing._reset_for_tests()
    with tracing.span("demo.test::outer") as outer_tp:
        assert outer_tp is not None
        with tracing.span("demo.test::inner") as inner_tp:
            pass
        ms = tracing.manual_span("demo.test::manual")
        ms.finish()
    spans = {s["name"]: s for s in tracing.drain_ring()}
    outer = spans["demo.test::outer"]
    inner = spans["demo.test::inner"]
    manual = spans["demo.test::manual"]
    assert inner["trace_id"] == outer["trace_id"]
    assert inner["parent_span_id"] == outer["span_id"]
    # manual span started while outer was active: same trace
    assert manual["trace_id"] == outer["trace_id"]
    assert manual["parent_span_id"] == outer["span_id"]
    assert outer_tp == f"00-{outer['trace_id']}-{outer['span_id']}-01"


def test_trace_store_since_cursor(clean_tracing):
    ts = trace_store.TraceStore(cap=100)
    ts.ingest([{"name": f"s{i}"} for i in range(5)], {"node_id": "n1"})
    batch, start = ts.since(0)
    assert start == 0 and len(batch) == 5
    assert all(s["node_id"] == "n1" for s in batch)
    # nothing new past the acked cursor
    batch2, start2 = ts.since(start + len(batch))
    assert batch2 == [] and start2 == 5
    ts.ingest([{"name": "s5"}])
    batch3, start3 = ts.since(5)
    assert [s["name"] for s in batch3] == ["s5"] and start3 == 5


def test_critical_path_for_trace_sums_exactly():
    ms = 1_000_000  # ns per ms
    spans = [
        {"name": "serve.handle::request", "trace_id": "t", "span_id": "a",
         "parent_span_id": None, "start_time_unix_nano": 0,
         "end_time_unix_nano": 100 * ms, "attributes": {}},
        {"name": "serve.handle::route", "trace_id": "t", "span_id": "b",
         "parent_span_id": "a", "start_time_unix_nano": 5 * ms,
         "end_time_unix_nano": 20 * ms, "attributes": {}},
        {"name": "execute::handle_request", "trace_id": "t",
         "span_id": "c", "parent_span_id": "b",
         "start_time_unix_nano": 40 * ms, "end_time_unix_nano": 90 * ms,
         "attributes": {}, "worker_id": "w1"},
    ]
    res = trace_store.critical_path_for_trace(spans)
    assert res["end_to_end_ms"] == pytest.approx(100.0)
    segs = res["segments"]
    total = sum(seg["ms"] for seg in segs.values())
    assert total == pytest.approx(100.0, abs=1e-6)
    # deepest-span attribution: route 15ms, execute 50ms, and the
    # queue/transit holes (5+20+10 = 35ms) are the root's SELF time
    exe = next(v for k, v in segs.items() if k.startswith("execute::"))
    assert exe["ms"] == pytest.approx(50.0)
    root = next(v for k, v in segs.items()
                if k.startswith("serve.handle::request"))
    assert root["ms"] == pytest.approx(35.0)
    assert res["dominant"].startswith("execute::")

    # without a covering root, the hole becomes an explicit gap segment
    res2 = trace_store.critical_path_for_trace(spans[1:])
    assert any(k.startswith("gap:") for k in res2["segments"])
    total2 = sum(seg["ms"] for seg in res2["segments"].values())
    assert total2 == pytest.approx(res2["end_to_end_ms"], abs=1e-6)


def test_critical_path_for_tasks_uses_submit_spans():
    ring = [{"task_id": b"\x01" * 16, "name": "f", "type": "task",
             "status": "ok", "ts": 0.0,
             "phases": {"queue": 0.001, "lease": 0.001, "execute": 0.002,
                        "store_result": 0.001, "total": 0.01}}]
    spans = [{"name": "submit::f",
              "attributes": {"task_id": (b"\x01" * 16).hex()},
              "start_time_unix_nano": 0,
              "end_time_unix_nano": 3_000_000}]
    res = trace_store.critical_path_for_tasks(ring, spans)
    assert res["tasks"] == 1
    segs = res["segments"]
    assert segs["driver_submit"]["mean_ms"] == pytest.approx(3.0)
    # transit = total - attributed = 10 - (1+1+2+1) - 3 = 2ms
    assert segs["transit"]["mean_ms"] == pytest.approx(2.0)
    out = trace_store.format_breakdown(res)
    assert "driver_submit" in out and "critical path" in out


# ---------------------------------------------------------------------------
# collection through a live runtime (workers push over the pipe)
# ---------------------------------------------------------------------------


@pytest.fixture
def traced_rt(clean_tracing, monkeypatch):
    monkeypatch.setenv("RTPU_TRACING", "1")
    tracing._reset_for_tests()
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_worker_spans_reach_driver_store(traced_rt, tmp_path):
    @ray_tpu.remote
    def traced(x):
        return x + 1

    assert ray_tpu.get(traced.remote(1), timeout=60) == 2

    def seen():
        # keep the pipeline busy so worker pushes fire promptly
        ray_tpu.get(traced.remote(0), timeout=60)
        spans = state.list_spans()
        ex = [s for s in spans if s["name"] == "execute::traced"
              and s.get("worker_id")]
        sub = [s for s in spans if s["name"] == "submit::traced"]
        return ex and sub and (ex, sub)

    got = _wait_for(seen)
    assert got, "worker execute spans never reached the driver TraceStore"
    ex, sub = got
    # driver submit span and worker execute span join one trace
    by_task = {s["attributes"].get("task_id"): s for s in sub}
    joined = [e for e in ex
              if e["attributes"].get("task_id") in by_task
              and e["trace_id"] ==
              by_task[e["attributes"]["task_id"]]["trace_id"]]
    assert joined, "execute spans did not share the submit span's trace"
    # origin labels ride the collection hop
    assert ex[0]["component"] == "worker"

    # unified Perfetto export: loads as JSON, has per-process rows and
    # real slices
    doc = state.export_perfetto(str(tmp_path / "t.json"))
    loaded = json.loads((tmp_path / "t.json").read_text())
    assert loaded == doc
    evs = doc["traceEvents"]
    assert any(e.get("ph") == "M" and e.get("name") == "process_name"
               for e in evs)
    assert any(e.get("ph") == "X" and "::" in str(e.get("name"))
               for e in evs)

    # aggregate critical path over the flight ring: execute attributed,
    # driver submit CPU visible from trace data
    res = state.summarize_critical_path()
    assert res["tasks"] > 0
    assert "execute" in res["segments"]
    assert "driver_submit" in res["segments"]


def test_enable_tracing_mid_session_reaches_live_workers(clean_tracing):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def warm(x):
            return x

        # worker exists BEFORE arming — it must learn over the pipe
        assert ray_tpu.get(warm.remote(1), timeout=60) == 1
        assert state.list_spans() == []
        tracing.enable_tracing()

        def seen():
            ray_tpu.get(warm.remote(0), timeout=60)
            return [s for s in state.list_spans()
                    if s["name"] == "execute::warm"]

        assert _wait_for(seen), \
            "pre-armed worker never recorded after enable_tracing()"
        tracing.disable_tracing()
        tracing.drain_ring()
        before = len(state.list_spans())
        ray_tpu.get(warm.remote(2), timeout=60)
        time.sleep(0.5)
        # disarm reached the driver at least: no new driver submit spans
        new = [s for s in state.list_spans()[before:]
               if s["name"] == "submit::warm"]
        assert not new
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# tpu_watch single-instance hygiene (ISSUE 7 satellite)
# ---------------------------------------------------------------------------


def test_tpu_watch_status_and_stale_pidfile(tmp_path):
    from ray_tpu.util import tpu_watch

    pidfile = str(tmp_path / "w.pid")
    log = str(tmp_path / "w.log")
    st = tpu_watch.watcher_status(pidfile, log, str(tmp_path / "c.json"),
                                  scan=lambda: [])
    assert st["running"] is False and st["pid"] is None

    # a pidfile pointing at a live NON-watcher process (this pytest) is
    # stale, not running
    tpu_watch.write_pidfile(pidfile, os.getpid())
    st = tpu_watch.watcher_status(pidfile, log, str(tmp_path / "c.json"),
                                  scan=lambda: [])
    assert st["running"] is False
    assert st["pidfile_stale"] is True


def test_tpu_watch_single_instance_gate(tmp_path):
    from ray_tpu.util import tpu_watch

    pidfile = str(tmp_path / "w.pid")
    # no watcher anywhere: we may start, and the pidfile now names us
    assert tpu_watch.ensure_single_instance(pidfile, force=False,
                                            scan=lambda: []) is True
    assert tpu_watch.read_pidfile(pidfile) == os.getpid()
    # stale pidfile (live pid, but not a watcher cmdline) is overwritten
    tpu_watch.write_pidfile(pidfile, os.getpid())
    assert tpu_watch.ensure_single_instance(pidfile, force=False,
                                            scan=lambda: []) is True
