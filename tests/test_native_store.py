"""Native C++ arena store: alloc/seal/get/release/delete/evict + client."""

import os
import uuid

import numpy as np
import pytest

from ray_tpu._native import NativeArena, load_store_lib
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import StoreClient

pytestmark = pytest.mark.skipif(load_store_lib() is None,
                                reason="native store lib unavailable")


@pytest.fixture
def arena():
    session = uuid.uuid4().hex[:12]
    a = NativeArena(session, capacity=1 << 20)  # 1 MiB
    yield a
    a.close()
    NativeArena.destroy(session)


def _oid(i: int) -> bytes:
    return i.to_bytes(4, "big") + b"\x00" * 16


def test_create_seal_get_roundtrip(arena):
    payload = os.urandom(1000)
    view = arena.create(_oid(1), len(payload))
    view[:] = payload
    del view
    arena.seal(_oid(1))
    arena.release(_oid(1))

    got = arena.get(_oid(1))
    assert got is not None and bytes(got) == payload
    del got
    arena.release(_oid(1))


def test_get_before_seal_fails(arena):
    v = arena.create(_oid(2), 100)
    assert v is not None
    del v
    assert arena.get(_oid(2)) is None     # not sealed yet
    assert not arena.contains(_oid(2))
    arena.seal(_oid(2))
    assert arena.contains(_oid(2))


def test_delete_and_space_reuse(arena):
    for i in range(3):
        v = arena.create(_oid(10 + i), 200_000)
        assert v is not None, f"alloc {i} failed"
        del v
        arena.seal(_oid(10 + i))
        arena.release(_oid(10 + i))
    used_before = arena.stats()["used"]
    for i in range(3):
        assert arena.delete(_oid(10 + i)) is None or True
    assert arena.stats()["used"] < used_before
    # space actually reusable
    v = arena.create(_oid(99), 500_000)
    assert v is not None


def test_lru_eviction_on_pressure(arena):
    # fill most of the 1 MiB arena with refcount-0 sealed objects
    for i in range(4):
        v = arena.create(_oid(20 + i), 200_000)
        assert v is not None
        del v
        arena.seal(_oid(20 + i))
        arena.release(_oid(20 + i))
    # allocation beyond free space triggers LRU eviction of the oldest
    v = arena.create(_oid(30), 300_000)
    assert v is not None
    assert not arena.contains(_oid(20))   # oldest got evicted
    assert arena.contains(_oid(23))       # newest survives


def test_pinned_objects_not_evicted(arena):
    v = arena.create(_oid(40), 400_000)
    del v
    arena.seal(_oid(40))
    arena.release(_oid(40))
    pinned = arena.get(_oid(40))          # hold a pin
    assert pinned is not None
    v2 = arena.create(_oid(41), 800_000)  # cannot fit without evicting 40
    assert v2 is None                     # eviction refused: 40 is pinned
    del pinned
    arena.release(_oid(40))
    v3 = arena.create(_oid(41), 800_000)
    assert v3 is not None


def test_cross_handle_visibility():
    session = uuid.uuid4().hex[:12]
    a = NativeArena(session, capacity=1 << 20)
    b = NativeArena(session, capacity=1 << 20)  # attach, not create
    try:
        v = a.create(_oid(50), 64)
        v[:] = b"x" * 64
        del v
        a.seal(_oid(50))
        got = b.get(_oid(50))
        assert bytes(got) == b"x" * 64
    finally:
        a.close()
        b.close()
        NativeArena.destroy(session)


def test_spill_restore_roundtrip(monkeypatch):
    """Spilled objects are restorable back into shm once headroom exists
    (ISSUE r6 / VERDICT missing #4): refused while the store is still
    over threshold, promoted (and the spill file removed) after."""
    monkeypatch.setenv("RTPU_NATIVE_STORE", "0")
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(1 << 20))
    session = uuid.uuid4().hex[:12]
    client = StoreClient(session)
    try:
        resident = ObjectID.from_random()
        spilly = ObjectID.from_random()
        v1 = np.arange(100_000, dtype=np.float64)   # ~800 KB -> shm
        v2 = np.arange(50_000, dtype=np.float64)    # ~400 KB -> spills
        client.put(resident, v1)
        assert not client.contains_spilled(resident)
        client.put(spilly, v2)
        assert client.contains_spilled(spilly)
        # r14: the spill path compresses, so the PHYSICAL dir byte count
        # may undercut the logical payload — it just has to be real
        assert 0 < client.spill_dir_bytes() <= v2.nbytes + 4096

        # reads + chunked reads serve straight from the spill file
        raw = client.get_raw(spilly)
        assert raw is not None
        assert client.get_raw_chunk(spilly, 0, 64) == raw[:64]

        # no shm headroom yet: restore refuses, the file stays
        assert not client.restore_spilled(spilly)
        assert client.contains_spilled(spilly)

        client.delete(resident)                     # headroom appears
        assert client.restore_spilled(spilly)
        assert not client.contains_spilled(spilly)
        assert client.spill_dir_bytes() == 0
        np.testing.assert_array_equal(client.get(spilly), v2)
        # restore is idempotent once resident
        assert client.restore_spilled(spilly)
    finally:
        StoreClient.cleanup_session(session)


def test_spill_restore_through_arena(monkeypatch):
    """With the native arena as the backend, restore lands the object in
    the arena (create/seal) and a local get reads it zero-copy."""
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(1 << 20))
    session = uuid.uuid4().hex[:12]
    # tiny arena so the first put overflows it into file segments
    monkeypatch.setenv("RTPU_STORE_CAPACITY", str(1 << 20))
    client = StoreClient(session)
    if client._arena is None:
        pytest.skip("arena unavailable")
    try:
        a = ObjectID.from_random()
        b = ObjectID.from_random()
        client.put(a, np.arange(110_000, dtype=np.float64))  # overflows
        client.put(b, np.arange(60_000, dtype=np.float64))
        # one of the two crossed the threshold into the spill dir
        spilled = [o for o in (a, b) if client.contains_spilled(o)]
        assert spilled
        target = spilled[0]
        client.delete(a if target == b else b)
        assert client.restore_spilled(target)
        assert not client.contains_spilled(target)
        got = client.get(target)
        assert got[1] == 1.0
        del got
        client.release(target)
    finally:
        StoreClient.cleanup_session(session)


def test_store_client_uses_arena_for_big_objects():
    session = uuid.uuid4().hex[:12]
    client = StoreClient(session)
    if client._arena is None:
        pytest.skip("arena unavailable")
    try:
        oid = ObjectID.from_random()
        big = np.arange(100_000, dtype=np.float64)
        inline, size = client.put(oid, big)
        assert inline is None             # went to shm, not inline
        assert size >= big.nbytes
        assert client._arena.stats()["num_objects"] == 1
        back = client.get(oid)
        np.testing.assert_array_equal(back, big)
        del back
        client.release(oid)
        client.delete(oid)
        assert client._arena.stats()["num_objects"] == 0
    finally:
        StoreClient.cleanup_session(session)
