"""DQN (replay + target net) and Anakin fully-jitted PPO."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_rl2():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_jax_cartpole_env_dynamics():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    state = env.reset(jax.random.PRNGKey(0))
    assert state.obs.shape == (4,)
    out = env.step(state, 1)
    assert float(out.reward) == 1.0
    assert not bool(out.done)
    # pushing one way forever terminates the episode
    s = state
    done = False
    for _ in range(200):
        o = env.step(s, 1)
        s = o.state
        if bool(o.done):
            done = True
            break
    assert done


def test_jax_cartpole_vectorized_autoreset():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    states = jax.vmap(env.reset)(keys)
    step = jax.jit(jax.vmap(env.step))
    for _ in range(50):
        actions = np.ones(8, np.int32)
        out = step(states, actions)
        states = out.state
    # auto-reset keeps observations in bounds
    assert np.all(np.abs(np.asarray(states.obs)[:, 0]) < 2.5)


def test_dqn_learns_cartpole(rt_rl2):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(lr=1e-3, learning_starts=300,
                        train_batch_size=64, updates_per_iteration=32,
                        target_update_freq=50)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(30):
        result = algo.train()
        returns.append(result.get("episode_return_mean", 0.0))
    algo.cleanup()
    assert max(returns[-5:]) > 40, f"DQN failed to learn: {returns}"


def test_anakin_ppo_learns_cartpole():
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=32, rollout_len=64,
                     lr=1e-3, entropy_coeff=0.01, seed=0)
    returns = []
    for _ in range(30):
        metrics = algo.train()
        returns.append(metrics["episode_return_mean"])
    # fully-jitted loop learns: returns clearly above the random ~20
    assert max(returns[-10:]) > 60, f"Anakin failed to learn: {returns}"


def test_anakin_single_program_no_host_sync():
    """One train() call = one jitted program (compile once, reuse)."""
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=8, rollout_len=8,
                     num_epochs=1, num_minibatches=1, seed=1)
    m1 = algo.train()
    m2 = algo.train()
    assert set(m1) == set(m2)
    assert np.isfinite(m1["policy_loss"])


# ---------------------------------------------------------------------------
# SAC
# ---------------------------------------------------------------------------

def test_sac_learner_update_shapes_and_dynamics(rt_rl2):
    """One SAC update: finite losses, targets polyak-move, alpha adapts."""
    import jax

    from ray_tpu.rllib.sac import SACLearner

    learner = SACLearner({"observation_dim": 3, "action_dim": 1},
                         {"lr": 3e-4, "tau": 0.05}, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.standard_normal((64, 3)).astype(np.float32),
        "actions": np.tanh(rng.standard_normal((64, 1))).astype(np.float32),
        "rewards": rng.standard_normal(64).astype(np.float32),
        "next_obs": rng.standard_normal((64, 3)).astype(np.float32),
        "dones": np.zeros(64, np.float32),
    }
    t0 = jax.tree.leaves(learner.target_params)[0].copy()
    m = learner.update(batch)
    assert np.isfinite(m["critic_loss"]) and np.isfinite(m["actor_loss"])
    assert m["alpha"] > 0
    t1 = jax.tree.leaves(learner.target_params)[0]
    assert not np.allclose(t0, t1), "polyak target did not move"


def test_sac_trains_on_pendulum_smoke(rt_rl2):
    from ray_tpu.rllib import SACConfig

    config = (SACConfig()
              .environment("Pendulum-v1")
              .env_runners(num_envs_per_env_runner=2,
                           rollout_fragment_length=64)
              .training(learning_starts=100, train_batch_size=64,
                        updates_per_iteration=4)
              .debugging(seed=0))
    algo = config.build()
    for _ in range(3):
        result = algo.train()
    assert "critic_loss" in result
    assert result["num_env_steps_sampled"] > 0
    state = algo.learner_group.get_state()
    assert "params" in state and "log_alpha" in state
    algo.cleanup()


def test_sac_rejects_discrete_env(rt_rl2):
    from ray_tpu.rllib import SACConfig

    with pytest.raises(ValueError, match="continuous"):
        SACConfig().environment("CartPole-v1").build()


# ---------------------------------------------------------------------------
# connectors
# ---------------------------------------------------------------------------

def test_connector_pipeline_normalize_and_scale():
    from ray_tpu.rllib import (ConnectorPipelineV2, NormalizeObservations,
                               ScaleActions)

    norm = NormalizeObservations()
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 3.0, (512, 4))
    out = norm(data)
    # running stats converge toward standardization
    out2 = norm(rng.normal(5.0, 3.0, (512, 4)))
    assert abs(out2.mean()) < 0.3 and 0.6 < out2.std() < 1.4
    # state roundtrip
    state = norm.get_state()
    norm2 = NormalizeObservations()
    norm2.set_state(state)
    x = rng.normal(5.0, 3.0, (8, 4))
    np.testing.assert_allclose(norm(x.copy()), norm2(x.copy()), atol=1e-6)

    scale = ScaleActions(low=np.array([-2.0]), high=np.array([2.0]))
    np.testing.assert_allclose(scale(np.array([[-1.0], [0.0], [1.0]])),
                               [[-2.0], [0.0], [2.0]])
    pipe = ConnectorPipelineV2([NormalizeObservations()])
    assert len(pipe.append(NormalizeObservations())) == 2


def test_env_runner_with_connectors(rt_rl2):
    from ray_tpu.rllib import NormalizeObservations, SingleAgentEnvRunner

    runner = SingleAgentEnvRunner(
        "CartPole-v1", num_envs=2, seed=0,
        env_to_module=NormalizeObservations(clip=5.0))
    b = runner.sample(num_steps=40)
    assert np.abs(b["obs"]).max() <= 5.0 + 1e-6
    runner.stop()


# ---------------------------------------------------------------------------
# offline RL
# ---------------------------------------------------------------------------

def test_offline_roundtrip_and_bc(rt_rl2, tmp_path):
    from ray_tpu.rllib import OfflineReader, record_episodes, train_bc

    path = str(tmp_path / "exp")
    record_episodes("CartPole-v1", path, num_steps=300, seed=0, num_envs=2)
    reader = OfflineReader(path)
    data = reader.read_all()
    assert set(data) >= {"obs", "actions", "rewards"}
    n = len(data["obs"])
    assert n > 100
    # batch iteration covers the data
    seen = sum(len(b["obs"]) for b in reader.iter_batches(64))
    assert seen == (n // 64) * 64
    # as_dataset rides the data plane
    ds = reader.as_dataset(parallelism=4)
    assert ds.count() == n

    # BC learns to imitate: logp of dataset actions goes up
    learner = train_bc(path, {"observation_dim": 4, "action_dim": 2,
                              "discrete": True},
                       num_epochs=3, minibatch_size=64)
    batch = {"obs": data["obs"].astype(np.float32),
             "actions": data["actions"]}
    final = learner.update(batch, minibatch_size=64, num_epochs=1)
    assert final["bc_logp"] > np.log(0.5) - 0.2  # better than uniform(2)


def test_appo_single_step_and_adaptive_kl(rt_rl2):
    """APPO: IMPALA's async pipeline + PPO clipped loss; the adaptive KL
    coefficient moves toward kl_target (reference appo.py role)."""
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(minibatch_size=64, use_kl_loss=True,
                        kl_target=10.0)  # huge target: coeff must shrink
              .debugging(seed=0))
    algo = config.build()
    r1 = algo.train()
    assert "policy_loss" in r1 and "kl" in r1
    coeffs = [algo._kl_coeff]
    for _ in range(3):
        r = algo.train()
        coeffs.append(algo._kl_coeff)
    algo.cleanup()
    # fully-synced single-pass updates measure ~zero KL, far below the
    # huge target, so the adaptive coefficient halves step over step
    assert coeffs[-1] < coeffs[0]
    assert r["num_env_steps_sampled"] > 0


def test_appo_learns_cartpole(rt_rl2):
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=256)
              .training(lr=5e-4, minibatch_size=256, num_epochs=4,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(12):
        returns.append(algo.train().get("episode_return_mean", 0.0))
    algo.cleanup()
    assert max(returns[-4:]) > 50, f"APPO failed to learn: {returns}"


# ---------------------------------------------------------------------------
# MARWIL + CQL (round-5 offline algorithms)
# ---------------------------------------------------------------------------

def _rollout_cartpole(policy, seed, n_eps, max_steps=200):
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    rows = {"obs": [], "actions": [], "rewards": [], "dones": []}
    for ep in range(n_eps):
        obs, _ = env.reset(seed=seed + ep)
        done = False
        steps = 0
        while not done and steps < max_steps:
            a = policy(obs)
            rows["obs"].append(obs.astype(np.float32))
            nobs, r, term, trunc, _ = env.step(a)
            rows["actions"].append(a)
            rows["rewards"].append(r)
            done = term or trunc
            rows["dones"].append(float(done))
            obs = nobs
            steps += 1
    return rows


def _eval_greedy(learner, seed, n_eps=15):
    import gymnasium as gym
    import jax

    env = gym.make("CartPole-v1")
    total = 0.0
    for ep in range(n_eps):
        obs, _ = env.reset(seed=seed + ep)
        done = False
        while not done:
            out = learner.module.forward_inference(
                learner.params,
                jax.numpy.asarray(obs[None].astype(np.float32)))
            a = int(jax.device_get(out["actions"])[0])
            obs, r, term, trunc, _ = env.step(a)
            total += r
            done = term or trunc
    return total / n_eps


def test_marwil_beats_bc_on_mixed_quality_data(tmp_path):
    """VERDICT r4 #7 done-criterion: on a dataset where a few
    high-return episodes are buried under many random ones, MARWIL's
    exponential advantage weighting recovers the good policy while plain
    BC imitates the (mostly random) mixture."""
    from ray_tpu.rllib.offline import (OfflineWriter, reward_to_go,
                                       train_bc, train_marwil)

    heur = lambda o: int(o[2] + 0.5 * o[3] > 0)  # near-perfect CartPole
    rng = np.random.default_rng(7)
    rand = lambda o: int(rng.integers(0, 2))
    d1 = _rollout_cartpole(heur, 0, 3)
    d2 = _rollout_cartpole(rand, 100, 60)
    merged = {k: np.asarray(d1[k] + d2[k]) for k in d1}
    rets = reward_to_go(merged["rewards"].astype(np.float32)[:, None],
                        merged["dones"].astype(np.float32)[:, None],
                        0.99)[:, 0]
    path = str(tmp_path / "mixed")
    w = OfflineWriter(path)
    w.write({"obs": np.stack(merged["obs"]),
             "actions": merged["actions"].astype(np.int64),
             "rewards": merged["rewards"].astype(np.float32),
             "returns": rets})
    w.flush()

    spec = {"observation_dim": 4, "action_dim": 2, "discrete": True,
            "hidden": (64, 64)}
    bc = train_bc(path, spec, num_epochs=15, minibatch_size=128, seed=0)
    mw = train_marwil(path, spec, beta=2.0, num_epochs=15,
                      minibatch_size=128, seed=0)
    r_bc = _eval_greedy(bc, 999)
    r_mw = _eval_greedy(mw, 999)
    # measured across seeds on this box: bc 59-133, marwil 244-384
    assert r_mw > 1.5 * r_bc, (r_mw, r_bc)
    assert r_mw > 180, (r_mw, r_bc)


def test_cql_conservative_q_penalty(tmp_path):
    """VERDICT r4 #7 done-criterion: the conservative penalty pushes Q on
    out-of-distribution (random) actions BELOW Q on dataset actions;
    plain SAC trained on the same data shows no such gap."""
    import jax

    from ray_tpu.rllib.cql import CQLLearner
    from ray_tpu.rllib.sac import SACLearner

    rng = np.random.default_rng(0)
    n = 2048
    obs = rng.standard_normal((n, 3)).astype(np.float32)
    good = np.tanh(obs[:, :1])
    actions = np.clip(good + 0.05 * rng.standard_normal((n, 1)),
                      -0.99, 0.99).astype(np.float32)
    rewards = (1.0 - (actions[:, 0] - good[:, 0]) ** 2).astype(np.float32)
    batch = {"obs": obs, "actions": actions, "rewards": rewards,
             "next_obs": obs, "dones": np.ones(n, np.float32)}
    spec = {"observation_dim": 3, "action_dim": 1, "hidden": (64, 64)}

    def ood_gap(learner):
        r = np.random.default_rng(1)
        rand_a = r.uniform(-1, 1, (n, 1)).astype(np.float32)
        q_data, _ = learner.module.q_values(learner.params, obs, actions)
        q_rand, _ = learner.module.q_values(learner.params, obs, rand_a)
        return float(jax.device_get(q_rand.mean() - q_data.mean()))

    cql = CQLLearner(spec, {"min_q_weight": 5.0, "num_actions": 4,
                            "bc_iters": 20}, seed=0)
    sac = SACLearner(spec, {}, seed=0)
    idx = np.random.default_rng(2)
    for _ in range(120):
        rows = idx.integers(0, n, 256)
        sub = {k: v[rows] for k, v in batch.items()}
        metrics = cql.update(sub)
        sac.update(sub)
    assert "cql_penalty" in metrics and "cql_gap" in metrics
    g_cql, g_sac = ood_gap(cql), ood_gap(sac)
    # measured on this box: cql ~ -1.3, sac ~ +0.01
    assert g_cql < -0.5, (g_cql, g_sac)
    assert g_cql < g_sac - 0.5, (g_cql, g_sac)


def test_record_episodes_returns_and_next_obs(rt_rl2, tmp_path):
    """record_episodes now ships returns/dones/next_obs; returns must be
    the discounted reward-to-go consistent with dones."""
    from ray_tpu.rllib import OfflineReader, record_episodes

    path = str(tmp_path / "exp5")
    record_episodes("CartPole-v1", path, num_steps=200, seed=0, num_envs=2,
                    gamma=0.9)
    data = OfflineReader(path).read_all()
    assert set(data) >= {"obs", "actions", "rewards", "dones", "returns",
                         "next_obs"}
    assert data["next_obs"].shape == data["obs"].shape
    # at every non-terminal step t within one env column the recursion
    # returns[t] = r[t] + gamma * returns[t+1] holds; spot-check by
    # reconstructing from a done-terminated suffix: the step BEFORE a done
    # has return r[t] + 0.9 * r[t+1]-chain — verify terminal steps exactly
    term_rows = data["dones"] > 0
    np.testing.assert_allclose(data["returns"][term_rows],
                               data["rewards"][term_rows], rtol=1e-5)


# ---------------------------------------------------------------------------
# DreamerV3 (reference rllib/algorithms/dreamerv3 role; JAX from scratch)
# ---------------------------------------------------------------------------


def _dreamer_sequences(rng, batch, T, n_actions=4, noise=2):
    """Goal-reading toy env: obs encodes a per-episode goal action (+
    noise dims); acting the goal yields reward 1 delivered with the NEXT
    obs (replay convention: rewards[t] results from actions[t-1]).
    Actions are random-policy (all the learner's training data)."""
    obs_dim = n_actions + noise
    goals = rng.integers(0, n_actions, size=batch)
    obs = np.zeros((batch, T, obs_dim), np.float32)
    for b in range(batch):
        obs[b, :, goals[b]] = 1.0
    obs[:, :, n_actions:] = rng.standard_normal(
        (batch, T, noise)).astype(np.float32) * 0.3
    actions = rng.integers(0, n_actions, size=(batch, T))
    rewards = np.zeros((batch, T), np.float32)
    rewards[:, 1:] = (actions[:, :-1] == goals[:, None]).astype(
        np.float32)
    continues = np.ones((batch, T), np.float32)
    return {"obs": obs, "actions": actions.astype(np.int32),
            "rewards": rewards, "continues": continues}, goals


def test_dreamerv3_world_model_learns():
    """The RSSM must learn to reconstruct observations and predict the
    action-conditioned reward from (h, z) — losses drop by a large
    factor over random-policy sequences."""
    from ray_tpu.rllib.dreamerv3 import DreamerV3Learner

    rng = np.random.default_rng(0)
    lr = DreamerV3Learner(
        {"observation_dim": 6, "action_dim": 4},
        {"deter": 64, "hidden": 64, "groups": 4, "classes": 4,
         "horizon": 5, "wm_lr": 3e-3}, seed=0)
    batch, _ = _dreamer_sequences(rng, batch=16, T=8)
    first = lr.update(batch)
    for _ in range(150):
        batch, _ = _dreamer_sequences(rng, batch=16, T=8)
        m = lr.update(batch)
    assert m["wm_recon"] < 0.3 * first["wm_recon"], (first, m)
    # the zero-init reward head starts at symlog-0 predictions, so the
    # ratio vs the first update is uninformative; assert an absolute
    # level instead: the best CONSTANT predictor scores ~0.09 on the
    # 25%-Bernoulli symlog rewards, so <0.06 proves the head actually
    # reads the action-conditioned state (probe: 0.026-0.055 @ 150)
    assert m["wm_reward"] < 0.06, (first, m)
    assert np.isfinite(m["wm_loss"])


def test_dreamerv3_actor_learns_from_imagination():
    """End-to-end: training purely from imagined rollouts must beat the
    random policy on the goal-reading env (random = 0.25 hit rate)."""
    from ray_tpu.rllib.dreamerv3 import DreamerV3Learner

    rng = np.random.default_rng(1)
    lr = DreamerV3Learner(
        {"observation_dim": 6, "action_dim": 4},
        {"deter": 64, "hidden": 64, "groups": 4, "classes": 4,
         "horizon": 5, "wm_lr": 3e-3, "actor_lr": 3e-3,
         "entropy_coef": 1e-2}, seed=0)
    for i in range(250):
        batch, _ = _dreamer_sequences(rng, batch=16, T=8)
        m = lr.update(batch)

    # evaluate the actor through the acting path (posterior filtering)
    batch, goals = _dreamer_sequences(rng, batch=64, T=8)
    state = lr.policy_state(64)
    prev_a = np.zeros(64, np.int64)
    hits, total = 0, 0
    for t in range(8):
        state, a = lr.act(state, batch["obs"][:, t], prev_a,
                          rng_seed=1000 + t, greedy=True)
        hits += int((np.asarray(a) == goals).sum())
        total += 64
        prev_a = np.asarray(a)
    rate = hits / total

    # stochastic acting: the carried key must advance (different draws
    # step to step), and sampled actions still beat random
    state = lr.policy_state(64)
    prev_a = np.zeros(64, np.int64)
    samp_hits = 0
    keys = []
    for t in range(8):
        state, a = lr.act(state, batch["obs"][:, t], prev_a)
        keys.append(tuple(np.asarray(state[2]).tolist()))
        samp_hits += int((np.asarray(a) == goals).sum())
        prev_a = np.asarray(a)
    assert len(set(keys)) == 8, "acting key did not advance"
    assert samp_hits / total > 0.5
    # probe: 0.97-0.98 across seeds 0/1/2 at 250 updates (twohot critic
    # + zero-init heads + entropy 1e-2); 0.8 leaves seed margin
    assert rate > 0.8, f"greedy hit rate {rate:.2f} (random 0.25): {m}"


def test_sequence_window_cache_sees_appended_shards(tmp_path):
    """ADVICE r5: the window cache was keyed on seq_len alone, so shards
    appended after the first epoch were silently ignored. The key now
    fingerprints the shard list and the reader re-lists the directory."""
    from ray_tpu.rllib.offline import OfflineReader, OfflineWriter

    path = str(tmp_path / "shards")

    def episode(n, base):
        return {
            "obs": np.full((n, 2), base, np.float32),
            "next_obs": np.full((n, 2), base + 1, np.float32),
            "actions": np.zeros(n, np.int64),
            "rewards": np.ones(n, np.float32),
            "dones": np.eye(1, n, n - 1, dtype=bool)[0],
            "terminateds": np.eye(1, n, n - 1, dtype=bool)[0],
        }

    w = OfflineWriter(path)
    w.write(episode(8, 0.0))
    w.flush()

    reader = OfflineReader(path)
    first = reader._sequence_windows(4)
    assert len(first) == 2  # 9 replay steps -> two non-overlapping windows
    assert reader._sequence_windows(4) is first  # cache hit, same shards

    # a second epoch of collection lands a new shard in the same dir
    w.write(episode(8, 10.0))
    w.flush()
    second = reader._sequence_windows(4)
    assert len(second) == 4, "appended shard silently ignored"
    # and the refreshed cache is stable again
    assert reader._sequence_windows(4) is second


def test_dreamerv3_offline_pipeline(tmp_path):
    """train_dreamerv3 over recorded single-env shards: sequence windows
    respect episode boundaries + the Dreamer replay shift, and the world
    model trains to finite, decreasing losses on real cartpole data."""
    import ray_tpu
    from ray_tpu.rllib import train_dreamerv3
    from ray_tpu.rllib.offline import OfflineReader, record_episodes

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        path = str(tmp_path / "dreamer-data")
        record_episodes("CartPole-v1", path, num_steps=600, num_envs=1,
                        seed=0)

        # window semantics (non-vacuous): terminal successor states DO
        # appear with continue=0; windows mid-episode carry the true
        # boundary reward (1.0 on cartpole), only each episode's first
        # state gets reward 0
        reader = OfflineReader(path)
        batch = next(reader.iter_sequences(8, 4, shuffle=False))
        assert batch["obs"].shape[:2] == (4, 8)
        wins = reader._sequence_windows(8)
        first_rewards = {float(w["rewards"][0]) for w in wins}
        assert 0.0 in first_rewards, "episode-start windows missing"
        assert 1.0 in first_rewards, "mid-episode windows lost the true boundary reward"
        assert any(w["continues"].min() == 0.0 for w in wins), \
            "terminal states never reach the learner"
        assert all(w["continues"][0] == 1.0 for w in wins)

        learner = train_dreamerv3(
            path, {"observation_dim": 4, "action_dim": 2},
            config={"deter": 32, "hidden": 32, "groups": 4, "classes": 4,
                    "horizon": 5, "wm_lr": 3e-3},
            seq_len=8, batch_size=8, num_updates=30)
        m = learner.last_metrics
        assert np.isfinite(m["wm_loss"]) and np.isfinite(m["imag_return"])
        assert m["wm_recon"] < 2.0, m  # symlog recon converging on 4-dim obs
    finally:
        ray_tpu.shutdown()
