"""DQN (replay + target net) and Anakin fully-jitted PPO."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_rl2():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_jax_cartpole_env_dynamics():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    state = env.reset(jax.random.PRNGKey(0))
    assert state.obs.shape == (4,)
    out = env.step(state, 1)
    assert float(out.reward) == 1.0
    assert not bool(out.done)
    # pushing one way forever terminates the episode
    s = state
    done = False
    for _ in range(200):
        o = env.step(s, 1)
        s = o.state
        if bool(o.done):
            done = True
            break
    assert done


def test_jax_cartpole_vectorized_autoreset():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    states = jax.vmap(env.reset)(keys)
    step = jax.jit(jax.vmap(env.step))
    for _ in range(50):
        actions = np.ones(8, np.int32)
        out = step(states, actions)
        states = out.state
    # auto-reset keeps observations in bounds
    assert np.all(np.abs(np.asarray(states.obs)[:, 0]) < 2.5)


def test_dqn_learns_cartpole(rt_rl2):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(lr=1e-3, learning_starts=300,
                        train_batch_size=64, updates_per_iteration=32,
                        target_update_freq=50)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(30):
        result = algo.train()
        returns.append(result.get("episode_return_mean", 0.0))
    algo.cleanup()
    assert max(returns[-5:]) > 40, f"DQN failed to learn: {returns}"


def test_anakin_ppo_learns_cartpole():
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=32, rollout_len=64,
                     lr=1e-3, entropy_coeff=0.01, seed=0)
    returns = []
    for _ in range(30):
        metrics = algo.train()
        returns.append(metrics["episode_return_mean"])
    # fully-jitted loop learns: returns clearly above the random ~20
    assert max(returns[-10:]) > 60, f"Anakin failed to learn: {returns}"


def test_anakin_single_program_no_host_sync():
    """One train() call = one jitted program (compile once, reuse)."""
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=8, rollout_len=8,
                     num_epochs=1, num_minibatches=1, seed=1)
    m1 = algo.train()
    m2 = algo.train()
    assert set(m1) == set(m2)
    assert np.isfinite(m1["policy_loss"])
