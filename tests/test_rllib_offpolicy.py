"""DQN (replay + target net) and Anakin fully-jitted PPO."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_rl2():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_jax_cartpole_env_dynamics():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    state = env.reset(jax.random.PRNGKey(0))
    assert state.obs.shape == (4,)
    out = env.step(state, 1)
    assert float(out.reward) == 1.0
    assert not bool(out.done)
    # pushing one way forever terminates the episode
    s = state
    done = False
    for _ in range(200):
        o = env.step(s, 1)
        s = o.state
        if bool(o.done):
            done = True
            break
    assert done


def test_jax_cartpole_vectorized_autoreset():
    import jax

    from ray_tpu.rllib import CartPoleJax

    env = CartPoleJax()
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    states = jax.vmap(env.reset)(keys)
    step = jax.jit(jax.vmap(env.step))
    for _ in range(50):
        actions = np.ones(8, np.int32)
        out = step(states, actions)
        states = out.state
    # auto-reset keeps observations in bounds
    assert np.all(np.abs(np.asarray(states.obs)[:, 0]) < 2.5)


def test_dqn_learns_cartpole(rt_rl2):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(lr=1e-3, learning_starts=300,
                        train_batch_size=64, updates_per_iteration=32,
                        target_update_freq=50)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(30):
        result = algo.train()
        returns.append(result.get("episode_return_mean", 0.0))
    algo.cleanup()
    assert max(returns[-5:]) > 40, f"DQN failed to learn: {returns}"


def test_anakin_ppo_learns_cartpole():
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=32, rollout_len=64,
                     lr=1e-3, entropy_coeff=0.01, seed=0)
    returns = []
    for _ in range(30):
        metrics = algo.train()
        returns.append(metrics["episode_return_mean"])
    # fully-jitted loop learns: returns clearly above the random ~20
    assert max(returns[-10:]) > 60, f"Anakin failed to learn: {returns}"


def test_anakin_single_program_no_host_sync():
    """One train() call = one jitted program (compile once, reuse)."""
    from ray_tpu.rllib import AnakinPPO

    algo = AnakinPPO("CartPole-v1", num_envs=8, rollout_len=8,
                     num_epochs=1, num_minibatches=1, seed=1)
    m1 = algo.train()
    m2 = algo.train()
    assert set(m1) == set(m2)
    assert np.isfinite(m1["policy_loss"])


# ---------------------------------------------------------------------------
# SAC
# ---------------------------------------------------------------------------

def test_sac_learner_update_shapes_and_dynamics(rt_rl2):
    """One SAC update: finite losses, targets polyak-move, alpha adapts."""
    import jax

    from ray_tpu.rllib.sac import SACLearner

    learner = SACLearner({"observation_dim": 3, "action_dim": 1},
                         {"lr": 3e-4, "tau": 0.05}, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.standard_normal((64, 3)).astype(np.float32),
        "actions": np.tanh(rng.standard_normal((64, 1))).astype(np.float32),
        "rewards": rng.standard_normal(64).astype(np.float32),
        "next_obs": rng.standard_normal((64, 3)).astype(np.float32),
        "dones": np.zeros(64, np.float32),
    }
    t0 = jax.tree.leaves(learner.target_params)[0].copy()
    m = learner.update(batch)
    assert np.isfinite(m["critic_loss"]) and np.isfinite(m["actor_loss"])
    assert m["alpha"] > 0
    t1 = jax.tree.leaves(learner.target_params)[0]
    assert not np.allclose(t0, t1), "polyak target did not move"


def test_sac_trains_on_pendulum_smoke(rt_rl2):
    from ray_tpu.rllib import SACConfig

    config = (SACConfig()
              .environment("Pendulum-v1")
              .env_runners(num_envs_per_env_runner=2,
                           rollout_fragment_length=64)
              .training(learning_starts=100, train_batch_size=64,
                        updates_per_iteration=4)
              .debugging(seed=0))
    algo = config.build()
    for _ in range(3):
        result = algo.train()
    assert "critic_loss" in result
    assert result["num_env_steps_sampled"] > 0
    state = algo.learner_group.get_state()
    assert "params" in state and "log_alpha" in state
    algo.cleanup()


def test_sac_rejects_discrete_env(rt_rl2):
    from ray_tpu.rllib import SACConfig

    with pytest.raises(ValueError, match="continuous"):
        SACConfig().environment("CartPole-v1").build()


# ---------------------------------------------------------------------------
# connectors
# ---------------------------------------------------------------------------

def test_connector_pipeline_normalize_and_scale():
    from ray_tpu.rllib import (ConnectorPipelineV2, NormalizeObservations,
                               ScaleActions)

    norm = NormalizeObservations()
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 3.0, (512, 4))
    out = norm(data)
    # running stats converge toward standardization
    out2 = norm(rng.normal(5.0, 3.0, (512, 4)))
    assert abs(out2.mean()) < 0.3 and 0.6 < out2.std() < 1.4
    # state roundtrip
    state = norm.get_state()
    norm2 = NormalizeObservations()
    norm2.set_state(state)
    x = rng.normal(5.0, 3.0, (8, 4))
    np.testing.assert_allclose(norm(x.copy()), norm2(x.copy()), atol=1e-6)

    scale = ScaleActions(low=np.array([-2.0]), high=np.array([2.0]))
    np.testing.assert_allclose(scale(np.array([[-1.0], [0.0], [1.0]])),
                               [[-2.0], [0.0], [2.0]])
    pipe = ConnectorPipelineV2([NormalizeObservations()])
    assert len(pipe.append(NormalizeObservations())) == 2


def test_env_runner_with_connectors(rt_rl2):
    from ray_tpu.rllib import NormalizeObservations, SingleAgentEnvRunner

    runner = SingleAgentEnvRunner(
        "CartPole-v1", num_envs=2, seed=0,
        env_to_module=NormalizeObservations(clip=5.0))
    b = runner.sample(num_steps=40)
    assert np.abs(b["obs"]).max() <= 5.0 + 1e-6
    runner.stop()


# ---------------------------------------------------------------------------
# offline RL
# ---------------------------------------------------------------------------

def test_offline_roundtrip_and_bc(rt_rl2, tmp_path):
    from ray_tpu.rllib import OfflineReader, record_episodes, train_bc

    path = str(tmp_path / "exp")
    record_episodes("CartPole-v1", path, num_steps=300, seed=0, num_envs=2)
    reader = OfflineReader(path)
    data = reader.read_all()
    assert set(data) >= {"obs", "actions", "rewards"}
    n = len(data["obs"])
    assert n > 100
    # batch iteration covers the data
    seen = sum(len(b["obs"]) for b in reader.iter_batches(64))
    assert seen == (n // 64) * 64
    # as_dataset rides the data plane
    ds = reader.as_dataset(parallelism=4)
    assert ds.count() == n

    # BC learns to imitate: logp of dataset actions goes up
    learner = train_bc(path, {"observation_dim": 4, "action_dim": 2,
                              "discrete": True},
                       num_epochs=3, minibatch_size=64)
    batch = {"obs": data["obs"].astype(np.float32),
             "actions": data["actions"]}
    final = learner.update(batch, minibatch_size=64, num_epochs=1)
    assert final["bc_logp"] > np.log(0.5) - 0.2  # better than uniform(2)


def test_appo_single_step_and_adaptive_kl(rt_rl2):
    """APPO: IMPALA's async pipeline + PPO clipped loss; the adaptive KL
    coefficient moves toward kl_target (reference appo.py role)."""
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(minibatch_size=64, use_kl_loss=True,
                        kl_target=10.0)  # huge target: coeff must shrink
              .debugging(seed=0))
    algo = config.build()
    r1 = algo.train()
    assert "policy_loss" in r1 and "kl" in r1
    coeffs = [algo._kl_coeff]
    for _ in range(3):
        r = algo.train()
        coeffs.append(algo._kl_coeff)
    algo.cleanup()
    # fully-synced single-pass updates measure ~zero KL, far below the
    # huge target, so the adaptive coefficient halves step over step
    assert coeffs[-1] < coeffs[0]
    assert r["num_env_steps_sampled"] > 0


def test_appo_learns_cartpole(rt_rl2):
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=256)
              .training(lr=5e-4, minibatch_size=256, num_epochs=4,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(12):
        returns.append(algo.train().get("episode_return_mean", 0.0))
    algo.cleanup()
    assert max(returns[-4:]) > 50, f"APPO failed to learn: {returns}"
