"""DAG API: eager execute, channels, compiled pipelines."""

import time
import uuid

import numpy as np

import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.experimental.channel import Channel, ChannelTimeoutError


@pytest.fixture
def rt_dag():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_channel_write_read_roundtrip():
    name = uuid.uuid4().hex[:8]
    ch = Channel(name, capacity=1 << 16, create=True)
    try:
        ch.write({"a": 1, "b": [1, 2, 3]})
        reader = Channel(name, create=False)
        assert reader.read(timeout=5) == {"a": 1, "b": [1, 2, 3]}
        # mutable: same channel carries the next value
        ch.write("second")
        assert reader.read(timeout=5) == "second"
        # no new value -> timeout
        with pytest.raises(ChannelTimeoutError):
            reader.read(timeout=0.1)
    finally:
        ch.unlink()


def test_dag_eager_execute(rt_dag):
    @ray_tpu.remote
    class Adder:
        def __init__(self, k):
            self.k = k

        def add(self, x):
            return x + self.k

    @ray_tpu.remote
    class Scaler:
        def scale(self, x):
            return x * 10

    a = Adder.remote(5)
    s = Scaler.remote()
    with InputNode() as inp:
        dag = s.scale.bind(a.add.bind(inp))
    out = ray_tpu.get(dag.execute(3))
    assert out == 80


def test_function_node_eager(rt_dag):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(10)) == 21


def test_compiled_dag_pipeline(rt_dag):
    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

    s1 = Stage.remote(1)
    s2 = Stage.remote(10)
    with InputNode() as inp:
        dag = s2.apply.bind(s1.apply.bind(inp))
    compiled = dag.experimental_compile()
    try:
        # repeated invocations reuse the same channels/loops
        for i in range(5):
            assert compiled.execute(i).get(timeout=30) == i + 11
    finally:
        compiled.teardown()


def test_compiled_dag_fan_in(rt_dag):
    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return 2 * x

        def add(self, a, b):
            return a + b

    w1 = Worker.remote()
    w2 = Worker.remote()
    w3 = Worker.remote()
    with InputNode() as inp:
        dag = w3.add.bind(w1.double.bind(inp), w2.double.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(3).get(timeout=30) == 12
        assert compiled.execute(5).get(timeout=30) == 20
    finally:
        compiled.teardown()


def test_compiled_dag_error_propagates(rt_dag):
    @ray_tpu.remote
    class Failer:
        def boom(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x

    f = Failer.remote()
    with InputNode() as inp:
        dag = f.boom.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get(timeout=30) == 1
        from ray_tpu.dag.compiled_dag import DAGExecutionError

        with pytest.raises(DAGExecutionError):
            compiled.execute(13).get(timeout=30)
        # pipeline survives the error
        assert compiled.execute(2).get(timeout=30) == 2
    finally:
        compiled.teardown()


def test_channel_ring_backlog_and_writer_backpressure():
    """Ring semantics: several unread values queue in slot order; a full
    ring blocks the writer (bounded -> ChannelFullError) until the
    slowest reader's cursor advances."""
    from ray_tpu.experimental.channel import Channel, ChannelFullError

    name = uuid.uuid4().hex[:8]
    ch = Channel(name, capacity=1 << 12, create=True, slots=4)
    try:
        reader = Channel(name, create=False)
        for i in range(3):
            ch.write(i)
        assert [reader.read(timeout=5) for _ in range(3)] == [0, 1, 2]
        for i in range(10):          # ring wraps across many cycles
            ch.write(("wrap", i))
            assert reader.read(timeout=5) == ("wrap", i)
        for i in range(4):           # fill every slot
            ch.write(i)
        with pytest.raises(ChannelFullError):
            ch.write(99, timeout=0.2)
        assert reader.read(timeout=5) == 0   # frees one slot
        ch.write(99, timeout=5)
        assert [reader.read(timeout=5) for _ in range(4)] == [1, 2, 3, 99]
    finally:
        ch.unlink()


def test_channel_unregistered_ring_is_bounded():
    """Before any reader registers, the ring itself bounds in-flight
    writes — a writer can never lap values a future reader is entitled
    to."""
    from ray_tpu.experimental.channel import Channel, ChannelFullError

    name = uuid.uuid4().hex[:8]
    ch = Channel(name, capacity=1 << 12, create=True, slots=3)
    try:
        for i in range(3):
            ch.write(i)
        with pytest.raises(ChannelFullError):
            ch.write(3, timeout=0.2)
        reader = Channel(name, create=False)
        assert reader.read(timeout=5) == 0   # backlog intact from value 0
    finally:
        ch.unlink()


def test_compiled_dag_pipelined_fifo_and_out_of_order_get(rt_dag):
    """max_in_flight admissions overlap; results map to THEIR invocation
    strictly FIFO even when futures are awaited out of order."""
    @ray_tpu.remote
    class Stage:
        def apply(self, x):
            return x * 10

    s = Stage.remote()
    with InputNode() as inp:
        dag = s.apply.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=8)
    try:
        futs = [compiled.execute(i) for i in range(8)]
        # out-of-order: awaiting the LAST future buffers results 0..6
        # into their own futures
        assert futs[7].get(timeout=60) == 70
        assert [futs[i].get(timeout=60) for i in range(7)] == [
            i * 10 for i in range(7)]
        # a second pipelined wave reuses the same rings
        futs = [compiled.execute(i) for i in range(8)]
        assert [f.get(timeout=60) for f in futs] == [
            i * 10 for i in range(8)]
    finally:
        compiled.teardown()


def test_compiled_dag_pipeline_throughput_overlaps_stages(rt_dag):
    """A 2-stage pipeline with pipelining admits the whole wave before
    draining — all results arrive, in order."""
    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

    s1, s2 = Stage.remote(1), Stage.remote(100)
    with InputNode() as inp:
        dag = s2.apply.bind(s1.apply.bind(inp))
    compiled = dag.experimental_compile(max_in_flight=4)
    try:
        for _ in range(3):  # several waves
            futs = [compiled.execute(i) for i in range(4)]
            assert [f.get(timeout=60) for f in futs] == [
                i + 101 for i in range(4)]
    finally:
        compiled.teardown()


def test_compiled_dag_concurrent_producers_fifo(rt_dag):
    """Two threads drive the same compiled DAG: admission order pairs
    each future with ITS result (the drive lock serializes admission and
    whoever drains settles futures for everyone)."""
    import threading

    @ray_tpu.remote
    class Stage:
        def apply(self, x):
            return x + 1

    s = Stage.remote()
    with InputNode() as inp:
        dag = s.apply.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=8)
    errors = []

    def drive(tid):
        try:
            for i in range(15):
                x = tid * 1000 + i
                got = compiled.execute(x).get(timeout=60)
                if got != x + 1:
                    errors.append((x, got))
        except BaseException as e:  # noqa: BLE001 — collected for assert
            errors.append(repr(e))

    try:
        threads = [threading.Thread(target=drive, args=(t,))
                   for t in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
    finally:
        compiled.teardown()


def test_compiled_dag_error_isolation_under_pipelining(rt_dag):
    """An error in invocation k surfaces on future k only — slots k-1 and
    k+1 resolve to their own correct results."""
    @ray_tpu.remote
    class Failer:
        def boom(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x

    f = Failer.remote()
    with InputNode() as inp:
        dag = f.boom.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=4)
    try:
        futs = [compiled.execute(x) for x in (1, 13, 2)]
        from ray_tpu.dag import DAGExecutionError

        assert futs[0].get(timeout=60) == 1
        with pytest.raises(DAGExecutionError):
            futs[1].get(timeout=60)
        assert futs[2].get(timeout=60) == 2
    finally:
        compiled.teardown()


def test_compiled_dag_backpressure_error(rt_dag):
    """A full pipeline (max_in_flight admissions outstanding) makes
    execute() block for a completion and raise DAGBackpressureError past
    its deadline — the shm-layer ChannelFullError never leaks."""
    @ray_tpu.remote
    class Slow:
        def apply(self, x):
            time.sleep(1.5)
            return x

    s = Slow.remote()
    with InputNode() as inp:
        dag = s.apply.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=2)
    try:
        f1 = compiled.execute(1)
        f2 = compiled.execute(2)
        from ray_tpu.dag import DAGBackpressureError, DAGExecutionError

        with pytest.raises(DAGBackpressureError):
            compiled.execute(3, timeout=0.2)
        assert issubclass(DAGBackpressureError, DAGExecutionError)
        assert f1.get(timeout=60) == 1
        assert f2.get(timeout=60) == 2
        # slots freed: the same admission now succeeds
        assert compiled.execute(3, timeout=60).get(timeout=60) == 3
    finally:
        compiled.teardown()


def test_compiled_dag_execute_async(rt_dag):
    """Asyncio drivers (serve replicas) admit and await without blocking
    their loop."""
    import asyncio

    @ray_tpu.remote
    class Stage:
        def apply(self, x):
            return x * 3

    s = Stage.remote()
    with InputNode() as inp:
        dag = s.apply.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=4)

    async def drive():
        futs = [await compiled.execute_async(i) for i in range(4)]
        return [await f for f in futs]

    try:
        assert asyncio.run(drive()) == [0, 3, 6, 9]
    finally:
        compiled.teardown()


def test_compiled_dag_teardown_unlinks_channels(rt_dag):
    import os

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    s = S.remote()
    with InputNode() as inp:
        dag = s.f.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(7).get(timeout=30) == 7
    paths = [ch.path for ch in compiled._channels]
    compiled.teardown()
    assert not any(os.path.exists(p) for p in paths)


def test_compiled_dag_teardown_frees_actor(rt_dag):
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    s = S.remote()
    with InputNode() as inp:
        dag = s.f.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(7).get(timeout=30) == 7
    compiled.teardown()
    # after teardown the actor serves normal calls again
    assert ray_tpu.get(s.f.remote(42), timeout=30) == 42


def test_device_channel_roundtrip_and_zero_copy(rt_dag):
    """DeviceChannel moves a jax array: raw bytes in the segment, and the
    CPU-backend reader ALIASES the channel buffer (no copy) — asserted via
    the consumer array's buffer pointer living inside the channel mapping
    (reference NCCL-channel role, torch_tensor_nccl_channel.py:29)."""
    import ctypes
    import uuid

    import jax
    import jax.numpy as jnp

    from ray_tpu.experimental.device_channel import DeviceChannel

    name = f"test-dev-{uuid.uuid4().hex[:6]}"
    ch = DeviceChannel(name, capacity=1 << 20, create=True)
    try:
        arr = jnp.arange(1024, dtype=jnp.float32) * 2.0
        ch.write(arr)
        reader = DeviceChannel(name, create=False)
        out = reader.read(timeout=5)
        assert isinstance(out, jax.Array)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))
        # zero-copy assertion (CPU backend): consumer buffer lies inside
        # the reader's channel mapping
        base = ctypes.addressof(ctypes.c_char.from_buffer(reader._mm))
        ptr = out.addressable_shards[0].data.unsafe_buffer_pointer()
        assert base <= ptr < base + len(reader._mm), (
            f"consumer array not aliased into the channel segment "
            f"(ptr={ptr:#x}, seg=[{base:#x},{base + len(reader._mm):#x}))")
        # control values still travel (pickle fallback)
        ch.write({"not": "a tensor"})
        assert reader.read(timeout=5) == {"not": "a tensor"}
        del out
    finally:
        ch.unlink()


def test_compiled_dag_device_edges(rt_dag):
    """Compiled DAG with DeviceTensorType edges: jax arrays flow
    actor->actor through device channels; consumers receive jax arrays."""
    import jax

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Scale:
        def apply(self, x):
            import jax
            import jax.numpy as jnp

            assert isinstance(x, jax.Array), type(x)
            return x * 2.0

    @ray_tpu.remote
    class Sum:
        def apply(self, x):
            import jax
            import jax.numpy as jnp

            assert isinstance(x, jax.Array), type(x)
            return jnp.sum(x)

    a, b = Scale.remote(), Sum.remote()
    with InputNode() as inp:
        inp.with_tensor_transport()
        mid = a.apply.bind(inp).with_tensor_transport()
        out = b.apply.bind(mid).with_tensor_transport()
    compiled = out.experimental_compile()
    try:
        import jax.numpy as jnp

        for k in range(3):
            fut = compiled.execute(jnp.ones((256,), jnp.float32) * (k + 1))
            val = fut.get(timeout=60)
            assert float(np.asarray(val)) == 2.0 * 256 * (k + 1)
    finally:
        compiled.teardown()
