"""DAG API: eager execute, channels, compiled pipelines."""

import time
import uuid

import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.experimental.channel import Channel, ChannelTimeoutError


@pytest.fixture
def rt_dag():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_channel_write_read_roundtrip():
    name = uuid.uuid4().hex[:8]
    ch = Channel(name, capacity=1 << 16, create=True)
    try:
        ch.write({"a": 1, "b": [1, 2, 3]})
        reader = Channel(name, create=False)
        assert reader.read(timeout=5) == {"a": 1, "b": [1, 2, 3]}
        # mutable: same channel carries the next value
        ch.write("second")
        assert reader.read(timeout=5) == "second"
        # no new value -> timeout
        with pytest.raises(ChannelTimeoutError):
            reader.read(timeout=0.1)
    finally:
        ch.unlink()


def test_dag_eager_execute(rt_dag):
    @ray_tpu.remote
    class Adder:
        def __init__(self, k):
            self.k = k

        def add(self, x):
            return x + self.k

    @ray_tpu.remote
    class Scaler:
        def scale(self, x):
            return x * 10

    a = Adder.remote(5)
    s = Scaler.remote()
    with InputNode() as inp:
        dag = s.scale.bind(a.add.bind(inp))
    out = ray_tpu.get(dag.execute(3))
    assert out == 80


def test_function_node_eager(rt_dag):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(10)) == 21


def test_compiled_dag_pipeline(rt_dag):
    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

    s1 = Stage.remote(1)
    s2 = Stage.remote(10)
    with InputNode() as inp:
        dag = s2.apply.bind(s1.apply.bind(inp))
    compiled = dag.experimental_compile()
    try:
        # repeated invocations reuse the same channels/loops
        for i in range(5):
            assert compiled.execute(i).get(timeout=30) == i + 11
    finally:
        compiled.teardown()


def test_compiled_dag_fan_in(rt_dag):
    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return 2 * x

        def add(self, a, b):
            return a + b

    w1 = Worker.remote()
    w2 = Worker.remote()
    w3 = Worker.remote()
    with InputNode() as inp:
        dag = w3.add.bind(w1.double.bind(inp), w2.double.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(3).get(timeout=30) == 12
        assert compiled.execute(5).get(timeout=30) == 20
    finally:
        compiled.teardown()


def test_compiled_dag_error_propagates(rt_dag):
    @ray_tpu.remote
    class Failer:
        def boom(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x

    f = Failer.remote()
    with InputNode() as inp:
        dag = f.boom.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get(timeout=30) == 1
        from ray_tpu.dag.compiled_dag import DAGExecutionError

        with pytest.raises(DAGExecutionError):
            compiled.execute(13).get(timeout=30)
        # pipeline survives the error
        assert compiled.execute(2).get(timeout=30) == 2
    finally:
        compiled.teardown()


def test_compiled_dag_backpressure(rt_dag):
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    s = S.remote()
    with InputNode() as inp:
        dag = s.f.bind(inp)
    compiled = dag.experimental_compile()
    try:
        fut = compiled.execute(1)
        from ray_tpu.dag.compiled_dag import DAGExecutionError

        with pytest.raises(DAGExecutionError):
            compiled.execute(2)          # previous result unconsumed
        assert fut.get(timeout=30) == 1
        assert compiled.execute(2).get(timeout=30) == 2
    finally:
        compiled.teardown()


def test_compiled_dag_teardown_frees_actor(rt_dag):
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    s = S.remote()
    with InputNode() as inp:
        dag = s.f.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(7).get(timeout=30) == 7
    compiled.teardown()
    # after teardown the actor serves normal calls again
    assert ray_tpu.get(s.f.remote(42), timeout=30) == 42
