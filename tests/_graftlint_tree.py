"""One full-tree graftlint pass shared by test_invariants.py and
test_graftlint.py — the analysis dominates the cost (a few seconds on
this throttled box), the rule passes are cheap, so the suite pays for it
once. Not a test module (leading underscore keeps pytest away)."""

from functools import lru_cache
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


@lru_cache(maxsize=1)
def tree_findings():
    from ray_tpu.devtools import graftlint

    return tuple(graftlint.lint([ROOT / "ray_tpu"], root=ROOT))
