"""Multi-agent RLlib: MARL module, env runner batching, PPO learning curve.

Reference roles: rllib/core/rl_module/marl_module.py,
rllib/env/multi_agent_env_runner.py, multi-agent PPO.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_rl():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_marl_module_per_policy_params():
    import jax

    from ray_tpu.rllib import MultiAgentRLModuleSpec

    spec = MultiAgentRLModuleSpec({
        "p0": {"observation_dim": 4, "action_dim": 3, "discrete": True,
               "hidden": (16,)},
        "p1": {"observation_dim": 6, "action_dim": 2, "discrete": True,
               "hidden": (16,)},
    })
    mod = spec.build()
    params = mod.init(jax.random.PRNGKey(0))
    assert set(params) == {"p0", "p1"}
    out = mod["p0"].forward_train(params["p0"],
                                  np.zeros((5, 4), np.float32))
    assert out["vf_preds"].shape == (5,)


def test_multi_agent_env_runner_batches_per_module():
    from ray_tpu.rllib import DebugCooperativeMatch, MultiAgentEnvRunner

    runner = MultiAgentEnvRunner(DebugCooperativeMatch, seed=0)
    batches = runner.sample(num_steps=40)
    # default mapping: one module per agent
    assert set(batches) == {"agent_0", "agent_1"}
    b = batches["agent_0"]
    assert b["obs"].shape == (40, 1, 4)
    assert b["rewards"].shape == (40, 1)
    assert b["next_obs"].shape == (1, 4)
    # shared-policy mapping: both agents ride one module -> [T, 2] arrays
    shared = MultiAgentEnvRunner(DebugCooperativeMatch,
                                 agent_to_module=lambda aid: "shared",
                                 seed=0)
    sb = shared.sample(num_steps=10)["shared"]
    assert sb["obs"].shape == (10, 2, 4)
    m = shared.get_metrics()
    assert "episode_return_mean" in m


def test_multi_agent_ppo_learns_cooperative_match(rt_rl):
    from ray_tpu.rllib import DebugCooperativeMatch, MultiAgentPPOConfig

    config = (MultiAgentPPOConfig()
              .environment(DebugCooperativeMatch)
              .multi_agent(policy_mapping_fn=lambda aid: aid)
              .env_runners(rollout_fragment_length=256)
              .training(lr=3e-3, minibatch_size=128, num_epochs=4,
                        entropy_coeff=0.01, gamma=0.0)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(12):
        result = algo.train()
        returns.append(result.get("episode_return_mean", 0.0))
    algo.cleanup()
    # random play: P(hit) = 1/4 per agent -> ep return ~= 16*(0.5+0.125*1)
    # ~= 10; perfect play = 16*(1+0.5)*2 = 48. Require clear learning.
    assert max(returns[-4:]) > 24, f"MA-PPO failed to learn: {returns}"


def test_multi_agent_ppo_remote_runners_and_checkpoint(rt_rl, tmp_path):
    from ray_tpu.rllib import DebugCooperativeMatch, MultiAgentPPOConfig

    config = (MultiAgentPPOConfig()
              .environment(DebugCooperativeMatch)
              .multi_agent(policy_mapping_fn=lambda aid: "shared")
              .env_runners(num_env_runners=2, rollout_fragment_length=64)
              .training(minibatch_size=64, num_epochs=1)
              .debugging(seed=0))
    algo = config.build()
    result = algo.train()
    assert result["num_env_steps_sampled"] == 64 * 2 * 2  # 2 runners x 2 agents
    assert "shared/policy_loss" in result
    state = algo.save_checkpoint(str(tmp_path))
    algo2 = (MultiAgentPPOConfig()
             .environment(DebugCooperativeMatch)
             .multi_agent(policy_mapping_fn=lambda aid: "shared")
             .training(minibatch_size=64, num_epochs=1)
             .debugging(seed=0)).build()
    algo2.load_checkpoint(state, str(tmp_path))
    w1 = algo.learner_group.get_weights()
    w2 = algo2.learner_group.get_weights()
    import jax

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), w1, w2)
    algo.cleanup()
    algo2.cleanup()
