"""Built-in metric registry invariants (ISSUE 4 tentpole + satellites).

The ``metric_defs.cc`` analog must stay the single source of truth:
every built-in has help text, the ``rtpu_`` prefix, one definition, one
registration — and the README reference table is generated from it, so
drift is a test failure, not a doc-review hope.
"""

import re
from pathlib import Path

import pytest

from ray_tpu.util import metric_defs

ROOT = Path(__file__).resolve().parents[1]


def test_registry_size_meets_acceptance_floor():
    # ISSUE 4 acceptance: >= 40 built-in core-runtime metrics
    assert len(metric_defs.all_defs()) >= 40


def test_every_def_has_prefix_help_and_unique_name():
    seen = set()
    for d in metric_defs.all_defs():
        assert d.name.startswith("rtpu_"), d.name
        assert d.help.strip(), f"{d.name} has empty help"
        assert d.name not in seen, f"duplicate def {d.name}"
        seen.add(d.name)
        assert d.kind in ("counter", "gauge", "histogram"), d.name
        if d.kind == "counter":
            assert d.name.endswith("_total"), \
                f"counter {d.name} must end in _total"
        if d.kind == "histogram":
            assert d.boundaries, f"histogram {d.name} needs boundaries"
            assert list(d.boundaries) == sorted(d.boundaries), d.name


def test_all_instantiate_and_expose_exactly_once():
    """Every def instantiates under its declared type and appears under
    exactly ONE HELP/TYPE header — duplicate registration across modules
    would repeat the header (forbidden by the text format)."""
    from ray_tpu.util.metrics import clear_registry, prometheus_text

    clear_registry()
    try:
        for d in metric_defs.all_defs():
            m = metric_defs.get(d.name)
            assert m.metric_type == d.kind, d.name
            m2 = metric_defs.get(d.name)  # second get: same instance
            assert m2 is m, d.name
        text = prometheus_text()
        for d in metric_defs.all_defs():
            assert text.count(f"# TYPE {d.name} ") == 1, d.name
            assert f"# HELP {d.name} " in text, d.name
    finally:
        clear_registry()


def test_get_survives_registry_clear():
    """A cleared registry (tests do this) must not leave metric_defs
    serving orphaned instances whose samples never reach /metrics."""
    from ray_tpu.util.metrics import clear_registry, prometheus_text

    clear_registry()
    try:
        c = metric_defs.get("rtpu_worker_deaths_total")
        c.inc(1)
        clear_registry()
        c2 = metric_defs.get("rtpu_worker_deaths_total")
        assert c2 is not c  # fresh registration, not the orphan
        c2.inc(2)
        assert "rtpu_worker_deaths_total 2.0" in prometheus_text()
    finally:
        clear_registry()


def test_markdown_table_lists_every_metric():
    table = metric_defs.markdown_table()
    assert table.startswith(metric_defs.MD_BEGIN)
    assert table.endswith(metric_defs.MD_END)
    for d in metric_defs.all_defs():
        assert f"`{d.name}`" in table, d.name


def test_readme_reference_table_matches_registry():
    """The README table is generated — regenerate and compare, so it can
    never drift from the registry (satellite: doc update)."""
    readme = (ROOT / "README.md").read_text()
    start = readme.find(metric_defs.MD_BEGIN)
    end = readme.find(metric_defs.MD_END)
    assert start != -1 and end != -1, (
        "README.md lacks the generated metrics reference markers; run "
        "python -m ray_tpu.util.metric_defs --update README.md")
    current = readme[start:end + len(metric_defs.MD_END)]
    assert current == metric_defs.markdown_table(), (
        "README metrics reference is stale — regenerate with "
        "python -m ray_tpu.util.metric_defs --update README.md")


def test_contention_profiler_exports():
    """Instrumented locks surface both the accumulators (summarize) and
    the wait histogram under names defined in metric_defs."""
    import threading
    import time

    from ray_tpu.util import contention
    from ray_tpu.util.metrics import prometheus_text

    lk = contention.timed_rlock("test.defs_lock")
    if not contention.enabled():
        pytest.skip("contention profiler disabled in env")

    def holder():
        with lk:
            time.sleep(0.03)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.005)
    with lk:
        pass
    t.join()
    s = contention.summarize()["test.defs_lock"]
    assert s["acquisitions"] >= 2
    assert s["contended"] >= 1
    assert s["wait_total_s"] > 0
    text = prometheus_text()
    assert 'rtpu_lock_wait_seconds_bucket{le="0.05",lock="test.defs_lock"}' \
        in text
    assert 'rtpu_lock_acquisitions{lock="test.defs_lock"}' in text


def test_condition_over_timed_rlock():
    """threading.Condition must work over the instrumented RLock (the
    driver's _stream_cv is built exactly this way)."""
    import threading

    from ray_tpu.util.contention import TimedRLock

    lk = TimedRLock("test.cv_lock")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait(2.0)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(5)
    assert hits == [1]
