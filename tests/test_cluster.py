"""Multi-node core: scheduling spread, object transfer, node failover.

Reference test pattern: ``python/ray/cluster_utils.py:135`` — extra node
daemons as separate processes on one machine.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _init(c, **kw):
    return ray_tpu.init(address=c.address, cluster_authkey=c.authkey,
                        num_cpus=2, **kw)


def test_cluster_boots_and_lists_nodes(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _init(cluster)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
        if len(nodes) >= 3:
            break
        time.sleep(0.2)
    assert len(nodes) >= 3  # head + 2 daemons


def test_tasks_spread_by_custom_resources(cluster):
    """Tasks needing a resource only peers have must run on the peers."""
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def whoami():
        import time as _t

        from ray_tpu.core.runtime import _get_runtime

        _t.sleep(0.5)  # hold the slot so the burst needs both nodes
        return _get_runtime().store.session  # node-unique session id

    sessions = set(ray_tpu.get([whoami.remote() for _ in range(8)],
                               timeout=90))
    # the driver node has no "worker" resource; with the burst spread over
    # 2 nodes x 2 slots, BOTH peer nodes must have executed tasks
    assert len(sessions) == 2


def test_remote_object_fetch(cluster):
    """A large object produced on a peer node is pulled to the driver."""
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def produce():
        return np.arange(1 << 16, dtype=np.float64)  # 512 KiB, not inline

    arr = ray_tpu.get(produce.remote(), timeout=90)
    np.testing.assert_array_equal(arr, np.arange(1 << 16, dtype=np.float64))


def test_remote_object_as_dependency_across_nodes(cluster):
    """ref produced on node A consumed by a task on node B."""
    cluster.add_node(num_cpus=2, resources={"a": 1})
    cluster.add_node(num_cpus=2, resources={"b": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"a": 1})
    def make():
        return np.ones(1 << 15)  # 256 KiB

    @ray_tpu.remote(resources={"b": 1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(make.remote()), timeout=120) == float(1 << 15)


def test_inline_results_from_remote_node(cluster):
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(20, 22), timeout=90) == 42


def test_remote_actor_roundtrip(cluster):
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=90) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=30) == 6


def test_node_death_retries_task_elsewhere(cluster):
    """Kill a node mid-task: retryable tasks re-run on a surviving node."""
    victim = cluster.add_node(num_cpus=2, resources={"pool": 4})
    cluster.add_node(num_cpus=2, resources={"pool": 4})
    _init(cluster)

    @ray_tpu.remote(resources={"pool": 1}, max_retries=2)
    def slow(i):
        import os
        import time as _t

        _t.sleep(3.0)
        return (i, os.getpid())

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(1.0)  # let tasks start on both nodes
    cluster.kill_node(victim)
    results = ray_tpu.get(refs, timeout=120)
    assert sorted(r[0] for r in results) == [0, 1, 2, 3]


def test_node_death_fails_nonretryable(cluster):
    victim = cluster.add_node(num_cpus=2, resources={"solo": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"solo": 1}, max_retries=0)
    def stuck():
        import time as _t

        _t.sleep(30)

    ref = stuck.remote()
    time.sleep(1.5)
    cluster.kill_node(victim)
    from ray_tpu.core.exceptions import WorkerCrashedError

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)
