"""Multi-node core: scheduling spread, object transfer, node failover.

Reference test pattern: ``python/ray/cluster_utils.py:135`` — extra node
daemons as separate processes on one machine.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster import Cluster

from conftest import poll_until


@pytest.fixture
def cluster():
    c = Cluster()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _init(c, **kw):
    return ray_tpu.init(address=c.address, cluster_authkey=c.authkey,
                        num_cpus=2, **kw)


def test_cluster_boots_and_lists_nodes(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _init(cluster)
    def _alive():
        nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
        return nodes if len(nodes) >= 3 else None

    nodes = poll_until(_alive, timeout=20, desc="head + 2 daemons alive")
    assert len(nodes) >= 3  # head + 2 daemons

    # host utilization samples ride heartbeats into the node table
    # (reporter-module role) — wait one heartbeat period for the first
    with_stats = poll_until(
        lambda: [n for n in ray_tpu.nodes()
                 if (n.get("stats") or {}).get("mem_total")],
        timeout=20, interval=0.5, desc="host stats on a node")
    assert with_stats, "no node ever reported host stats"


def test_tasks_spread_by_custom_resources(cluster):
    """Tasks needing a resource only peers have must run on the peers."""
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    cluster.add_node(num_cpus=2, resources={"worker": 2})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def whoami():
        import time as _t

        from ray_tpu.core.runtime import _get_runtime

        _t.sleep(0.5)  # hold the slot so the burst needs both nodes
        return _get_runtime().store.session  # node-unique session id

    sessions = set(ray_tpu.get([whoami.remote() for _ in range(8)],
                               timeout=90))
    # the driver node has no "worker" resource; with the burst spread over
    # 2 nodes x 2 slots, BOTH peer nodes must have executed tasks
    assert len(sessions) == 2


def test_remote_object_fetch(cluster):
    """A large object produced on a peer node is pulled to the driver."""
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def produce():
        return np.arange(1 << 16, dtype=np.float64)  # 512 KiB, not inline

    arr = ray_tpu.get(produce.remote(), timeout=90)
    np.testing.assert_array_equal(arr, np.arange(1 << 16, dtype=np.float64))


def test_remote_object_as_dependency_across_nodes(cluster):
    """ref produced on node A consumed by a task on node B."""
    cluster.add_node(num_cpus=2, resources={"a": 1})
    cluster.add_node(num_cpus=2, resources={"b": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"a": 1})
    def make():
        return np.ones(1 << 15)  # 256 KiB

    @ray_tpu.remote(resources={"b": 1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(make.remote()), timeout=120) == float(1 << 15)


def test_inline_results_from_remote_node(cluster):
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(20, 22), timeout=90) == 42


def test_remote_actor_roundtrip(cluster):
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=90) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=30) == 6


def test_node_death_retries_task_elsewhere(cluster):
    """Kill a node mid-task: retryable tasks re-run on a surviving node."""
    victim = cluster.add_node(num_cpus=2, resources={"pool": 4})
    cluster.add_node(num_cpus=2, resources={"pool": 4})
    _init(cluster)

    @ray_tpu.remote(resources={"pool": 1}, max_retries=2)
    def slow(i):
        import os
        import time as _t

        _t.sleep(3.0)
        return (i, os.getpid())

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(1.0)  # let tasks start on both nodes
    cluster.kill_node(victim)
    results = ray_tpu.get(refs, timeout=120)
    assert sorted(r[0] for r in results) == [0, 1, 2, 3]


def test_node_death_fails_nonretryable(cluster):
    victim = cluster.add_node(num_cpus=2, resources={"solo": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"solo": 1}, max_retries=0)
    def stuck():
        import time as _t

        _t.sleep(30)

    ref = stuck.remote()
    time.sleep(1.5)
    cluster.kill_node(victim)
    from ray_tpu.core.exceptions import WorkerCrashedError

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)


def test_node_affinity_strategy(cluster):
    """Hard node affinity pins tasks to the named node; affinity to a dead
    node fails (reference NodeAffinitySchedulingStrategy)."""
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _init(cluster)
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    nodes = cluster.list_nodes()
    daemons = [n for n in nodes if not n["is_head"]]
    target = daemons[0]["node_id"]

    @ray_tpu.remote
    def where():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    strat = NodeAffinitySchedulingStrategy(node_id=target.hex())
    sessions = set(ray_tpu.get(
        [where.options(scheduling_strategy=strat).remote()
         for _ in range(4)], timeout=90))
    assert len(sessions) == 1  # all pinned to one node

    # hard affinity to a bogus node fails fast
    from ray_tpu.core.exceptions import WorkerCrashedError

    bad = NodeAffinitySchedulingStrategy(node_id=(b"\x99" * 16).hex())
    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(where.options(scheduling_strategy=bad).remote(),
                    timeout=60)


def test_spread_strategy(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _init(cluster)

    @ray_tpu.remote
    def where():
        import time as _t

        from ray_tpu.core.runtime import _get_runtime

        _t.sleep(0.2)
        return _get_runtime().store.session

    sessions = set(ray_tpu.get(
        [where.options(scheduling_strategy="SPREAD").remote()
         for _ in range(9)], timeout=90))
    # head + 2 daemons in the round-robin: all three must appear
    assert len(sessions) == 3, sessions


def test_random_strategy(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _init(cluster)

    @ray_tpu.remote
    def where():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    sessions = set(ray_tpu.get(
        [where.options(scheduling_strategy="RANDOM").remote()
         for _ in range(12)], timeout=90))
    # uniform over 3 feasible nodes: all-12-on-one-node has p ~ 2e-5
    assert len(sessions) >= 2, sessions


def test_gcs_restart_fault_tolerance(tmp_path):
    """Kill + restart the GCS: durable tables (KV, named actors) survive
    via the snapshot; node daemons re-register via heartbeat NACK; new
    work schedules (reference GCS fault tolerance,
    gcs/store_client/redis_store_client.h role)."""
    c = Cluster(gcs_snapshot=str(tmp_path / "gcs.snap"))
    try:
        c.add_node(num_cpus=2, resources={"worker": 2})
        rt = _init(c)

        @ray_tpu.remote(resources={"worker": 1})
        def ping():
            return "pong"

        assert ray_tpu.get(ping.remote(), timeout=60) == "pong"
        rt.kv_op("put", "durable-key", b"survives")
        time.sleep(1.5)  # let the snapshot loop persist

        c.restart_gcs()

        # KV survived the restart
        val = poll_until(lambda: rt.kv_op("get", "durable-key"),
                         timeout=30, interval=0.5,
                         desc="durable KV after GCS restart")
        assert val == b"survives"

        # nodes re-registered: remote work schedules again
        ok = poll_until(
            lambda: ray_tpu.get(ping.remote(), timeout=20) == "pong",
            timeout=60, interval=0.5,
            desc="remote task schedules after GCS restart")
        assert ok, "remote task did not schedule after GCS restart"

        # the daemon's re-registration left a gcs_restart lifecycle
        # event (warning severity) in the head store — the event plane's
        # record that cluster state was rebuilt from the snapshot
        from ray_tpu.util import state

        restarts = poll_until(
            lambda: [e for e in state.list_events(limit=10000)
                     if e["name"] == "gcs_restart"],
            timeout=90, interval=0.5, desc="gcs_restart event collected")
        assert restarts[0]["severity"] == "warning"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_nested_task_spills_between_daemons(cluster):
    """A task on daemon A submits a nested task only daemon B can run:
    the daemon spills it instead of queueing forever (reference raylet
    spillback role)."""
    cluster.add_node(num_cpus=2, resources={"a": 1})
    cluster.add_node(num_cpus=2, resources={"b": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"b": 1})
    def inner():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    @ray_tpu.remote(resources={"a": 1})
    def outer():
        import ray_tpu as r
        from ray_tpu.core.runtime import _get_runtime

        inner_session = r.get(inner.remote(), timeout=90)
        return inner_session, _get_runtime().store.session

    inner_session, outer_session = ray_tpu.get(outer.remote(), timeout=120)
    assert inner_session != outer_session  # ran on the OTHER daemon


def test_named_actor_visible_across_nodes(cluster):
    """A named actor created on a daemon resolves from the driver via the
    global registry, and calls route to the hosting node."""
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)

    @ray_tpu.remote(resources={"worker": 1}, name="kvstore")
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    kv = KV.remote()
    assert ray_tpu.get(kv.put.remote("a", 1), timeout=90)
    # resolve BY NAME from the driver: global registry lookup
    handle = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(handle.get.remote("a"), timeout=60) == 1


def test_pg_strict_spread_across_nodes(cluster):
    """A STRICT_SPREAD group must land its bundles on DISTINCT nodes;
    bundle-pinned tasks run where their bundle was reserved (reference
    2-phase bundle reservation, gcs_placement_group_scheduler.h:111)."""
    cluster.add_node(num_cpus=2, resources={"slot": 1})
    cluster.add_node(num_cpus=2, resources={"slot": 1})
    cluster.add_node(num_cpus=2, resources={"slot": 1})
    _init(cluster)
    _wait_nodes(4)
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    pg = placement_group([{"CPU": 1, "slot": 1}] * 3,
                         strategy="STRICT_SPREAD")
    # wait() verifies every bundle holds an assignment (not a stub True)
    assert pg.wait(timeout_seconds=30)
    from ray_tpu.core.ids import PlacementGroupID
    from ray_tpu.util.placement_group import PlacementGroup

    ghost = PlacementGroup(PlacementGroupID.from_random(),
                           [{"CPU": 1}], "PACK")
    assert not ghost.wait(timeout_seconds=0.5)  # unknown group: False

    @ray_tpu.remote
    def where():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    refs = [
        where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(3)
    ]
    sessions = ray_tpu.get(refs, timeout=120)
    assert len(set(sessions)) == 3  # three distinct daemons


def test_pg_infeasible_is_atomic(cluster):
    """An infeasible group reserves NOTHING: creation raises and a
    subsequently feasible group still fits (all-or-nothing prepare)."""
    cluster.add_node(num_cpus=2)
    _init(cluster)
    _wait_nodes(2)
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    with pytest.raises(ValueError):
        # 4 bundles across 2 nodes cannot STRICT_SPREAD
        placement_group([{"CPU": 1}] * 4, strategy="STRICT_SPREAD")
    # nothing leaked: a group consuming BOTH nodes' full CPUs succeeds
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    remove_placement_group(pg)


def test_pg_slice_pack_atomic_and_schedulable(cluster):
    """SLICE_PACK (one bundle per slice host): atomic reservation over
    hosts carrying the slice resource; any-bundle tasks fan out."""
    cluster.add_node(num_cpus=2, resources={"tpu-host": 1})
    cluster.add_node(num_cpus=2, resources={"tpu-host": 1})
    _init(cluster)
    _wait_nodes(3)
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    with pytest.raises(ValueError):
        placement_group([{"tpu-host": 1}] * 3, strategy="SLICE_PACK")
    pg = placement_group([{"CPU": 1, "tpu-host": 1}] * 2,
                         strategy="SLICE_PACK")

    @ray_tpu.remote
    def host():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    refs = [
        host.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(2)
    ]
    assert len(set(ray_tpu.get(refs, timeout=120))) == 2


def test_pg_node_death_releases_and_reschedules(cluster):
    """Killing a node releases its bundles; the group reschedules them on
    a surviving node and parked bundle-pinned work completes there."""
    victim = cluster.add_node(num_cpus=2, resources={"slot": 1})
    _init(cluster)
    _wait_nodes(2)
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    # bundle 0 must be on the daemon? PACK picks the roomiest node --
    # force it by reserving a slot resource only the daemon has
    from ray_tpu.util.placement_group import remove_placement_group

    remove_placement_group(pg)
    pg = placement_group([{"CPU": 1, "slot": 1}], strategy="PACK")

    @ray_tpu.remote
    def where():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    strat = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    on_daemon = ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                            timeout=90)

    # a second daemon with the slot resource joins, then the first dies
    cluster.add_node(num_cpus=2, resources={"slot": 1})
    _wait_nodes(3)
    cluster.kill_node(victim)

    # the group reschedules onto the survivor; pinned work completes there
    deadline = time.monotonic() + 90
    landed = None
    while time.monotonic() < deadline:
        try:
            landed = ray_tpu.get(
                where.options(scheduling_strategy=strat).remote(),
                timeout=30)
            break
        except Exception:
            time.sleep(0.5)
    assert landed is not None and landed != on_daemon


def _wait_nodes(n, timeout=15):
    # poll_until retries transient GCS connection drops under suite load
    poll_until(
        lambda: len([x for x in ray_tpu.nodes() if x["Alive"]]) >= n,
        timeout=timeout, desc=f"cluster reaches {n} nodes")


def test_jax_trainer_gang_schedules_across_daemons(cluster, tmp_path):
    """JaxTrainer with a 2-'host' ScalingConfig trains through a
    STRICT_SPREAD placement group: one worker lands on each daemon, the
    jax.distributed rendezvous spans both processes (VERDICT r3 #1 done
    criterion)."""
    cluster.add_node(num_cpus=2, resources={"host": 1})
    cluster.add_node(num_cpus=2, resources={"host": 1})
    _init(cluster)
    _wait_nodes(3)
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        import jax

        import ray_tpu.train as train
        from ray_tpu.core.runtime import _get_runtime

        ctx = train.get_context()
        train.report({
            "rank": ctx.world_rank,
            "world": jax.process_count(),
            "session": _get_runtime().store.session,
        })

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2,
            resources_per_worker={"CPU": 1, "host": 1},
            placement_strategy="STRICT_SPREAD",
        ),
        run_config=RunConfig(name="gang", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["world"] == 2  # jax.distributed spans both procs


def test_borrowed_ref_survives_owner_drop(cluster):
    """A ref passed (nested) to an actor on another node stays alive after
    the owner drops every local reference: the borrower's node pin keeps
    the directory entry and segment (reference reference_count.h:61
    borrowing semantics)."""
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"worker": 1})
    class Holder:
        def hold(self, box):
            self.box = box
            return True

        def fetch(self):
            import ray_tpu as r

            return r.get(self.box[0], timeout=60)

    h = Holder.remote()
    ref = ray_tpu.put(np.arange(1 << 14, dtype=np.float64))  # 128 KiB
    assert ray_tpu.get(h.hold.remote([ref]), timeout=90)
    del ref
    import gc

    gc.collect()
    time.sleep(1.5)  # owner unpin propagates; borrower pin must hold
    out = ray_tpu.get(h.fetch.remote(), timeout=90)
    np.testing.assert_array_equal(out, np.arange(1 << 14, dtype=np.float64))


def test_gcs_directory_bounded_with_live_refs(monkeypatch, tmp_path):
    """Churn far past the directory cap while long-lived refs stay valid:
    pinned entries are never evicted/freed; unpinned ones are reclaimed
    (VERDICT r3 #2 done criterion)."""
    monkeypatch.setenv("RTPU_GCS_MAX_OBJECTS", "200")
    monkeypatch.setenv("RTPU_GCS_EVICT_MIN_AGE_S", "0")
    c = Cluster()
    try:
        _init(c)
        rng = np.random.default_rng(0)
        held = [ray_tpu.put(rng.standard_normal(4)) for _ in range(100)]
        expect = ray_tpu.get(held, timeout=60)
        # 2x the cap of short-lived objects: refs dropped immediately
        for i in range(400):
            ray_tpu.put(np.float64(i))
        import gc

        gc.collect()
        time.sleep(1.0)
        got = ray_tpu.get(held, timeout=60)
        for a, b in zip(got, expect):
            np.testing.assert_array_equal(a, b)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_cancel_routes_to_remote_node(cluster, tmp_path):
    """Cancelling a ref whose task was forwarded to a peer node must stop
    the REMOTE worker (ADVICE r2 medium: the fallback used to mark the
    object cancelled while the task kept running on the peer)."""
    cluster.add_node(num_cpus=2, resources={"worker": 1})
    _init(cluster)
    _wait_nodes(2)
    marker = str(tmp_path / "remote-spinning")

    @ray_tpu.remote(resources={"worker": 1})
    def spin(path):
        open(path, "w").close()
        import time as _t

        t0 = _t.monotonic()
        while _t.monotonic() - t0 < 60:
            pass
        return "finished"

    import os

    ref = spin.remote(marker)
    deadline = time.monotonic() + 60
    while not os.path.exists(marker):
        assert time.monotonic() < deadline, "remote task never started"
        time.sleep(0.05)
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    from ray_tpu.core.exceptions import TaskCancelledError

    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=45)
    assert time.monotonic() - t0 < 30, "remote cancel did not interrupt"


def _vm_hwm_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmHWM:"):
                return int(line.split()[1])
    return 0


def test_chunked_transfer_bounded_memory(cluster):
    """A large object moves node-to-node in chunks (reference
    push_manager.h/pull_manager.h roles): neither daemon materializes the
    whole blob — peak RSS grows by ~the object (shm pages touched), never
    by 2-3x of it (whole-blob pickle/recv buffers)."""
    src = cluster.add_node(num_cpus=2, resources={"src": 1})
    dst = cluster.add_node(num_cpus=2, resources={"dst": 1})
    _init(cluster)
    _wait_nodes(3)

    @ray_tpu.remote(resources={"src": 1})
    def produce(n):
        return np.full(n, 7.0)

    @ray_tpu.remote(resources={"dst": 1})
    def consume(x):
        return float(x[0]), float(x[-1]), x.nbytes

    # warm: spawn workers + peer connections + a small transfer first so
    # baseline HWM includes all fixed costs
    assert ray_tpu.get(consume.remote(produce.remote(1 << 10)),
                       timeout=120)[2] == (1 << 10) * 8

    src_pid = cluster._node_procs[src].pid
    dst_pid = cluster._node_procs[dst].pid
    base_src = _vm_hwm_kb(src_pid)
    base_dst = _vm_hwm_kb(dst_pid)

    n = (256 << 20) // 8  # 256 MiB of float64
    lo, hi, nbytes = ray_tpu.get(consume.remote(produce.remote(n)),
                                 timeout=300)
    assert (lo, hi) == (7.0, 7.0)
    assert nbytes == 256 << 20

    size_kb = (256 << 20) // 1024
    # 0.75x slack: the bound catches a whole-blob (2-3x) path, not page
    # accounting jitter — the suite under load once missed 0.5x by 0.4%
    slack_kb = (192 << 20) // 1024
    d_src = _vm_hwm_kb(src_pid) - base_src
    d_dst = _vm_hwm_kb(dst_pid) - base_dst
    # serving/receiving touches the object's shm pages once (~size) plus
    # chunk-size scratch; a whole-blob path costs 2-3x size in anon RAM
    assert d_src < size_kb + slack_kb, f"src daemon ballooned: {d_src} kB"
    assert d_dst < size_kb + slack_kb, f"dst daemon ballooned: {d_dst} kB"


def test_cross_node_fetch_of_spilled_object(monkeypatch):
    """ISSUE r6 / VERDICT missing #4: node A fills past spill_threshold,
    and an object that lives only in A's spill DIRECTORY is still pullable
    from node B — chunked reads come off the spill file, and once A has
    headroom the serve path RESTORES the object back into shm (reference
    ``local_object_manager.h:110`` restore-for-remote-pull)."""
    # tiny store on every node: two 3 MB puts fit (6 MB of segments), the
    # 6 MB one tips past the 7 MB threshold and spills — and 7 MB leaves
    # restore headroom once the residents are freed. Env must be set
    # BEFORE the daemons boot (cluster._env snapshots it).
    monkeypatch.setenv("RTPU_NATIVE_STORE", "0")
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(7 << 20))
    monkeypatch.setenv("RTPU_STORE_PREFAULT_BYTES", "0")
    c = Cluster()
    try:
        c.add_node(num_cpus=2, resources={"spiller": 2})
        _init(c)
        _wait_nodes(2)

        @ray_tpu.remote(resources={"spiller": 1})
        def produce():
            import ray_tpu as rt
            from ray_tpu.core.runtime import _get_runtime

            refs = [rt.put(np.full((3 << 20) // 8, float(i)))
                    for i in range(2)]                      # fill shm
            refs.append(rt.put(np.full((6 << 20) // 8, 7.0)))  # spills
            store = _get_runtime().store
            spilled = [store.contains_spilled(r.id) for r in refs]
            return refs, spilled

        refs, spilled = ray_tpu.get(produce.remote(), timeout=120)
        assert spilled == [False, False, True], spilled

        @ray_tpu.remote(resources={"spiller": 1})
        def probe(oid_hex):
            from ray_tpu.core.ids import ObjectID
            from ray_tpu.core.runtime import _get_runtime

            store = _get_runtime().store
            oid = ObjectID(bytes.fromhex(oid_hex))
            return store.contains_spilled(oid), store.contains(oid)

        # free the shm residents: A gains headroom, so serving the pull
        # below can restore the spilled object into shm first. The freed
        # publication is async — wait until A actually dropped them
        # (restore's headroom gate reads A's real shm usage).
        ray_tpu.free(refs[:2])
        poll_until(
            lambda: not ray_tpu.get(probe.remote(refs[0].hex()),
                                    timeout=60)[1],
            timeout=30, interval=0.5, desc="freed residents dropped on A")

        # node B (the driver) pulls the object that exists ONLY in A's
        # spill file — 6 MB > pull_chunk_bytes, so this is a chunked read
        # straight off the spill file
        big = ray_tpu.get(refs[2], timeout=120)
        assert big.nbytes == 6 << 20
        assert float(big[0]) == float(big[-1]) == 7.0

        # the serve path restored it: gone from the spill dir, still
        # readable on A (freed-headroom publication is async — poll)
        def _restored():
            sp, present = ray_tpu.get(probe.remote(refs[2].hex()),
                                      timeout=60)
            return (sp, present) if not sp else None

        still_spilled, present = poll_until(
            _restored, timeout=30, interval=0.5,
            desc="spilled object restored on A")
        assert present
        assert not still_spilled, "spilled object was never restored"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_cross_node_streaming_backpressure(cluster):
    """Consumer acks relay to the node running the producer: a forwarded
    backpressured generator paces to the consumer instead of parking
    forever (or streaming unthrottled, round 2's fallback)."""
    cluster.add_node(num_cpus=2, resources={"peer": 2})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"peer": 1})
    def warm():
        return None

    ray_tpu.get(warm.remote(), timeout=90)

    @ray_tpu.remote(resources={"peer": 1}, num_returns="streaming",
                    _generator_backpressure_num_objects=2)
    def fast_gen():
        for i in range(6):
            yield (i, time.monotonic())

    g = fast_gen.remote()
    stamps = []
    for ref in g:
        stamps.append(ray_tpu.get(ref, timeout=90))
        time.sleep(0.5)  # slow consumer
    assert [i for i, _ in stamps] == list(range(6))
    t = [ts for _, ts in stamps]
    spread = t[5] - t[0]
    assert spread > 1.0, f"producer ran ahead of backpressure: {spread:.2f}s"


def test_locality_aware_scheduling(cluster):
    """A task whose big arg lives on a peer schedules on that peer even
    though the head has free CPUs: ship the task to the data (reference
    hybrid_scheduling_policy.h:50 locality scoring; VERDICT r3 #6 done
    criterion)."""
    cluster.add_node(num_cpus=2, resources={"b": 2})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"b": 1})
    def whoami():
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().store.session

    b_session = ray_tpu.get(whoami.remote(), timeout=90)

    @ray_tpu.remote(resources={"b": 1})
    def produce():
        return np.zeros((50 << 20) // 8)  # 50 MB, lives on daemon b

    ref = produce.remote()
    # wait for the DIRECTORY to know the location — without get()ing the
    # object here (that would copy it to the head and erase the signal)
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            st = rt.cluster.gcs.call("obj_state", ref.id.binary(),
                                     timeout=10)
        except (ConnectionError, TimeoutError, OSError):
            st = None  # transient drop under suite load; poll again
        if st is not None and st["status"] == "READY":
            break
        time.sleep(0.2)
    else:
        raise AssertionError("produce() never completed")

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        from ray_tpu.core.runtime import _get_runtime

        return float(x[0]), _get_runtime().store.session

    val, sess = ray_tpu.get(consume.remote(ref), timeout=120)
    assert val == 0.0
    assert sess == b_session, "task did not follow its 50MB dependency"


def test_stream_backpressure_consumer_on_third_node(cluster):
    """Generator created on the head, producer forwarded to node B,
    consumed by a task on node C: acks route C -> owner(head) -> B, so
    the producer paces instead of parking 300s (review r3 finding)."""
    cluster.add_node(num_cpus=2, resources={"prod": 1})
    cluster.add_node(num_cpus=2, resources={"cons": 1})
    _init(cluster)
    _wait_nodes(3)

    @ray_tpu.remote(resources={"prod": 1})
    def warm_p():
        return None

    @ray_tpu.remote(resources={"cons": 1})
    def warm_c():
        return None

    ray_tpu.get([warm_p.remote(), warm_c.remote()], timeout=120)

    @ray_tpu.remote(resources={"prod": 1}, num_returns="streaming",
                    _generator_backpressure_num_objects=2)
    def gen():
        for i in range(6):
            yield (i, time.monotonic())

    @ray_tpu.remote(resources={"cons": 1})
    def consume(g):
        out = []
        for ref in g:
            out.append(ray_tpu.get(ref, timeout=60))
            time.sleep(0.5)  # slow consumer on node C
        return out

    stamps = ray_tpu.get(consume.remote(gen.remote()), timeout=180)
    assert [i for i, _ in stamps] == list(range(6))
    spread = stamps[5][1] - stamps[0][1]
    assert spread > 1.0, f"producer ran ahead: {spread:.2f}s"


def test_task_events_ship_to_gcs_cluster_wide(cluster):
    """Task events from EVERY node land in the GCS store: the state API
    lists tasks that ran on peer daemons too (reference TaskEventBuffer ->
    GcsTaskManager pipeline; VERDICT missing #8)."""
    cluster.add_node(num_cpus=2, resources={"peer": 2})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"peer": 1})
    def remote_side():
        return 1

    @ray_tpu.remote(num_cpus=1)
    def local_side():
        return 2

    assert ray_tpu.get([remote_side.remote() for _ in range(3)]
                       + [local_side.remote()], timeout=120) == [1, 1, 1, 2]

    from conftest import poll_until
    from ray_tpu.util.state import list_tasks, summarize_tasks

    def _names():  # events flush on the heartbeat; polls retry transient
        names = {}
        for t in list_tasks():
            names.setdefault(t["name"], set()).add(t["node"])
        ok = (len(names.get("remote_side", ())) >= 1
              and len(names.get("local_side", ())) >= 1)
        return names if ok else None

    names = poll_until(_names, timeout=20, interval=0.5,
                       desc="task events from both nodes in the GCS")
    assert "remote_side" in names and "local_side" in names
    # the two task kinds executed on DIFFERENT nodes
    assert names["remote_side"] != names["local_side"]
    assert summarize_tasks()["remote_side"]["FINISHED"] >= 3


def test_metrics_federation_across_nodes(cluster, monkeypatch):
    """ISSUE 3 acceptance: the head /metrics endpoint exposes samples
    originating from >= 2 distinct worker processes AND >= 2 cluster
    nodes, each carrying node_id/worker_id labels — scraped live over
    HTTP. The full pipeline: worker registries push deltas over the
    control pipe; node registries (plus their workers') ride the GCS
    heartbeat; the head pulls peers' at scrape time."""
    import re
    import urllib.request

    from conftest import poll_until

    monkeypatch.setenv("RTPU_METRICS_PUSH_INTERVAL_S", "0.2")
    cluster.add_node(num_cpus=2, resources={"peer": 2})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"peer": 1})
    def remote_side(i):
        time.sleep(0.2)
        return i

    @ray_tpu.remote(num_cpus=1)
    def local_side(i):
        time.sleep(0.2)
        return i

    # concurrency forces >= 2 workers on the head AND on the daemon
    out = ray_tpu.get([remote_side.remote(i) for i in range(4)]
                      + [local_side.remote(i) for i in range(4)],
                      timeout=120)
    assert sorted(out) == sorted(list(range(4)) * 2)

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    url = f"http://127.0.0.1:{dash.port}/metrics"
    try:
        def scrape():
            txt = urllib.request.urlopen(url, timeout=5).read().decode()
            wids, nids = set(), set()
            for m in re.finditer(r'rtpu_worker_tasks_total\{([^}]*)\}',
                                 txt):
                tags = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
                if tags.get("component") != "worker":
                    continue
                wids.add(tags.get("worker_id"))
                nids.add(tags.get("node_id"))
            wids.discard(None)
            nids.discard(None)
            return txt if (len(wids) >= 2 and len(nids) >= 2) else None

        # worker pushes (0.2s) -> daemon heartbeat metrics (~2s) -> GCS
        # -> head scrape; generous margin for the 2-vCPU box
        txt = poll_until(scrape, timeout=60, interval=0.5,
                         desc=">=2 workers and >=2 nodes on head /metrics")
    finally:
        stop_dashboard()

    # node-level (raylet/driver) registries federate too, with node ids
    assert re.search(r'component="raylet"', txt)
    # and phase histograms from the daemon's own flight recorder arrive
    # labeled with its node id
    assert re.search(
        r'rtpu_task_phase_seconds_count\{[^}]*node_id="\w+"', txt)


def test_core_runtime_metrics_from_all_layers_on_head(cluster,
                                                      monkeypatch):
    """ISSUE 4 acceptance: the head /metrics shows BUILT-IN core-runtime
    metrics from >= 2 nodes (scheduler + object store from the head,
    unlabeled, AND from the daemon, node_id-labeled) plus the GCS
    server's own instrumentation (component="gcs"): per-method RPC
    counters/latency, heartbeat-gap histogram, table sizes."""
    import re
    import urllib.request

    from conftest import poll_until

    monkeypatch.setenv("RTPU_METRICS_PUSH_INTERVAL_S", "0.2")
    cluster.add_node(num_cpus=2, resources={"peer": 2})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"peer": 1})
    def remote_side(i):
        return np.zeros(50_000), i  # big enough to hit the store

    @ray_tpu.remote(num_cpus=1)
    def local_side(i):
        return np.zeros(50_000), i

    out = ray_tpu.get([remote_side.remote(i) for i in range(3)]
                      + [local_side.remote(i) for i in range(3)],
                      timeout=120)
    assert sorted(x[1] for x in out) == [0, 0, 1, 1, 2, 2]

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    url = f"http://127.0.0.1:{dash.port}/metrics"
    try:
        def scrape():
            txt = urllib.request.urlopen(url, timeout=5).read().decode()
            ok = (
                # scheduler: head (unlabeled) + daemon (node-labeled)
                re.search(r"^rtpu_scheduler_tasks_dispatched_total \d",
                          txt, re.M)
                and re.search(r'rtpu_scheduler_tasks_dispatched_total\{'
                              r'[^}]*node_id="\w+"', txt)
                # object store: both origins again
                and re.search(r"^rtpu_object_store_bytes_used \d",
                              txt, re.M)
                and re.search(r'rtpu_object_store_bytes_used\{'
                              r'[^}]*node_id="\w+"', txt)
                # GCS process instrumentation arrives via metrics_get
                and re.search(r'rtpu_gcs_rpc_total\{[^}]*'
                              r'component="gcs"[^}]*'
                              r'method="node_heartbeat"', txt)
                and re.search(r'rtpu_gcs_heartbeat_gap_seconds_count\{'
                              r'[^}]*component="gcs"', txt)
                and re.search(r'rtpu_gcs_table_size\{[^}]*'
                              r'table="objects"', txt)
            )
            return txt if ok else None

        # worker pushes (0.2s) -> daemon heartbeat (~2s) -> GCS -> head
        txt = poll_until(scrape, timeout=60, interval=0.5,
                         desc="scheduler/store/GCS built-ins on head "
                              "/metrics")
    finally:
        stop_dashboard()

    # spillback decisions surfaced with a reason label
    assert re.search(
        r'rtpu_cluster_tasks_forwarded_total\{[^}]*reason="\w+"', txt)
    # the GCS's state-lock contention accounting federates too
    assert re.search(r'rtpu_lock_acquisitions\{[^}]*component="gcs"'
                     r'[^}]*lock="gcs.state"', txt) or \
        re.search(r'rtpu_lock_acquisitions\{[^}]*lock="gcs.state"', txt)


def test_refs_nested_in_results_survive_producer_exit(monkeypatch):
    """A ref nested in a task's RETURN value is pinned by the owner against
    the return object's lifetime (advisor r3): after the producing worker
    exits and its local refs are GC'd, a consumer that deserializes the
    result well past the free grace must still fetch the inner object."""
    monkeypatch.setenv("RTPU_GCS_FREE_GRACE_S", "1.0")
    c = Cluster()
    try:
        c.add_node(num_cpus=2)
        ray_tpu.init(address=c.address, cluster_authkey=c.authkey,
                     num_cpus=2)

        @ray_tpu.remote
        def produce():
            inner = ray_tpu.put(np.arange(30_000, dtype=np.float64))
            return {"inner": inner}

        out_ref = produce.remote()
        # wait for completion WITHOUT deserializing (deserializing would
        # create a local borrow pin and mask the bug)
        ready, _ = ray_tpu.wait([out_ref], num_returns=1, timeout=90)
        assert ready
        time.sleep(4.0)  # > free grace + sweep tick: unpinned would sweep
        out = ray_tpu.get(out_ref, timeout=30)
        inner_val = ray_tpu.get(out["inner"], timeout=30)
        np.testing.assert_array_equal(
            inner_val, np.arange(30_000, dtype=np.float64))
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_node_label_scheduling(cluster):
    """NodeLabelSchedulingStrategy routes to label-matching nodes; a task
    with unsatisfiable hard predicates fails loudly (reference
    node_label_scheduling_policy.h role)."""
    from ray_tpu.util.scheduling_strategies import (
        DoesNotExist, In, NodeLabelSchedulingStrategy)

    cluster.add_node(num_cpus=2, labels={"tpu-generation": "v5e"})
    cluster.add_node(num_cpus=2, labels={"tpu-generation": "v6e"})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        from ray_tpu.core.runtime import _get_runtime

        return dict(_get_runtime().labels)

    v5 = NodeLabelSchedulingStrategy(hard={"tpu-generation": In("v5e")})
    out = ray_tpu.get([whoami.options(scheduling_strategy=v5).remote()
                       for _ in range(3)], timeout=90)
    assert all(o == {"tpu-generation": "v5e"} for o in out), out

    v6 = NodeLabelSchedulingStrategy(hard={"tpu-generation": In("v6e")})
    assert ray_tpu.get(whoami.options(scheduling_strategy=v6).remote(),
                       timeout=90) == {"tpu-generation": "v6e"}

    # soft preference: prefer v6e, but any hard-matching node is allowed
    soft = NodeLabelSchedulingStrategy(
        hard={"tpu-generation": In("v5e", "v6e")},
        soft={"tpu-generation": In("v6e")})
    assert ray_tpu.get(whoami.options(scheduling_strategy=soft).remote(),
                       timeout=90)["tpu-generation"] == "v6e"

    # unlabeled head only: DoesNotExist matches the head node
    head_only = NodeLabelSchedulingStrategy(
        hard={"tpu-generation": DoesNotExist()})
    assert ray_tpu.get(
        whoami.options(scheduling_strategy=head_only).remote(),
        timeout=90) == {}

    # unsatisfiable hard predicate fails fast, not a silent hang
    never = NodeLabelSchedulingStrategy(hard={"tpu-generation": In("v99")})
    with pytest.raises(Exception):
        ray_tpu.get(whoami.options(scheduling_strategy=never).remote(),
                    timeout=30)


def test_broadcast_replicates_via_relay_tree(cluster):
    """Explicit broadcast pushes the object to every node through the
    relay tree (reference PushManager role): all daemons end up holding a
    copy, advertised in the directory."""
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    _init(cluster)
    _wait_nodes(3)

    import ray_tpu.experimental as rexp

    blob = np.random.default_rng(0).standard_normal(1 << 20)  # 8 MiB
    ref = ray_tpu.put(blob)
    n = rexp.broadcast_object(ref)
    assert n == 3

    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()

    def _replicated():
        st = rt.cluster.gcs.call("obj_state", ref.id.binary(), timeout=10)
        # head + 3 daemons hold it once the relay tree finished
        return st if st and len(st.get("locations") or ()) >= 4 else None

    st = poll_until(_replicated, timeout=60, interval=0.3,
                    desc="broadcast replicated to all nodes")
    assert st and len(st["locations"]) >= 4, st
    # broadcast again: everyone already holds it -> no targets
    assert rexp.broadcast_object(ref) == 0


def test_rpc_wire_version_handshake():
    """Versioned wire contract (reference protobuf schema role): matching
    majors connect and carry calls; a major mismatch is refused with a
    clear WireVersionError at connect time."""
    import threading

    from multiprocessing.connection import Client as MpClient
    from multiprocessing.connection import Listener

    from ray_tpu.cluster.rpc import (RpcClient, RpcServer, WIRE_VERSION,
                                     WireVersionError, parse_addr)

    server = RpcServer("127.0.0.1", 0, b"k", lambda m, a, c: ("ok", m, a))
    try:
        # happy path: handshake succeeds, calls flow
        cli = RpcClient(server.addr, b"k")
        assert cli.server_wire_version == WIRE_VERSION
        assert cli.call("ping", 1, timeout=10) == ("ok", "ping", (1,))
        cli.close()

        # server refuses a future-major client with a nack
        conn = MpClient(parse_addr(server.addr), family="AF_INET",
                        authkey=b"k")
        conn.send(("hello", (WIRE_VERSION[0] + 1, 0)))
        assert conn.poll(10)
        reply = conn.recv()
        assert reply[0] == "hello_nack" and "wire major" in reply[2]
        conn.close()
    finally:
        server.close()

    # client raises WireVersionError when the server nacks
    lst = Listener(("127.0.0.1", 0), family="AF_INET", authkey=b"k")

    def fake_server():
        c = lst.accept()
        c.recv()
        c.send(("hello_nack", (9, 0), "wire major 1 != 9"))

    threading.Thread(target=fake_server, daemon=True).start()
    try:
        with pytest.raises(WireVersionError, match="refused"):
            RpcClient(f"127.0.0.1:{lst.address[1]}", b"k")
    finally:
        lst.close()


def test_rpc_handshake_malformed_hello_nacked():
    """('hello', 5) and non-hello first messages get a clean nack — the
    reader thread must not die with an uncaught TypeError (that leaks the
    conn and times the peer out with a misleading error)."""
    from multiprocessing.connection import Client as MpClient

    from ray_tpu.cluster.rpc import RpcServer, parse_addr

    server = RpcServer("127.0.0.1", 0, b"k", lambda m, a, c: None)
    try:
        for bad in (("hello", 5), ("hello", ()), ("req", 1, "x", ())):
            conn = MpClient(parse_addr(server.addr), family="AF_INET",
                            authkey=b"k")
            conn.send(bad)
            assert conn.poll(10)
            assert conn.recv()[0] == "hello_nack"
            conn.close()
    finally:
        server.close()


def test_memory_dump_lists_cluster_objects(cluster):
    """`ray_tpu memory` / GCS obj_list: directory dump with pin counts
    (reference `ray memory` refcount-dump role)."""
    _init(cluster)
    refs = [ray_tpu.put(np.ones(1 << 15)) for _ in range(3)]
    from ray_tpu.cluster.rpc import RpcClient

    cli = RpcClient(cluster.address, cluster.authkey.encode())
    try:
        rows = cli.call("obj_list", 100, timeout=30)
    finally:
        cli.close()
    big = [r for r in rows if (r["size"] or 0) >= (1 << 15) * 8]
    assert len(big) >= 3
    assert all(r["pins"] >= 1 and r["status"] == "READY" for r in big)
    del refs


def test_task_events_dedup_on_cursor_rewind(cluster):
    """A node that re-registers rewinds its event cursor to 0 and reships
    history; the GCS drops events below its per-node high-water mark
    (advisor r3: duplicated task events in the state API)."""
    from ray_tpu.cluster.rpc import RpcClient

    cli = RpcClient(cluster.address, cluster.authkey.encode())
    try:
        nid = b"\x01" * 16
        evs = [{"name": f"t{i}", "ts": i} for i in range(5)]
        assert cli.call("task_events", nid, evs, 0, timeout=10)
        # cursor rewind after re-register: same 5 events again from seq 0,
        # plus 2 genuinely new ones
        evs2 = evs + [{"name": "t5", "ts": 5}, {"name": "t6", "ts": 6}]
        assert cli.call("task_events", nid, evs2, 0, timeout=10)
        got = [e for e in cli.call("task_events_get", 100, timeout=10)
               if e["node"] == nid.hex()[:8]]
        names = [e["name"] for e in got]
        assert names == [f"t{i}" for i in range(7)], names
    finally:
        cli.close()


def test_gcs_sqlite_external_store_fault_tolerance(tmp_path):
    """VERDICT r4 #6 done-criterion: the GCS backed by an EXTERNAL sqlite
    store (redis_store_client.h role) survives kill -9 with named
    actors, KV, and placement groups intact — the store file can live on
    storage that outlives the head node's disk."""
    import os

    db = str(tmp_path / "external" / "gcs.db")
    c = Cluster(gcs_snapshot=f"sqlite://{db}")
    try:
        c.add_node(num_cpus=4, resources={"worker": 4})
        rt = _init(c)

        @ray_tpu.remote(resources={"worker": 1})
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        a = Counter.options(name="survivor", lifetime="detached").remote()
        assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
        rt.kv_op("put", "durable-key", b"sqlite-survives")
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group([{"worker": 1}], strategy="PACK")
        assert pg.wait(timeout_seconds=30)
        time.sleep(1.5)  # let the snapshot loop persist
        assert os.path.exists(db)

        c.restart_gcs()  # kill -9 + fresh process reading the sqlite db

        val = poll_until(lambda: rt.kv_op("get", "durable-key"),
                         timeout=30, interval=0.5,
                         desc="durable KV after sqlite GCS restart")
        assert val == b"sqlite-survives"
        # named actor record survived: resolvable by name again
        deadline = time.monotonic() + 60
        got = None
        while time.monotonic() < deadline:
            try:
                h = ray_tpu.get_actor("survivor")
                got = ray_tpu.get(h.bump.remote(), timeout=20)
                break
            except Exception:
                time.sleep(0.5)
        assert got == 2, got
        # pg record survived the restart (read back from the GCS)
        pgs = poll_until(lambda: rt.cluster.gcs.call("pg_list", timeout=10),
                         timeout=30, interval=0.5,
                         desc="pg records after sqlite GCS restart")
        assert pgs, "placement group records lost after GCS restart"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_sqlite_store_client_unit(tmp_path):
    """Round trip, unchanged-table skip, and corrupt-row tolerance of the
    sqlite StoreClient (no cluster boot needed)."""
    import os
    import sqlite3

    from ray_tpu.cluster.gcs_store import (SqliteStoreClient,
                                           make_store_client)

    db = str(tmp_path / "t.db")
    s = make_store_client(f"sqlite://{db}")
    assert isinstance(s, SqliteStoreClient)
    snap = {"kv": {"ns": {"k": b"v"}}, "functions": {"h": b"blob"},
            "actors": {b"a": {"state": "ALIVE"}},
            "named_actors": {"n": b"a"}, "pgs": {}}
    s.save(snap)
    s.save(snap)  # unchanged: second save is a no-op (hash skip)
    s.close()

    s2 = SqliteStoreClient(db)
    got = s2.load()
    assert got["kv"] == snap["kv"] and got["named_actors"] == {"n": b"a"}
    s2.close()

    # corrupt ONE table row: the rest must still load
    conn = sqlite3.connect(db)
    conn.execute("UPDATE gcs_tables SET payload=? WHERE name='functions'",
                 (b"\x80garbage",))
    conn.commit()
    conn.close()
    s3 = SqliteStoreClient(db)
    got = s3.load()
    assert "functions" not in got and got["kv"] == snap["kv"]
    s3.close()

    # a corrupt/truncated db file must not block boot: it is set aside
    # and a fresh store opens (the file backend boots empty the same way)
    bad = str(tmp_path / "bad.db")
    with open(bad, "wb") as fh:
        fh.write(b"this is not a sqlite file at all")
    s4 = SqliteStoreClient(bad)
    assert s4.load() is None
    assert s4.save(snap) is True
    s4.close()
    assert os.path.exists(bad + ".corrupt")

    # file backend still the default for bare paths
    from ray_tpu.cluster.gcs_store import FileStoreClient

    f = make_store_client(str(tmp_path / "plain.snap"))
    assert isinstance(f, FileStoreClient)
    f.save(snap)
    assert f.load()["kv"] == snap["kv"]
    assert make_store_client(None) is None


def test_trace_spans_cross_processes_and_nodes(cluster):
    """ISSUE 7: one trace id spans >= 3 processes (driver submit ->
    worker execute -> nested submit -> second worker) and >= 2 nodes,
    collected over worker pipe pushes + GCS-heartbeat shipping. Tracing
    is armed MID-SESSION, so the daemon (booted un-armed) must learn via
    the KV/pubsub push and relay to its workers (satellite fix)."""
    from ray_tpu.util import state, tracing

    cluster.add_node(num_cpus=2, resources={"side": 2})
    _init(cluster)
    tracing.enable_tracing()
    try:
        @ray_tpu.remote(resources={"side": 1})
        def traced_inner(x):
            return x + 1

        @ray_tpu.remote(resources={"side": 1})
        def traced_outer():
            return ray_tpu.get(traced_inner.remote(1), timeout=60)

        assert ray_tpu.get(traced_outer.remote(), timeout=90) == 2

        def full_trace():
            # fresh work keeps worker pushes + heartbeats flowing
            try:
                ray_tpu.get(traced_outer.remote(), timeout=90)
                spans = state.list_spans(limit=100_000)
            except ConnectionError:
                return None
            outers = [s for s in spans
                      if s["name"] == "execute::traced_outer"]
            for o in reversed(outers):
                trace = [s for s in spans
                         if s["trace_id"] == o["trace_id"]]
                if not any(s["name"] == "execute::traced_inner"
                           for s in trace):
                    continue
                pids = {(s.get("attributes") or {}).get("process.pid")
                        for s in trace}
                nodes = {s.get("node_id") for s in trace
                         if s.get("node_id")}
                if len(pids - {None}) >= 3 and len(nodes) >= 2:
                    return trace
            return None

        deadline = time.monotonic() + 90
        trace = None
        while time.monotonic() < deadline and trace is None:
            trace = full_trace()
            if trace is None:
                time.sleep(0.5)
        assert trace is not None, \
            "no trace spanning >=3 processes and >=2 nodes arrived"
        # the nested submit happened INSIDE the outer execute
        outer_exec = next(s for s in trace
                          if s["name"] == "execute::traced_outer")
        inner_sub = [s for s in trace
                     if s["name"] == "submit::traced_inner"]
        assert inner_sub
        assert inner_sub[0]["parent_span_id"] == outer_exec["span_id"]
    finally:
        tracing.disable_tracing()
        tracing._reset_for_tests()
        import os as _os
        _os.environ.pop("RTPU_TRACING", None)


def test_profile_merges_nodes_and_pids_with_components(cluster):
    """ISSUE 9 acceptance: one state.profile() merge contains stacks
    from >= 2 nodes and >= 3 pids with correct component labels —
    worker batches over control-pipe pushes, the daemon's own sampler
    window over GCS-heartbeat ProfileStore deltas, the head's locally.
    Armed MID-SESSION, so the daemon (booted un-armed) must learn via
    the KV/pubsub push and relay to its workers."""
    from conftest import poll_until
    from ray_tpu.util import profiling, state

    cluster.add_node(num_cpus=2, resources={"side": 2})
    _init(cluster)
    _wait_nodes(2)
    profiling.enable_profiling()
    try:
        @ray_tpu.remote(resources={"side": 1})
        def spin_side(sec):
            t = time.monotonic() + sec
            x = 0
            while time.monotonic() < t:
                x += 1
            return x

        @ray_tpu.remote(num_cpus=1)
        def spin_local(sec):
            t = time.monotonic() + sec
            x = 0
            while time.monotonic() < t:
                x += 1
            return x

        # warm both nodes' workers so arming reached them
        ray_tpu.get([spin_side.remote(0.05), spin_local.remote(0.05)],
                    timeout=120)

        def merged_wide_enough():
            # fresh short spins keep worker pushes + heartbeats flowing
            ray_tpu.get([spin_side.remote(0.4), spin_local.remote(0.4)],
                        timeout=120)
            prof = state.profile()
            procs = prof["processes"]
            nodes = {p["node_id"] for p in procs.values()}
            pids = {(p["node_id"], p["pid"]) for p in procs.values()}
            comps = {p["component"] for p in procs.values()}
            top_w = prof["top_self_by_component"].get("worker", [])
            if len(nodes) >= 2 and len(pids) >= 3 \
                    and {"driver", "worker", "raylet"} <= comps \
                    and any("spin_" in r["function"] for r in top_w):
                return prof
            return None

        prof = poll_until(merged_wide_enough, timeout=90, interval=0.5,
                          desc="profile merge spanning >=2 nodes, "
                               ">=3 pids, driver+worker components")
        procs = prof["processes"]
        # component labels are correct per origin: worker batches carry
        # worker@, the daemon's own sampler reports raylet@, the head
        # driver@ — and every process row carries actual samples
        for key, p in procs.items():
            assert key.startswith(f"{p['component']}@")
            assert p["samples"] + p["idle_samples"] > 0
        assert any(p["component"] == "raylet" for p in procs.values()), \
            "daemon's own sampler batches never arrived via heartbeat"
    finally:
        profiling.disable_profiling()
        profiling._reset_for_tests()
        import os as _os
        _os.environ.pop("RTPU_PROFILING", None)


# ---------------------------------------------------------------------------
# event plane (ISSUE 18): death events with postmortems at the head,
# cluster-wide log federation
# ---------------------------------------------------------------------------

def test_worker_sigkill_one_death_event_at_head(cluster):
    """A worker SIGKILLed on a PEER node produces exactly ONE
    worker_death event at the head — correct cause class, non-empty
    postmortem with the worker's stderr tail — shipped over the daemon
    heartbeat with the acked-cursor dedup contract."""
    from ray_tpu.util import state

    cluster.add_node(num_cpus=2, resources={"die": 1})
    cluster.add_node(num_cpus=2)
    _init(cluster)
    _wait_nodes(3)

    @ray_tpu.remote(resources={"die": 1}, max_retries=0)
    def victim():
        import os as _os
        import signal as _signal
        import sys as _sys

        _sys.stderr.write("OSError: cross-node death marker\n")
        _sys.stderr.flush()
        _os.kill(_os.getpid(), _signal.SIGKILL)

    from ray_tpu.core.exceptions import WorkerCrashedError

    with pytest.raises(WorkerCrashedError) as ei:
        ray_tpu.get(victim.remote(), timeout=120)
    assert ei.value.error_type == "worker_died:signal:SIGKILL"
    assert "cross-node death marker" in str(ei.value)

    deaths = poll_until(
        lambda: [e for e in state.list_events(limit=100000)
                 if e["name"] == "worker_death"
                 and e.get("task") == "victim"],
        timeout=60, interval=0.5, desc="worker_death event at head")
    # several heartbeats have passed by now: the cursor contract must
    # have deduped re-ships down to exactly one record
    time.sleep(2.0)
    deaths = [e for e in state.list_events(limit=100000)
              if e["name"] == "worker_death" and e.get("task") == "victim"]
    assert len(deaths) == 1, deaths
    ev = deaths[0]
    assert ev["cause"] == "signal:SIGKILL"
    assert ev["severity"] == "error"
    assert ev["component"] == "raylet"  # reaped by the peer's daemon
    pm = ev["postmortem"]
    assert pm["cause"] == "signal:SIGKILL"
    assert "cross-node death marker" in pm.get("stderr_tail", "")
    # node_register events from the GCS's own table rode along too
    assert sum(1 for e in state.list_events(limit=100000)
               if e["name"] == "node_register") >= 3


def test_daemon_kill_one_node_death_event(cluster):
    """SIGKILL a node daemon: after the heartbeat timeout the GCS emits
    exactly ONE node_death event whose postmortem records the blast
    radius (there is no process left to read a stderr tail from)."""
    from ray_tpu.util import state

    victim = cluster.add_node(num_cpus=2, resources={"doomed": 1})
    cluster.add_node(num_cpus=2)
    _init(cluster)
    _wait_nodes(3)

    # learn the victim's node id before killing it
    daemons = [n for n in cluster.list_nodes() if not n["is_head"]]
    victim_ids = {n["node_id"].hex()[:8] for n in daemons}
    cluster.kill_node(victim)

    deaths = poll_until(
        lambda: [e for e in state.list_events(limit=100000)
                 if e["name"] == "node_death"],
        timeout=60, interval=0.5,
        desc="node_death event after heartbeat timeout")
    assert len(deaths) == 1, deaths
    ev = deaths[0]
    assert ev["node_id"] in victim_ids
    assert ev["component"] == "gcs"
    assert ev["severity"] == "error"
    # SIGKILL closes the daemon's GCS conn (usually "connection lost");
    # a blip-less box may only notice at the heartbeat timeout
    assert ev["cause"] in ("connection lost", "heartbeat timeout")
    pm = ev["postmortem"]
    assert pm["cause"] == ev["cause"]
    assert {"lost_objects", "dead_actors",
            "lost_pg_bundles"} <= set(pm)


def test_fetch_logs_cross_node_by_task_id(cluster):
    """Log federation: a task id resolves (via its death event) to the
    worker that ran it on a PEER node; the fetch rendezvous brings back
    that node's log tail with the error lines extracted — the
    `rtpu logs --task` backend."""
    from ray_tpu.util import state

    cluster.add_node(num_cpus=2, resources={"faraway": 1})
    _init(cluster)
    _wait_nodes(2)

    @ray_tpu.remote(resources={"faraway": 1}, max_retries=0)
    def remote_crash():
        import os as _os
        import signal as _signal
        import sys as _sys

        _sys.stderr.write("KeyError: federated log marker 456\n")
        _sys.stderr.flush()
        _os.kill(_os.getpid(), _signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(remote_crash.remote(), timeout=120)

    ev = poll_until(
        lambda: next((e for e in state.list_events(limit=100000)
                      if e["name"] == "worker_death"
                      and e.get("task") == "remote_crash"), None),
        timeout=60, interval=0.5, desc="remote death event at head")
    assert ev.get("task_id") and ev.get("worker_id")

    def _fetch():
        rows = state.fetch_logs({"task_id": ev["task_id"]}, timeout=10.0)
        return rows or None

    rows = poll_until(_fetch, timeout=60, interval=1.0,
                      desc="cross-node log fetch by task id")
    head_node = state._gcs().node_id.hex()[:8]
    assert rows[0]["node_id"] != head_node  # came from the peer
    assert "federated log marker 456" in rows[0]["tail"]
    assert any("KeyError" in ln for ln in rows[0]["error_lines"])


def test_device_report_federates_across_nodes(cluster, monkeypatch,
                                              capsys):
    """ISSUE 19 acceptance: ``state.device_report()`` on the head merges
    compiled-program registries from >= 2 nodes and >= 3 processes with
    component labels, and both surfaces (``/api/devices`` + ``rtpu
    devices``) render it. Pipeline: worker registries cast version-gated
    "device" snapshots over the control pipe; node stores ride the GCS
    heartbeat as idempotent per-node payloads; the head merges local +
    peers at read time."""
    import json
    import urllib.request

    monkeypatch.setenv("RTPU_DEVICE_PUSH_INTERVAL_S", "0.2")
    cluster.add_node(num_cpus=2, resources={"peer": 2})
    _init(cluster)
    _wait_nodes(2)

    # the driver registers a program of its own (process #1)
    import jax.numpy as jnp

    from ray_tpu.util import device_plane

    drv = device_plane.registered_jit(lambda x: x * 3.0,
                                      name="probe::driver",
                                      component="test")
    drv(jnp.ones((8,)))

    def _probe_body(name):
        import os as _os

        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
        import jax.numpy as _jnp

        from ray_tpu.util import device_plane as _dp

        f = _dp.registered_jit(lambda x: x * 2.0, name=name,
                               component="test")
        _jax.block_until_ready(f(_jnp.ones((8,))))
        return _os.getpid()

    @ray_tpu.remote(resources={"peer": 1})
    def remote_probe():
        return _probe_body("probe::remote")

    @ray_tpu.remote(num_cpus=1)
    def local_probe():
        return _probe_body("probe::local")

    pids = ray_tpu.get([remote_probe.remote(), local_probe.remote()],
                       timeout=120)
    assert len(set(pids)) == 2  # a worker process on each node

    from ray_tpu.util import state

    def _report():  # worker push (0.2s) -> heartbeat (~2s) -> GCS -> head
        rep = state.device_report()
        names = {r.get("program") for r in rep["programs"]}
        if not {"probe::driver", "probe::remote",
                "probe::local"} <= names:
            return None
        nids = {r.get("node_id") for r in rep["programs"]}
        procs = {(p.get("node_id"), p.get("pid"))
                 for p in rep["processes"]}
        comps = {p.get("component") for p in rep["processes"]}
        ok = (len(nids) >= 2 and len(procs) >= 3
              and {"driver", "worker"} <= comps)
        return rep if ok else None

    rep = poll_until(_report, timeout=60, interval=0.5,
                     desc="device report merges 2 nodes / 3 pids")
    assert rep["totals"]["processes"] >= 3
    assert rep["totals"]["compiles"] >= 3
    by_name = {r["program"]: r for r in rep["programs"]}
    assert by_name["probe::remote"]["component"] == "worker"
    head_node = state._gcs().node_id.hex()[:8]
    assert by_name["probe::remote"]["node_id"] != head_node
    assert by_name["probe::driver"]["node_id"] == head_node

    # both render surfaces over a live dashboard
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    url = f"http://127.0.0.1:{dash.port}"
    try:
        api = json.loads(urllib.request.urlopen(
            url + "/api/devices", timeout=10).read().decode())["result"]
        assert api["totals"]["processes"] >= 3
        assert {r["program"] for r in api["programs"]} >= {
            "probe::driver", "probe::remote", "probe::local"}

        import argparse

        from ray_tpu.scripts import _cmd_devices

        rc = _cmd_devices(argparse.Namespace(url=url, limit=50,
                                             census=True))
        out = capsys.readouterr().out
        assert rc == 0
        assert "probe::remote" in out and "probe::driver" in out
        assert "process(es)" in out
    finally:
        stop_dashboard()
