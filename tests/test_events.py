"""Event plane (ISSUE 18): lifecycle events, death postmortems, the
alerting watchdog, and log federation — local-mode unit + integration.

Multi-node shipping (heartbeat cursor, GCS node events, cross-node log
rendezvous) lives in test_cluster.py; chaos-path death assertions in
test_chaos_matrix.py. This file covers the recording plane (ring,
arming, drain), the postmortem builder (the forensics folded into
WorkerCrashedError/ActorDiedError), the Watchdog hysteresis engine with
synthetic metric views, and the single-process ends of list_events/
fetch_logs.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import events
from ray_tpu.util.event_store import EventStore

from conftest import poll_until


@pytest.fixture
def plane():
    """Fresh events-module state; restores the default-ON env after."""
    saved = os.environ.pop("RTPU_EVENTS", None)
    events._reset_for_tests()
    yield events
    if saved is None:
        os.environ.pop("RTPU_EVENTS", None)
    else:
        os.environ["RTPU_EVENTS"] = saved
    events._reset_for_tests()


# ---------------------------------------------------------------------------
# recording plane: ring, arming, drain
# ---------------------------------------------------------------------------

def test_events_on_by_default_and_kill_switch(plane):
    assert events.events_enabled()  # no env -> ON
    events.emit("worker_spawn", pid=1)
    assert events.ring_stats()["len"] == 1

    os.environ["RTPU_EVENTS"] = "0"
    events._reset_for_tests()
    assert not events.events_enabled()
    assert events.record("worker_spawn", pid=2) is None
    events.emit("worker_spawn", pid=2)  # no-op, not an error
    assert events.drain_ring() == []


def test_record_stamps_name_ts_severity(plane):
    rec = events.record("worker_death", worker_id="abcd1234")
    assert rec["name"] == "worker_death"
    assert rec["severity"] == "error"  # death events default to error
    assert rec["worker_id"] == "abcd1234"
    assert rec["ts"] == pytest.approx(time.time(), abs=30)
    assert events.record("worker_spawn")["severity"] == "info"
    assert events.record("actor_restart")["severity"] == "warning"
    # explicit severity wins over the catalog default
    assert events.record("worker_spawn",
                         severity="error")["severity"] == "error"


def test_ring_bounded_drains_once_and_counts_drops(plane):
    events._ring_cap = 4  # shrink the ring for the overflow path
    for i in range(6):
        events.emit("object_spill", object_id=f"{i:016x}")
    stats = events.ring_stats()
    assert stats["len"] == 4 and stats["dropped"] == 2
    batch = events.drain_ring()
    assert [e["object_id"] for e in batch] == [
        f"{i:016x}" for i in range(2, 6)]  # oldest overflowed out
    assert events.drain_ring() == []  # events leave the ring exactly once


def test_arming_flip_roundtrip(plane):
    events.disable_events()
    assert os.environ["RTPU_EVENTS"] == "0"
    assert not events.events_enabled()
    events.enable_events()
    assert os.environ["RTPU_EVENTS"] == "1"
    assert events.events_enabled()
    # apply_remote is the worker/daemon side of the same payload
    events.apply_remote({"enabled": False})
    assert not events.events_enabled()
    events.apply_remote(events.push_spec() | {"enabled": True})
    assert events.events_enabled()


def test_event_store_cursor_and_eviction():
    st = EventStore(cap=64)
    st.ingest([{"name": "worker_spawn", "i": i} for i in range(10)],
              {"node_id": "aa", "component": "raylet"})
    assert len(st) == 10
    assert st.snapshot(3)[-1]["i"] == 9
    assert st.snapshot()[0]["component"] == "raylet"  # labels stamped
    batch, start = st.since(0, max_n=4)
    assert start == 0 and [e["i"] for e in batch] == [0, 1, 2, 3]
    batch, start = st.since(4)
    assert start == 4 and [e["i"] for e in batch] == list(range(4, 10))
    # eviction advances the readable window: cursor 0 resumes at start>0
    st2 = EventStore(cap=64)  # deque floor is 64
    st2.ingest([{"i": i} for i in range(100)])
    batch, start = st2.since(0)
    assert start == 36 and batch[0]["i"] == 36


# ---------------------------------------------------------------------------
# postmortems: the death forensics builder
# ---------------------------------------------------------------------------

def test_describe_exit_cause_classes():
    assert events.describe_exit(None) == "unknown"
    assert events.describe_exit(0) == "clean_exit"
    assert events.describe_exit(3) == "exit:3"
    assert events.describe_exit(-9) == "signal:SIGKILL"
    assert events.describe_exit(-15) == "signal:SIGTERM"


def test_read_log_tail_proc_fd_fallback(tmp_path):
    """A log file deleted under a live process is still readable through
    /proc/<pid>/fd — the known 0-byte-log failure mode on this box."""
    log = tmp_path / "w.log"
    with open(log, "w") as f:
        child = subprocess.Popen(
            [sys.executable, "-c",
             "import sys,time; sys.stderr.write('RuntimeError: boom\\n');"
             "sys.stderr.flush(); time.sleep(60)"],
            stdout=subprocess.DEVNULL, stderr=f)
    try:
        poll_until(lambda: log.stat().st_size > 0, timeout=20,
                   desc="child wrote stderr")
        os.unlink(log)  # delete the file under the live process
        tail = events._read_log_tail(str(log), child.pid, 4096)
        assert "RuntimeError: boom" in tail
    finally:
        child.kill()
        child.wait()


def test_extract_error_lines_and_last_stack():
    text = "\n".join([
        "boot ok",
        "Traceback (most recent call last):",
        '  File "x.py", line 1, in <module>',
        "ValueError: first",
        "Current thread 0x00007f0000000000 (most recent call first):",
        '  File "old.py", line 9 in spin',
        "noise",
        "Current thread 0x00007f1111111111 (most recent call first):",
        '  File "new.py", line 3 in work',
        "MemoryError",
    ])
    errs = events.extract_error_lines(text)
    assert "Traceback (most recent call last):" in errs
    assert "ValueError: first" in errs and "MemoryError" in errs
    assert "boot ok" not in errs
    stack = events.extract_last_stack(text)
    assert stack.startswith("Current thread 0x00007f1111111111")
    assert "new.py" in stack and "old.py" not in stack
    assert events.extract_last_stack("no dumps here") is None


def test_build_and_format_postmortem(tmp_path):
    log = tmp_path / "worker.log"
    log.write_text("starting\nZeroDivisionError: division by zero\n")
    pm = events.build_postmortem(exit_status=1, log_path=str(log))
    assert pm["cause"] == "exit:1" and pm["exit_status"] == 1
    assert "ZeroDivisionError" in pm["stderr_tail"]
    assert pm["error_lines"] == ["ZeroDivisionError: division by zero"]
    txt = events.format_postmortem(pm)
    assert "cause: exit:1" in txt and "ZeroDivisionError" in txt
    # bounded even for a crash-loop's worth of log
    huge = events.build_postmortem(
        exit_status=-9, log_path=str(log),
        extra_field="x")
    huge["error_lines"] = ["SomeError: y" * 50] * 200
    assert len(events.format_postmortem(huge)) <= 1200
    assert events.format_postmortem(None) == ""
    # never raises on unreadable inputs
    pm2 = events.build_postmortem(exit_status=-11,
                                  log_path="/nonexistent/x.log", pid=None)
    assert pm2["cause"] == "signal:SIGSEGV" and "stderr_tail" not in pm2


# ---------------------------------------------------------------------------
# alerting watchdog: hysteresis over synthetic metric views
# ---------------------------------------------------------------------------

@pytest.fixture
def watchdog_env(plane):
    from ray_tpu.util import alerts

    saved = os.environ.pop("RTPU_ALERTS", None)
    alerts._reset_for_tests()
    yield alerts
    if saved is None:
        os.environ.pop("RTPU_ALERTS", None)
    else:
        os.environ["RTPU_ALERTS"] = saved
    alerts._reset_for_tests()


def _drained_names():
    return [e["name"] for e in events.drain_ring()]


def test_gauge_rule_hysteresis_raise_and_clear(watchdog_env):
    alerts = watchdog_env
    rule = {"name": "hot", "kind": "gauge_above", "metric": "g",
            "threshold": 0.5, "severity": "warning", "description": "d"}
    wd = alerts.Watchdog(rules=[rule], sample_fn=lambda: {})
    hot = {"g": [((), 0.9)]}
    cold = {"g": [((), 0.1)]}
    assert wd.evaluate_once(hot) == []          # tick 1: breach, no raise
    assert _drained_names() == []
    active = wd.evaluate_once(hot)              # tick 2: FOR_TICKS met
    assert [a["alert"] for a in active] == ["hot"]
    assert active[0]["value"] == 0.9 and active[0]["threshold"] == 0.5
    assert _drained_names() == ["alert_raised"]
    assert wd.evaluate_once(cold) != []         # healthy tick 1: still on
    assert wd.evaluate_once(cold) == []         # healthy tick 2: cleared
    assert _drained_names() == ["alert_cleared"]
    # no data at all: nothing flaps, nothing raises
    assert wd.evaluate_once({}) == []


def test_gauge_flapping_never_raises(watchdog_env):
    """A metric alternating around the threshold never accumulates
    FOR_TICKS consecutive breaches — hysteresis kills the flap."""
    alerts = watchdog_env
    rule = {"name": "flap", "kind": "gauge_above", "metric": "g",
            "threshold": 0.5, "severity": "warning", "description": "d"}
    wd = alerts.Watchdog(rules=[rule], sample_fn=lambda: {})
    for i in range(8):
        view = {"g": [((), 0.9 if i % 2 == 0 else 0.1)]}
        assert wd.evaluate_once(view) == []
    assert _drained_names() == []


def test_hist_p_rule_windows_bucket_deltas(watchdog_env):
    """hist_p_above quantiles the WINDOW (bucket deltas vs the previous
    tick), not cumulative history — old slowness can't page forever."""
    alerts = watchdog_env
    rule = {"name": "slow", "kind": "hist_p_above", "metric": "h",
            "q": 0.5, "threshold": 1.0, "min_count": 1,
            "severity": "warning", "description": "d"}
    wd = alerts.Watchdog(rules=[rule], sample_fn=lambda: {})
    bounds = [0.1, 1.0, 10.0]

    def view(counts, total):
        return {"h": [((), (counts, 0.0, total, bounds))]}

    # ticks 1+2: five slow observations -> p50 = 10.0 > 1.0 -> raise
    wd.evaluate_once(view([0, 0, 5], 5))
    # same cumulative counts: empty window -> below min_count -> holds
    assert wd.evaluate_once(view([0, 0, 5], 5)) == []
    active = wd.evaluate_once(view([0, 0, 6], 6))  # one more slow obs
    assert [a["alert"] for a in active] == ["slow"]
    # two windows of only-fast observations clear it
    wd.evaluate_once(view([20, 0, 6], 26))
    assert wd.evaluate_once(view([40, 0, 6], 46)) == []
    assert _drained_names() == ["alert_raised", "alert_cleared"]


def test_stall_rule_needs_depth_and_no_flow(watchdog_env):
    alerts = watchdog_env
    rule = {"name": "stall", "kind": "stall", "metric": "depth",
            "flow": "done", "min_depth": 1, "threshold": 0,
            "severity": "warning", "description": "d"}
    wd = alerts.Watchdog(rules=[rule], sample_fn=lambda: {})

    def view(depth, done):
        return {"depth": [((), depth)], "done": [((), done)]}

    assert wd.evaluate_once(view(3, 100)) == []  # first tick: baseline
    assert wd.evaluate_once(view(3, 100)) == []  # stalled tick 1
    active = wd.evaluate_once(view(3, 100))      # stalled tick 2: raise
    assert [a["alert"] for a in active] == ["stall"]
    # flow resumes (counter advances) -> clears after CLEAR_TICKS
    wd.evaluate_once(view(3, 120))
    assert wd.evaluate_once(view(2, 140)) == []


def test_watchdog_kill_switch_and_active_alerts(watchdog_env):
    alerts = watchdog_env
    os.environ["RTPU_ALERTS"] = "0"
    alerts._reset_for_tests()
    os.environ["RTPU_ALERTS"] = "0"
    assert alerts.start_watchdog() is None
    assert alerts.active_alerts() == []


def test_default_rules_evaluate_against_real_registry(watchdog_env):
    """The shipped rule table runs against this process's live metric
    view without raising (smoke: names/kinds/fields are coherent)."""
    alerts = watchdog_env
    wd = alerts.Watchdog()
    out = wd.evaluate_once()
    assert isinstance(out, list)
    rule_names = {r["name"] for r in wd.rules}
    assert {"heartbeat_gap", "queue_stall", "arena_occupancy"} <= rule_names


# ---------------------------------------------------------------------------
# runtime integration: death postmortems in user errors + local planes
# ---------------------------------------------------------------------------

@pytest.fixture
def rt(plane):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_sigkilled_task_error_carries_postmortem(rt):
    """The r16 machine-readable contract extended with forensics: a
    SIGKILLed worker surfaces as WorkerCrashedError with
    error_type='worker_died:signal:SIGKILL', a structured postmortem,
    and the stderr excerpt folded into the message."""
    from ray_tpu.core.exceptions import WorkerCrashedError

    @ray_tpu.remote(max_retries=0)
    def doomed():
        sys.stderr.write("RuntimeError: pre-kill marker\n")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(WorkerCrashedError) as ei:
        ray_tpu.get(doomed.remote(), timeout=120)
    err = ei.value
    assert err.error_type == "worker_died:signal:SIGKILL"
    assert err.postmortem["cause"] == "signal:SIGKILL"
    assert "pre-kill marker" in err.postmortem.get("stderr_tail", "")
    assert "worker postmortem" in str(err)
    assert "pre-kill marker" in str(err)


def test_worker_death_event_visible_with_postmortem(rt):
    """Exactly one worker_death event per reaped worker, queryable via
    state.list_events, carrying the cause class and the postmortem."""
    from ray_tpu.util import state

    @ray_tpu.remote(max_retries=0)
    def seppuku():
        sys.stderr.write("ValueError: event marker\n")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(seppuku.remote(), timeout=120)

    deaths = poll_until(
        lambda: [e for e in state.list_events(limit=10000)
                 if e["name"] == "worker_death"
                 and e.get("task") == "seppuku"],
        timeout=60, desc="worker_death event collected")
    assert len(deaths) == 1  # one reap -> one event
    ev = deaths[0]
    assert ev["cause"] == "signal:SIGKILL"
    assert ev["severity"] == "error"
    assert ev["component"] in ("driver", "worker")
    pm = ev["postmortem"]
    assert pm["cause"] == "signal:SIGKILL"
    assert "event marker" in pm.get("stderr_tail", "")
    # spawn events exist too (the worker had to be born to die)
    assert any(e["name"] == "worker_spawn"
               for e in state.list_events(limit=10000))
    # name filter narrows server-side
    only = state.list_events(filters=[("name", "=", "worker_death")])
    assert only and all(e["name"] == "worker_death" for e in only)


def test_fetch_logs_by_worker_and_task_id_local(rt):
    """Log federation, single-node half: a dead worker's log resolves by
    worker_id AND by task_id (via the death event), with error lines
    extracted from the tail."""
    from ray_tpu.util import state

    @ray_tpu.remote(max_retries=0)
    def shouty():
        sys.stderr.write("IndexError: log marker 123\n")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(shouty.remote(), timeout=120)
    ev = poll_until(
        lambda: next((e for e in state.list_events(limit=10000)
                      if e["name"] == "worker_death"
                      and e.get("task") == "shouty"), None),
        timeout=60, desc="death event for shouty")

    rows = state.fetch_logs({"worker_id": ev["worker_id"]})
    assert rows and "log marker 123" in rows[0]["tail"]
    assert any("IndexError" in ln for ln in rows[0]["error_lines"])

    rows2 = state.fetch_logs({"task_id": ev["task_id"]})
    assert rows2 and "log marker 123" in rows2[0]["tail"]


def test_disarmed_plane_records_nothing(rt):
    """RTPU_EVENTS=0 at runtime: disable_events() stops recording in the
    driver and its workers; re-enabling restores the flow."""
    from ray_tpu.util import state

    events.disable_events()
    try:
        @ray_tpu.remote
        def ping():
            return 1

        assert ray_tpu.get(ping.remote(), timeout=60) == 1
        before = len(state.list_events(limit=100000))

        @ray_tpu.remote(max_retries=0)
        def die_quiet():
            os.kill(os.getpid(), signal.SIGKILL)

        with pytest.raises(Exception):
            ray_tpu.get(die_quiet.remote(), timeout=120)
        time.sleep(1.0)
        assert len(state.list_events(limit=100000)) == before
    finally:
        events.enable_events()


def test_dashboard_routes_and_cli(rt, capsys):
    """/api/events, /api/logs, /api/alerts serve the plane over HTTP,
    and the `rtpu events` / `rtpu logs` CLI render them (the operator
    surface: ISSUE 18 acceptance that a death is explainable end to
    end without ssh)."""
    import argparse
    import json
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard
    from ray_tpu import scripts

    @ray_tpu.remote(max_retries=0)
    def crash():
        sys.stderr.write("TypeError: http marker 789\n")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(crash.remote(), timeout=120)

    dash = start_dashboard(port=0)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        def _api(path):
            return json.loads(urllib.request.urlopen(
                base + path, timeout=15).read())["result"]

        deaths = poll_until(
            lambda: [e for e in _api("/api/events?name=worker_death")
                     if e.get("task") == "crash"],
            timeout=60, desc="death event over /api/events")
        ev = deaths[0]
        assert ev["postmortem"]["cause"] == "signal:SIGKILL"

        rows = _api(f"/api/logs?worker_id={ev['worker_id']}")
        assert rows and "http marker 789" in rows[0]["tail"]

        assert _api("/api/alerts") == []  # healthy box: nothing raised

        # CLI renderers against the same endpoints
        rc = scripts._cmd_events(argparse.Namespace(
            url=base, limit=200, name="worker_death"))
        out = capsys.readouterr().out
        assert rc == 0 and "worker_death" in out
        assert "postmortem: cause=signal:SIGKILL" in out

        rc = scripts._cmd_logs(argparse.Namespace(
            url=base, task_id=ev["task_id"], actor_id=None,
            worker_id=None, node_id=None, errors_only=True))
        out = capsys.readouterr().out
        assert rc == 0 and "TypeError: http marker 789" in out

        rc = scripts._cmd_logs(argparse.Namespace(
            url=base, task_id=None, actor_id=None, worker_id=None,
            node_id=None, errors_only=False))
        assert rc == 2  # no target given
    finally:
        stop_dashboard()
