"""Autoscaler MVP: fake TPU provider, slice scale-up/down, min/max bounds.

Reference patterns: StandardAutoscaler loop (autoscaler.py:172), fake
multi-node provider (fake_multi_node/), GCP TPU slice provisioning
(gcp/node_provider.py:75-94)."""

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeTpuNodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
    request_resources,
)


@pytest.fixture(autouse=True)
def _clear_requests():
    request_resources([])
    yield
    request_resources([])


def _mk(idle_timeout=0.0, **kw):
    provider = FakeTpuNodeProvider(
        node_types={"cpu-worker": {"CPU": 4.0}})
    config = AutoscalerConfig(
        node_types=[
            NodeTypeConfig("cpu-worker", min_workers=0, max_workers=4),
            NodeTypeConfig("v5e-16", min_workers=0, max_workers=2,
                           is_slice=True),
        ],
        idle_timeout_s=idle_timeout, **kw)
    return provider, StandardAutoscaler(provider, config)


def test_demand_for_slice_head_scales_up_whole_slice():
    provider, asc = _mk()
    request_resources([{"TPU-v5e-16-head": 1.0}])
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert len(nodes) == 4  # v5e-16 = 4 hosts x 4 chips
    heads = [n for n in nodes if n.is_slice_head]
    assert len(heads) == 1
    assert heads[0].resources["TPU-v5e-16-head"] == 1.0
    pod = heads[0].tags["pod_name"]
    assert all(n.resources.get(pod) == 1.0 for n in nodes)
    # demand satisfied: another update launches nothing new
    asc.update()
    assert len(provider.non_terminated_nodes()) == 4


def test_aggregate_chip_demand_provisions_slice():
    provider, asc = _mk()
    request_resources([{"TPU": 16.0}])  # > one host's 4 chips -> slice
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert sum(n.resources["TPU"] for n in nodes) == 16.0
    assert len({n.slice_id for n in nodes}) == 1


def test_cpu_demand_uses_cheap_nodes_not_slices():
    provider, asc = _mk()
    request_resources([{"CPU": 3.0}, {"CPU": 2.0}])
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert all(n.node_type == "cpu-worker" for n in nodes)
    assert len(nodes) == 2


def test_idle_slice_scales_down_as_a_unit():
    provider, asc = _mk(idle_timeout=0.0)
    request_resources([{"TPU-v5e-16-head": 1.0}])
    asc.update()
    assert len(provider.non_terminated_nodes()) == 4
    request_resources([])    # demand released
    import time

    time.sleep(0.05)
    asc.update()             # idle > 0s timeout -> whole slice terminates
    assert provider.non_terminated_nodes() == []
    assert any(t.startswith("slice-v5e-16") for t in provider.terminate_calls)


def test_min_workers_floor_and_max_workers_cap():
    provider = FakeTpuNodeProvider(node_types={"cpu-worker": {"CPU": 4.0}})
    config = AutoscalerConfig(
        node_types=[NodeTypeConfig("cpu-worker", min_workers=2,
                                   max_workers=3)],
        idle_timeout_s=0.0)
    asc = StandardAutoscaler(provider, config)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 2  # floor
    request_resources([{"CPU": 4.0}] * 10)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 3  # cap
    request_resources([])
    import time

    time.sleep(0.05)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 2  # back to floor


def test_busy_nodes_survive_idle_timeout():
    provider, asc = _mk(idle_timeout=0.0)
    request_resources([{"CPU": 2.0}])
    asc.update()
    (node,) = provider.non_terminated_nodes()
    request_resources([])
    import time

    time.sleep(0.05)
    # report the node busy: it must NOT be terminated
    asc.update(used_resources={node.node_id: {"CPU": 2.0}})
    assert len(provider.non_terminated_nodes()) == 1
    time.sleep(0.05)
    asc.update(used_resources={})
    assert provider.non_terminated_nodes() == []
