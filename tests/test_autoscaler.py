"""Autoscaler MVP: fake TPU provider, slice scale-up/down, min/max bounds.

Reference patterns: StandardAutoscaler loop (autoscaler.py:172), fake
multi-node provider (fake_multi_node/), GCP TPU slice provisioning
(gcp/node_provider.py:75-94)."""

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeTpuNodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
    request_resources,
)


@pytest.fixture(autouse=True)
def _clear_requests():
    request_resources([])
    yield
    request_resources([])


def _mk(idle_timeout=0.0, **kw):
    provider = FakeTpuNodeProvider(
        node_types={"cpu-worker": {"CPU": 4.0}})
    config = AutoscalerConfig(
        node_types=[
            NodeTypeConfig("cpu-worker", min_workers=0, max_workers=4),
            NodeTypeConfig("v5e-16", min_workers=0, max_workers=2,
                           is_slice=True),
        ],
        idle_timeout_s=idle_timeout, **kw)
    return provider, StandardAutoscaler(provider, config)


def test_demand_for_slice_head_scales_up_whole_slice():
    provider, asc = _mk()
    request_resources([{"TPU-v5e-16-head": 1.0}])
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert len(nodes) == 4  # v5e-16 = 4 hosts x 4 chips
    heads = [n for n in nodes if n.is_slice_head]
    assert len(heads) == 1
    assert heads[0].resources["TPU-v5e-16-head"] == 1.0
    pod = heads[0].tags["pod_name"]
    assert all(n.resources.get(pod) == 1.0 for n in nodes)
    # demand satisfied: another update launches nothing new
    asc.update()
    assert len(provider.non_terminated_nodes()) == 4


def test_aggregate_chip_demand_provisions_slice():
    provider, asc = _mk()
    request_resources([{"TPU": 16.0}])  # > one host's 4 chips -> slice
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert sum(n.resources["TPU"] for n in nodes) == 16.0
    assert len({n.slice_id for n in nodes}) == 1


def test_cpu_demand_uses_cheap_nodes_not_slices():
    provider, asc = _mk()
    request_resources([{"CPU": 3.0}, {"CPU": 2.0}])
    asc.update()
    nodes = provider.non_terminated_nodes()
    assert all(n.node_type == "cpu-worker" for n in nodes)
    assert len(nodes) == 2


def test_idle_slice_scales_down_as_a_unit():
    provider, asc = _mk(idle_timeout=0.0)
    request_resources([{"TPU-v5e-16-head": 1.0}])
    asc.update()
    assert len(provider.non_terminated_nodes()) == 4
    request_resources([])    # demand released
    import time

    time.sleep(0.05)
    asc.update()             # idle > 0s timeout -> whole slice terminates
    assert provider.non_terminated_nodes() == []
    assert any(t.startswith("slice-v5e-16") for t in provider.terminate_calls)


def test_min_workers_floor_and_max_workers_cap():
    provider = FakeTpuNodeProvider(node_types={"cpu-worker": {"CPU": 4.0}})
    config = AutoscalerConfig(
        node_types=[NodeTypeConfig("cpu-worker", min_workers=2,
                                   max_workers=3)],
        idle_timeout_s=0.0)
    asc = StandardAutoscaler(provider, config)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 2  # floor
    request_resources([{"CPU": 4.0}] * 10)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 3  # cap
    request_resources([])
    import time

    time.sleep(0.05)
    asc.update()
    assert len(provider.non_terminated_nodes()) == 2  # back to floor


def test_busy_nodes_survive_idle_timeout():
    provider, asc = _mk(idle_timeout=0.0)
    request_resources([{"CPU": 2.0}])
    asc.update()
    (node,) = provider.non_terminated_nodes()
    request_resources([])
    import time

    time.sleep(0.05)
    # report the node busy: it must NOT be terminated
    asc.update(used_resources={node.node_id: {"CPU": 2.0}})
    assert len(provider.non_terminated_nodes()) == 1
    time.sleep(0.05)
    asc.update(used_resources={})
    assert provider.non_terminated_nodes() == []


# ---------------------------------------------------------------------------
# GCP provider against a recorded API surface (reference
# gcp/node_provider.py:75-94 behavior; no cloud, no network)
# ---------------------------------------------------------------------------


class _FakeGcpApi:
    """Scripted transport: answers like the TPU/GCE REST APIs and records
    every call for assertions."""

    def __init__(self):
        self.calls = []
        self.tpu_nodes = {}
        self.instances = {}

    def __call__(self, method, url, body=None):
        self.calls.append((method, url, body))
        if "tpu.googleapis.com" in url:
            return self._tpu(method, url, body)
        return self._gce(method, url, body)

    def _tpu(self, method, url, body):
        if method == "POST" and "/nodes?nodeId=" in url:
            name = url.rsplit("nodeId=", 1)[1]
            acc = body["acceleratorType"]
            n_hosts = {"v5litepod-16": 4, "v5litepod-4": 1}.get(acc, 1)
            self.tpu_nodes[name] = {
                "name": f"projects/p/locations/z/nodes/{name}",
                "state": "READY",
                "acceleratorType": acc,
                "labels": body["labels"],
                "networkEndpoints": [
                    {"ipAddress": f"10.0.0.{i}"} for i in range(n_hosts)],
            }
            return {"name": f"op-{name}", "done": True}
        if method == "GET" and "/nodes/" in url:
            name = url.rsplit("/", 1)[1]
            return self.tpu_nodes[name]
        if method == "GET" and url.endswith("/nodes"):
            return {"nodes": list(self.tpu_nodes.values())}
        if method == "DELETE":
            name = url.rsplit("/", 1)[1]
            self.tpu_nodes.pop(name, None)
            return {"name": f"op-del-{name}", "done": True}
        raise AssertionError(f"unexpected tpu call {method} {url}")

    def _gce(self, method, url, body):
        if method == "POST" and url.endswith("/instances"):
            self.instances[body["name"]] = {
                "name": body["name"], "status": "RUNNING",
                "labels": body["labels"],
            }
            return {"name": f"op-{body['name']}", "done": True}
        if method == "GET" and "/instances?filter=" in url:
            return {"items": list(self.instances.values())}
        if method == "DELETE":
            name = url.rsplit("/", 1)[1]
            self.instances.pop(name, None)
            return {"name": f"op-del-{name}", "done": True}
        raise AssertionError(f"unexpected gce call {method} {url}")


_NODE_TYPES = {
    "head": {"kind": "compute", "machine_type": "n2-standard-8",
             "resources": {"CPU": 8.0}},
    "v5e-16": {"kind": "tpu", "accelerator_type": "v5litepod-16",
               "runtime_version": "tpu-ubuntu2204-base"},
}


def _gcp_provider():
    from ray_tpu.autoscaler.gcp import GcpTpuNodeProvider

    api = _FakeGcpApi()
    prov = GcpTpuNodeProvider(
        project="p", zone="z", cluster_name="demo",
        node_types=_NODE_TYPES, transport=api, poll_interval_s=0.0)
    return prov, api


def test_gcp_create_slice_maps_hosts_and_head_resource():
    prov, api = _gcp_provider()
    hosts = prov.create_slice("v5e-16")
    assert len(hosts) == 4  # 16 chips / 4 per host
    assert all(h.slice_id == hosts[0].slice_id for h in hosts)
    assert hosts[0].is_slice_head
    assert hosts[0].resources["TPU-v5litepod-16-head"] == 1.0
    assert all(h.resources["TPU"] == 4.0 for h in hosts)
    slice_name = hosts[0].slice_id
    assert all(h.resources[slice_name] == 1.0 for h in hosts)
    # the create rode the TPU API with cluster labels
    post = next(c for c in api.calls if c[0] == "POST")
    assert post[2]["labels"]["rtpu-cluster"] == "demo"


def test_gcp_list_and_terminate_slice_as_unit():
    prov, api = _gcp_provider()
    hosts = prov.create_slice("v5e-16")
    prov.create_nodes("head", 1)
    live = prov.non_terminated_nodes()
    assert len(live) == 5  # 4 slice hosts + 1 compute
    prov.terminate_node(hosts[2].node_id)  # any host kills the slice
    live = prov.non_terminated_nodes()
    assert len(live) == 1 and live[0].slice_id is None


def test_gcp_list_filters_foreign_clusters():
    prov, api = _gcp_provider()
    prov.create_slice("v5e-16")
    api.tpu_nodes["other"] = {
        "name": "projects/p/locations/z/nodes/other", "state": "READY",
        "acceleratorType": "v5litepod-4",
        "labels": {"rtpu-cluster": "SOMEONE-ELSE"},
        "networkEndpoints": [{}]}
    assert all(n.tags["rtpu-cluster"] == "demo"
               for n in prov.non_terminated_nodes())


class _RecordingRunner:
    def __init__(self):
        self.ran = []

    def run(self, node, cmd):
        self.ran.append((node.node_id, cmd))


def test_launcher_up_down_roundtrip(tmp_path):
    from ray_tpu.autoscaler import launcher

    cfg = {
        "cluster_name": "demo",
        "provider": {"type": "gcp", "project_id": "p",
                     "availability_zone": "z"},
        "auth": {"ssh_user": "u"},
        "head_node_type": "head",
        "available_node_types": {
            "head": _NODE_TYPES["head"],
            "v5e-16": dict(_NODE_TYPES["v5e-16"], min_workers=1),
        },
        "setup_commands": ["pip check"],
        "head_start_commands": ["start-head"],
        "worker_start_commands": ["start-worker"],
    }
    prov, api = _gcp_provider()
    runner = _RecordingRunner()
    out = launcher.up(cfg, provider=prov, runner=runner)
    assert out["head_created"]
    # head got setup + start; every slice host got setup + worker start
    head_cmds = [c for nid, c in runner.ran if nid == out["head"].node_id]
    assert head_cmds == ["pip check", "start-head"]
    worker_hosts = {nid for nid, c in runner.ran if c == "start-worker"}
    assert len(worker_hosts) == 4  # all hosts of the v5e-16 slice
    # idempotent: second up creates nothing new
    out2 = launcher.up(cfg, provider=prov, runner=runner)
    assert not out2["head_created"]
    assert not out2["workers_started"]
    assert launcher.down(cfg, provider=prov) == 2  # head + slice
    assert prov.non_terminated_nodes() == []


def test_launcher_yaml_validation(tmp_path):
    from ray_tpu.autoscaler import launcher

    p = tmp_path / "c.yaml"
    p.write_text("cluster_name: x\nprovider: {type: fake}\n")
    with pytest.raises(ValueError):
        launcher.load_config(str(p))


def test_v2_instance_manager_lifecycle():
    """Autoscaler v2 (reference autoscaler/v2/instance_manager role):
    explicit state machine, idempotent reconcile, cloud-death adoption."""
    from ray_tpu.autoscaler.fake_provider import FakeTpuNodeProvider
    from ray_tpu.autoscaler.v2 import (ALLOCATED, InstanceManager, QUEUED,
                                       RAY_RUNNING, TERMINATED)

    provider = FakeTpuNodeProvider({"v5e-8": {"CPU": 8, "TPU": 8}})
    im = InstanceManager(provider)
    ids = im.launch("v5e-8", count=2)
    assert [im.instances[i].status for i in ids] == [QUEUED, QUEUED]

    im.reconcile()
    assert all(im.instances[i].status == ALLOCATED for i in ids)
    cloud_ids = [im.instances[i].cloud_id for i in ids]
    assert all(cloud_ids)
    # reconcile is idempotent: no duplicate launches
    im.reconcile()
    assert len(provider.non_terminated_nodes()) == 2

    # GCS observes one node alive -> RAY_RUNNING binding
    im.reconcile(alive_node_ids={cloud_ids[0]})
    assert im.instances[ids[0]].status == RAY_RUNNING
    assert im.instances[ids[1]].status == ALLOCATED

    # cloud kills the other VM behind our back -> TERMINATING -> TERMINATED
    provider.terminate_node(cloud_ids[1])
    im.reconcile(alive_node_ids={cloud_ids[0]})
    assert im.instances[ids[1]].status == TERMINATED

    # explicit terminate of the running one
    im.terminate(ids[0])
    im.reconcile()
    assert im.instances[ids[0]].status == TERMINATED
    assert len(provider.non_terminated_nodes()) == 0
    assert im.summary()[TERMINATED] == 2

    # invalid transitions raise loudly
    import pytest as _pytest

    from ray_tpu.autoscaler.v2 import Instance

    inst = Instance("x", "t")
    with _pytest.raises(ValueError):
        inst.transition(RAY_RUNNING)


def test_kuberay_provider_patches_raycluster():
    """KubeRay integration (reference kuberay/node_provider.py role):
    scaling patches workerGroup replicas + workersToDelete on the CR."""
    from ray_tpu.autoscaler.kuberay import FakeKubeApi, KubeRayNodeProvider

    cr = {"spec": {"workerGroupSpecs": [
        {"groupName": "tpu-v5e-8", "replicas": 1, "numOfHosts": 1,
         "rayStartParams": {"num-cpus": "8", "num-tpus": "8"}},
        {"groupName": "cpu", "replicas": 0,
         "rayStartParams": {"num-cpus": "4"}},
    ]}}
    api = FakeKubeApi(cr)
    provider = KubeRayNodeProvider(api, "ray-ns", "demo")

    created = provider.create_nodes("tpu-v5e-8", 2)
    assert len(created) == 2
    assert created[0].resources == {"CPU": 8.0, "TPU": 8.0}
    assert api.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 3

    nodes = provider.non_terminated_nodes()
    assert sum(1 for n in nodes if n.node_type == "tpu-v5e-8") == 3
    assert sum(1 for n in nodes if n.node_type == "cpu") == 0

    provider.terminate_node("tpu-v5e-8-2")
    g = api.cr["spec"]["workerGroupSpecs"][0]
    assert g["replicas"] == 2
    assert g["scaleStrategy"]["workersToDelete"] == ["tpu-v5e-8-2"]

    import pytest as _p

    with _p.raises(ValueError):
        provider.create_nodes("nope", 1)


def test_usage_stats_report(monkeypatch, tmp_path):
    from ray_tpu.usage_stats import (collect_usage, usage_stats_enabled,
                                     write_usage_report)

    class FakeRt:
        session = "abc123"
        session_dir = str(tmp_path)
        total = {"CPU": 4.0}
        cluster = None

        class gcs:
            actors = {}

    rec = collect_usage(FakeRt())
    assert rec["ray_tpu_version"] and rec["num_nodes"] == 1
    path = write_usage_report(FakeRt())
    assert path and "usage_stats.json" in path
    import json as _json

    assert _json.load(open(path))["session_id"] == "abc123"

    monkeypatch.setenv("RTPU_USAGE_STATS_ENABLED", "0")
    assert not usage_stats_enabled()
    assert write_usage_report(FakeRt()) == ""


def test_v2_scheduler_bin_packing_and_infeasible():
    """v2 ResourceDemandScheduler (reference autoscaler/v2/scheduler.py):
    FFD bin-pack over the instance table, min/max floors, infeasible
    reporting — a pure function, no provider."""
    from ray_tpu.autoscaler.v2 import (Instance, NodeTypeSpec, RAY_RUNNING,
                                       ResourceDemandScheduler)

    types = [NodeTypeSpec("cpu", {"CPU": 4.0}, min_workers=1, max_workers=3),
             NodeTypeSpec("tpu", {"CPU": 8.0, "TPU": 8.0}, max_workers=2)]
    sched = ResourceDemandScheduler(types)

    # empty table: min_workers floor launches one cpu node
    dec = sched.schedule([], {}, set())
    assert dec.launches == {"cpu": 1}

    # FFD: biggest bundle first launches the tpu node, whose spare CPUs
    # then absorb the small bundles — no second cpu node needed
    insts = {"i1": Instance("i1", "cpu", status=RAY_RUNNING)}
    demand = [{"CPU": 2.0}, {"CPU": 2.0},
              {"CPU": 4.0},
              {"TPU": 8.0},
              {"GPU": 1.0}]                           # nobody has GPUs
    dec = sched.schedule(demand, insts, set())
    assert dec.launches == {"tpu": 1}
    assert dec.packing.get("i1") == 1                 # the CPU:4 bundle
    assert dec.infeasible == [{"GPU": 1.0}]
    # same inputs -> same decision (pure function)
    dec2 = sched.schedule(demand, insts, set())
    assert dec2.launches == dec.launches and dec2.infeasible == dec.infeasible

    # max_workers cap: demand for 5 tpu bundles only launches 2 nodes
    dec = sched.schedule([{"TPU": 8.0}] * 5, {}, set())
    assert dec.launches.get("tpu") == 2
    assert len(dec.infeasible) == 3

    # idle release: idle unpacked nodes terminate, but never below
    # min_workers and never a node that demand packed onto
    insts = {f"i{k}": Instance(f"i{k}", "cpu", status=RAY_RUNNING)
             for k in range(3)}
    dec = sched.schedule([{"CPU": 4.0}], insts, idle_instance_ids={"i0",
                                                                   "i1",
                                                                   "i2"})
    assert dec.packing  # one instance took the bundle
    packed = set(dec.packing)
    assert packed.isdisjoint(dec.terminations)
    # the packed node satisfies min_workers=1, so both idle ones go
    assert len(dec.terminations) == 2


def test_v2_scheduler_packs_against_available_capacity():
    """ADVICE r5: pending demand must bin-pack against each node's
    AVAILABLE resources, not its full declared resources — a saturated
    cluster otherwise absorbs every bundle on paper and never scales up."""
    from ray_tpu.autoscaler.v2 import (Instance, NodeTypeSpec, RAY_RUNNING,
                                       ResourceDemandScheduler)

    types = [NodeTypeSpec("cpu", {"CPU": 4.0}, max_workers=4)]
    sched = ResourceDemandScheduler(types)
    insts = {"i1": Instance("i1", "cpu", status=RAY_RUNNING)}

    # saturated node (0 CPU free): the bundle needs a NEW node
    dec = sched.schedule([{"CPU": 4.0}], insts, set(),
                         available={"i1": {"CPU": 0.0}})
    assert dec.launches == {"cpu": 1}, dec.launches
    assert not dec.packing

    # partially free node: small bundle packs, big bundle launches
    dec = sched.schedule([{"CPU": 2.0}, {"CPU": 4.0}], insts, set(),
                         available={"i1": {"CPU": 2.0}})
    assert dec.packing.get("i1") == 1
    assert dec.launches == {"cpu": 1}

    # no availability info (pre-RAY_RUNNING instances): full declared
    # resources remain the seed — launches stay idempotent
    dec = sched.schedule([{"CPU": 4.0}], insts, set())
    assert dec.launches == {} and dec.packing.get("i1") == 1


def test_v2_autoscaler_end_to_end_converges():
    """AutoscalerV2: demand -> scheduler -> InstanceManager -> provider,
    idle scale-down after timeout, crash-resume from the instance table."""
    from ray_tpu.autoscaler.fake_provider import FakeTpuNodeProvider
    from ray_tpu.autoscaler.v2 import (AutoscalerV2, NodeTypeSpec,
                                       RAY_RUNNING, TERMINATED)

    provider = FakeTpuNodeProvider({"cpu": {"CPU": 4.0}})
    types = [NodeTypeSpec("cpu", {"CPU": 4.0}, min_workers=0,
                          max_workers=4)]
    # injected clock: idle-timeout behavior without wall-clock races
    fake_now = [0.0]
    a = AutoscalerV2(provider, types, idle_timeout_s=60.0,
                     clock=lambda: fake_now[0])

    # demand appears -> two nodes launched and allocated in one pass
    a.update(demand=[{"CPU": 4.0}, {"CPU": 4.0}])
    cloud = {n.node_id for n in provider.non_terminated_nodes()}
    assert len(cloud) == 2

    # GCS sees them -> RAY_RUNNING
    a.update(demand=[{"CPU": 4.0}, {"CPU": 4.0}], alive_node_ids=cloud)
    running = [i for i in a.im.instances.values()
               if i.status == RAY_RUNNING]
    assert len(running) == 2

    # demand drains; nodes stay until idle_timeout then scale to zero
    a.update(demand=[], alive_node_ids=cloud)
    assert len(provider.non_terminated_nodes()) == 2  # not yet idle long
    fake_now[0] = 61.0
    a.update(demand=[], alive_node_ids=cloud)
    assert len(provider.non_terminated_nodes()) == 0
    assert all(i.status == TERMINATED for i in a.im.instances.values())
