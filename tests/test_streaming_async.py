"""Streaming generators, true async actors, cooperative cancel, log
streaming — reference analogs: ObjectRefGenerator (_raylet.pyx:273), async
actor fibers (core_worker/fiber.h), cancellation handler (_raylet.pyx:2084),
log monitor (GcsLogSubscriber, _raylet.pyx:3148)."""

import time

import pytest

import ray_tpu
from ray_tpu import ObjectRefGenerator
from ray_tpu.core.exceptions import TaskCancelledError, TaskError


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# streaming generators
# ---------------------------------------------------------------------------

def test_streaming_generator_basic(rt):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ObjectRefGenerator)
    vals = [ray_tpu.get(ref) for ref in g]
    assert vals == [0, 10, 20, 30, 40]
    assert len(g) == 5


def test_streaming_overlaps_producer(rt):
    """The consumer must receive item 0 while the producer still runs."""
    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(4)])  # spawn the pool first

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.8)

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(g))
    first_latency = time.monotonic() - t0
    assert first == 0
    # producer takes ~3.2s total; the first item must arrive far sooner
    assert first_latency < 2.0, f"first item took {first_latency:.1f}s"
    rest = [ray_tpu.get(r) for r in g]
    assert rest == [1, 2, 3]


def test_streaming_generator_error_mid_stream(rt):
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("boom mid-stream")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    with pytest.raises((TaskError, ValueError)):
        for ref in g:
            ray_tpu.get(ref)


def test_streaming_empty_generator(rt):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        if False:
            yield

    assert [ray_tpu.get(r) for r in empty.remote()] == []


def test_streaming_actor_method(rt):
    @ray_tpu.remote
    class Chunker:
        def chunks(self, n):
            for i in range(n):
                yield bytes([i]) * 4

    c = Chunker.remote()
    g = c.chunks.options(num_returns="streaming").remote(3)
    vals = [ray_tpu.get(r) for r in g]
    assert vals == [b"\x00" * 4, b"\x01" * 4, b"\x02" * 4]


def test_streaming_async_actor_method(rt):
    """num_returns="streaming" on an ASYNC actor method drains the async
    generator on the actor's loop (ADVICE r2: this raised TypeError) and
    keeps interleaving with other calls."""
    @ray_tpu.remote
    class AsyncChunker:
        async def chunks(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 10

        async def ping(self):
            return "pong"

    c = AsyncChunker.remote()
    g = c.chunks.options(num_returns="streaming").remote(4)
    # an interleaved call completes while the stream is live
    assert ray_tpu.get(c.ping.remote(), timeout=30) == "pong"
    vals = [ray_tpu.get(r) for r in g]
    assert vals == [0, 10, 20, 30]


def test_streaming_async_actor_backpressure(rt):
    """The backpressure option is honored on async actor streams too: the
    producer pauses until the consumer acks."""
    import time as _t

    @ray_tpu.remote
    class Slow:
        async def ping(self):
            return "pong"

        async def produce(self, n):
            for i in range(n):
                yield _t.monotonic()

    s = Slow.remote()
    ray_tpu.get(s.ping.remote(), timeout=60)  # warm BEFORE timing
    g = s.produce.options(
        num_returns="streaming",
        _generator_backpressure_num_objects=2).remote(6)
    _t.sleep(1.0)  # producer should be parked at 2 outstanding
    stamps = [ray_tpu.get(r, timeout=30) for r in g]
    assert len(stamps) == 6
    # with bp=2 the 3rd+ items were produced AFTER our sleep (consumer-
    # paced), so the stream spans the sleep window
    assert stamps[-1] - stamps[0] > 0.5


# ---------------------------------------------------------------------------
# true async actors: awaits interleave on one loop
# ---------------------------------------------------------------------------

def test_async_actor_calls_interleave(rt):
    """Call A blocks on an internal event; call B completes first; call C
    releases A — impossible unless calls share one live event loop."""
    @ray_tpu.remote
    class Gate:
        def __init__(self):
            import asyncio

            self.ev = asyncio.Event()

        async def wait_open(self):
            await self.ev.wait()
            return "A-done"

        async def quick(self):
            return "B-done"

        async def open(self):
            self.ev.set()
            return "C-done"

    g = Gate.remote()
    a = g.wait_open.remote()
    # B completes while A is parked at its await
    assert ray_tpu.get(g.quick.remote(), timeout=30) == "B-done"
    _, pending = ray_tpu.wait([a], timeout=0.2)
    assert pending == [a], "A should still be waiting"
    assert ray_tpu.get(g.open.remote(), timeout=30) == "C-done"
    assert ray_tpu.get(a, timeout=30) == "A-done"


def test_async_actor_many_concurrent(rt):
    @ray_tpu.remote
    class Sleeper:
        async def nap(self, t):
            import asyncio

            await asyncio.sleep(t)
            return t

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(0.01), timeout=60)  # warm: spawn + first call
    t0 = time.monotonic()
    out = ray_tpu.get([s.nap.remote(0.5) for _ in range(10)], timeout=60)
    wall = time.monotonic() - t0
    assert out == [0.5] * 10
    # 10 x 0.5s sleeps must overlap on the loop, not serialize to 5s
    assert wall < 3.0, f"async naps serialized: {wall:.1f}s"


# ---------------------------------------------------------------------------
# cooperative cancel
# ---------------------------------------------------------------------------

def test_cancel_running_task(rt, tmp_path):
    marker = str(tmp_path / "spinning")

    @ray_tpu.remote
    def spin(path):
        open(path, "w").close()  # signal: loop entered (event, not sleep)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            pass  # pure-python loop: SetAsyncExc lands between bytecodes
        return "finished"

    import os

    ref = spin.remote(marker)
    deadline = time.monotonic() + 60
    while not os.path.exists(marker):
        assert time.monotonic() < deadline, "task never started"
        time.sleep(0.05)
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    # cancellation surfaces as a bare TaskCancelledError no matter when the
    # cancel landed (queued / running / force)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=45)
    assert time.monotonic() - t0 < 30, "cancel did not interrupt the task"


def test_cancel_queued_task(rt):
    @ray_tpu.remote(resources={"CPU": 4})
    def hog():
        time.sleep(3)

    @ray_tpu.remote(resources={"CPU": 4})
    def queued():
        return 1

    h = hog.remote()
    q = queued.remote()  # cannot start while hog holds all CPUs
    time.sleep(0.3)
    ray_tpu.cancel(q)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(q, timeout=30)
    ray_tpu.get(h, timeout=30)


def test_cancel_force_kills_worker(rt):
    @ray_tpu.remote
    def block_hard():
        time.sleep(60)  # blocking syscall: only force can end it promptly

    ref = block_hard.remote()
    time.sleep(1.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


# ---------------------------------------------------------------------------
# log streaming to driver
# ---------------------------------------------------------------------------

def test_log_to_driver(rt, capsys):
    @ray_tpu.remote
    def noisy():
        print("hello-from-worker-xyzzy", flush=True)
        return 1

    assert ray_tpu.get(noisy.remote(), timeout=30) == 1
    deadline = time.monotonic() + 5
    seen = ""
    while time.monotonic() < deadline:
        seen += capsys.readouterr().out
        if "hello-from-worker-xyzzy" in seen:
            break
        time.sleep(0.2)
    assert "hello-from-worker-xyzzy" in seen
    assert "(worker-" in seen  # prefixed with the worker identity


def test_streaming_backpressure_paces_producer(rt):
    """_generator_backpressure_num_objects=2: the producer pauses while 2
    yields are unconsumed (reference generator_waiter.cc). A slow consumer
    therefore paces production instead of letting it run ahead."""
    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(2)])

    @ray_tpu.remote(num_returns="streaming",
                    _generator_backpressure_num_objects=2)
    def fast_gen():
        out = []
        for i in range(6):
            out.append((i, time.monotonic()))
            yield out[-1]
        return

    g = fast_gen.remote()
    stamps = []
    for ref in g:
        stamps.append(ray_tpu.get(ref))
        time.sleep(0.5)  # slow consumer
    assert [i for i, _ in stamps] == list(range(6))
    t = [ts for _, ts in stamps]
    # without backpressure all 6 produce within ~ms of each other; with
    # bp=2 item 5's production trails item 0 by >= ~3 consumer intervals
    spread = t[5] - t[0]
    assert spread > 1.0, f"producer ran ahead of backpressure: {spread:.2f}s"


def test_streaming_no_backpressure_runs_ahead(rt):
    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(2)])

    @ray_tpu.remote(num_returns="streaming")
    def fast_gen():
        for i in range(6):
            yield (i, time.monotonic())

    g = fast_gen.remote()
    stamps = []
    for ref in g:
        stamps.append(ray_tpu.get(ref))
        time.sleep(0.2)
    t = [ts for _, ts in stamps]
    assert t[5] - t[0] < 0.5, "unbackpressured producer should not wait"


def test_generator_try_next_nonblocking(rt):
    """try_next polls without parking: None while the producer works,
    refs as items land, StopIteration at the end; next_item_ref is
    waitable for scheduler-style idle parking (the data topology
    executor's contract)."""
    import time as _t

    @ray_tpu.remote(num_returns="streaming")
    def produce():
        yield 1
        time.sleep(2.0)
        yield 2

    gen = produce.remote()
    # item 1 lands quickly; poll until it surfaces (bounded)
    deadline = _t.monotonic() + 10
    first = None
    while first is None and _t.monotonic() < deadline:
        first = gen.try_next()
        if first is None:
            _t.sleep(0.01)
    assert first is not None and ray_tpu.get(first) == 1
    # the poll call must not park, whatever it returns (under suite load
    # item 2 may already have landed — asserting None would race). The
    # producer gap (2s) is deliberately far above the margin (1s): a
    # PARKED call takes the full gap, while a non-blocking one under
    # 2-vCPU suite load can still lose several hundred ms to the
    # scheduler — 0.3s vs 0.4s was a coin flip (r12 under-load flake).
    t_poll = _t.monotonic()
    polled = gen.try_next()
    assert _t.monotonic() - t_poll < 1.0, "try_next blocked"
    if polled is not None:
        assert ray_tpu.get(polled) == 2
    ready, _ = ray_tpu.wait([gen.next_item_ref(), gen.completed()],
                            num_returns=1, timeout=10)
    assert ready
    if polled is None:
        second = None
        while second is None and _t.monotonic() < deadline:
            second = gen.try_next()
            if second is None:
                _t.sleep(0.01)
        assert ray_tpu.get(second) == 2
    # exhausted -> StopIteration (possibly after the sentinel resolves)
    while True:
        try:
            r = gen.try_next()
        except StopIteration:
            break
        assert r is None
        assert _t.monotonic() < deadline, "sentinel never resolved"
        _t.sleep(0.01)
