"""Multi-model serving plane (ISSUE 16): model registry (arena-paged
weights, LRU under a byte budget, pinning), speculative decoding
(greedy token-exactness for both drafters, acceptance fallback),
multiplexed deployment (lazy engines, swap counters, close hygiene),
and the routing legs (model residency fold, prefix affinity)."""

import dataclasses
import threading
import time

import numpy as np
import pytest

from ray_tpu.serve.admission import RequestShedError


def _f32_cfg(name="llama-debug"):
    from ray_tpu import models

    # f32: greedy parity across kernels (bf16 logit ties flip on 1-ULP
    # cross-kernel rounding differences — see test_serve_paged.py)
    return dataclasses.replace(models.get_config(name),
                               dtype="float32", param_dtype="float32")


def _drain(eng, max_steps=800):
    for _ in range(max_steps):
        if not eng.step():
            return
    raise AssertionError("engine did not drain")


def _run_prompts(eng, prompts, max_new):
    outs = []
    for p in prompts:
        sink = []
        outs.append(sink)
        eng.submit(p, max_new, sink.append)
    _drain(eng)
    return [[t for t in o if t is not None] for o in outs]


# ---------------------------------------------------------------------------
# model registry: budget, LRU, pinning, deltas
# ---------------------------------------------------------------------------

def test_registry_register_validation():
    from ray_tpu.serve.multiplex import ModelRegistry

    reg = ModelRegistry(budget_bytes=0)
    cfg = _f32_cfg()
    reg.register("m0", cfg)
    with pytest.raises(ValueError, match="already registered"):
        reg.register("m0", cfg)
    with pytest.raises(ValueError, match="needs a config"):
        reg.register("m1")
    with pytest.raises(ValueError, match="not registered"):
        reg.register("v0", base="nope", delta={"targets": {}})
    with pytest.raises(ValueError, match="no delta"):
        reg.register("v0", base="m0")
    assert "m0" in reg and reg.models() == ["m0"]


def test_registry_lru_never_evicts_pinned():
    """The acceptance-criterion invariant: eviction makes room from the
    LRU UNPINNED tail; when every resident model is pinned the request
    sheds with reason=model_budget instead of yanking weights out from
    under an in-flight decode."""
    from ray_tpu import models
    from ray_tpu.serve.multiplex import ModelRegistry

    cfg = _f32_cfg()
    one = models.params_bytes(models.init_params(
        __import__("jax").random.PRNGKey(0), cfg))
    # budget fits exactly one resident model
    reg = ModelRegistry(budget_bytes=one + 1)
    reg.register("m0", cfg, seed=0)
    reg.register("m1", cfg, seed=1)

    reg.ensure_resident("m0")
    reg.pin("m0")
    with pytest.raises(RequestShedError) as e:
        reg.ensure_resident("m1")
    assert e.value.reason == "model_budget"
    snap = reg.snapshot()
    assert snap["m0"]["resident"] and snap["m0"]["state"] == "hbm"
    assert not snap["m1"]["resident"]

    # unpin -> the LRU victim is evictable and m1 swaps in
    reg.unpin("m0")
    reg.ensure_resident("m1")
    snap = reg.snapshot()
    assert not snap["m0"]["resident"] and snap["m0"]["swaps_out"] == 1
    assert snap["m1"]["resident"] and snap["m1"]["swaps_in"] == 1
    # LRU order: touch m1, then re-admit m0 -> m1 was just used, but it
    # is the ONLY unpinned resident, so it goes
    reg.ensure_resident("m0")
    assert reg.snapshot()["m1"]["swaps_out"] == 1
    with pytest.raises(RuntimeError, match="unpin"):
        reg.unpin("m0")


def test_registry_evict_cb_and_reacquire():
    """Eviction fires the bound engine drop hook; ensure_resident hands
    back fresh params afterwards (the params_provider reacquire path)."""
    import jax

    from ray_tpu import models
    from ray_tpu.serve.multiplex import ModelRegistry

    cfg = _f32_cfg()
    one = models.params_bytes(models.init_params(jax.random.PRNGKey(0),
                                                 cfg))
    reg = ModelRegistry(budget_bytes=one + 1)
    reg.register("m0", cfg, seed=0)
    reg.register("m1", cfg, seed=1)
    dropped = []
    reg.bind("m0", lambda: dropped.append("m0"))
    p0 = reg.ensure_resident("m0")
    reg.ensure_resident("m1")
    assert dropped == ["m0"]
    p0b = reg.ensure_resident("m0")          # swap back in
    assert p0b is not p0
    np.testing.assert_array_equal(np.asarray(p0["embed"]),
                                  np.asarray(p0b["embed"]))


def test_registry_delta_variant_shares_base():
    """A base+delta variant materializes via apply_delta, charges only
    its unique bytes, and shares untouched leaves with the base."""
    import jax

    from ray_tpu import models
    from ray_tpu.serve.multiplex import ModelRegistry

    cfg = _f32_cfg()
    base_params = models.init_params(jax.random.PRNGKey(0), cfg)
    delta = models.make_delta(jax.random.PRNGKey(9), cfg, rank=2,
                              scale=0.1)
    reg = ModelRegistry(budget_bytes=0)
    reg.register("base", cfg, params=base_params)
    reg.register("tuned", base="base", delta=delta)
    snap = reg.snapshot()
    assert snap["tuned"]["base"] == "base"
    assert 0 < snap["tuned"]["bytes"] < snap["base"]["bytes"]

    got = reg.ensure_resident("tuned")
    want = models.apply_delta(reg.ensure_resident("base"), delta)
    for leaf in ("wq", "wv"):
        np.testing.assert_allclose(np.asarray(got["layers"][leaf]),
                                   np.asarray(want["layers"][leaf]),
                                   rtol=1e-6)
    # untouched leaves are the SAME arrays as the resident base
    bp = reg.ensure_resident("base")
    assert got["layers"]["wk"] is bp["layers"]["wk"]
    assert got["embed"] is bp["embed"]


# ---------------------------------------------------------------------------
# speculative decoding: exact greedy parity + fallback
# ---------------------------------------------------------------------------

def _spec_parity_case(drafter, **spec_kw):
    import jax

    from ray_tpu import models
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.serve.multiplex import SpeculativeLLMEngine

    cfg = _f32_cfg()
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(11)
    # a mix: repetitive prompts (drafts land) + random ones (they don't)
    prompts = [
        [1, 2, 3, 4, 1, 2, 3, 4, 1, 2],
        rng.integers(0, 256, 7).tolist(),
        [5, 6, 5, 6, 5, 6, 5],
        rng.integers(0, 256, 19).tolist(),
    ]
    plain = LLMEngine(cfg, params, max_slots=4, max_len=96, paged=True,
                      block_size=4, prefill_chunk=8)
    refs = _run_prompts(plain, prompts, 24)

    spec = SpeculativeLLMEngine(cfg, params, drafter=drafter,
                                max_slots=4, max_len=96, paged=True,
                                block_size=4, prefill_chunk=8, **spec_kw)
    outs = _run_prompts(spec, prompts, 24)
    assert outs == refs, "speculative output diverged from plain greedy"
    return spec


def test_spec_ngram_exact_parity():
    spec = _spec_parity_case("ngram", spec_k=4, spec_accept_floor=0.0)
    assert spec.stats["spec_rounds"] > 0
    assert spec.stats["spec_accepted"] > 0       # drafts actually landed
    s = spec.kv_state()["spec"]
    assert s["spec_accepted"] <= s["spec_proposed"]


def test_spec_model_drafter_exact_parity():
    # draft model: SAME debug config, different seed — vocab matches,
    # proposals mostly miss; exactness must hold regardless
    spec = _spec_parity_case("model", spec_k=3, draft_seed=5,
                             spec_accept_floor=0.0)
    assert spec.stats["spec_rounds"] > 0


def test_spec_validation():
    from ray_tpu.serve.multiplex import SpeculativeLLMEngine

    cfg = _f32_cfg()
    with pytest.raises(ValueError, match="greedy"):
        SpeculativeLLMEngine(cfg, temperature=0.7)
    with pytest.raises(ValueError, match="paged"):
        SpeculativeLLMEngine(cfg, paged=False)
    with pytest.raises(ValueError, match="spec_k"):
        SpeculativeLLMEngine(cfg, spec_k=0)
    with pytest.raises(ValueError, match="drafter"):
        SpeculativeLLMEngine(cfg, drafter="oracle")
    # model drafter with a mismatched vocab fails at first propose
    small = dataclasses.replace(cfg, vocab_size=128)
    eng = SpeculativeLLMEngine(cfg, drafter="model", draft_model=small,
                               max_slots=2, max_len=64)
    eng.submit([1, 2, 3], 4, lambda t: None)
    with pytest.raises(ValueError, match="vocab"):
        _drain(eng)


def test_spec_fallback_on_collapsed_acceptance():
    """With an impossible acceptance floor every request falls back to
    plain decode after warmup — and stays token-exact doing it."""
    spec = _spec_parity_case("ngram", spec_k=4, spec_accept_floor=1.1)
    assert spec.stats["spec_fallbacks"] >= 1
    # fallback stops proposing: rounds stop growing once off
    assert all(st["off"] for st in spec._spec.values()) or not spec._spec


# ---------------------------------------------------------------------------
# multiplexed deployment
# ---------------------------------------------------------------------------

def _consume(gen):
    return [t for t in gen]


def test_multiplex_two_models_parity_and_lazy_paging():
    """Two models behind one replica: each model's stream matches its
    dedicated single-model deployment token-for-token, engines come up
    lazily, and the registry's swap counters record the paging."""
    from ray_tpu.serve.llm import LLMDeployment
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment

    cfg0, cfg1 = _f32_cfg(), _f32_cfg("gpt2-debug")
    dep = MultiplexedLLMDeployment(
        {"m0": {"config": cfg0, "seed": 0},
         "m1": {"config": cfg1, "seed": 1}},
        max_slots=2, max_len=64, block_size=4, prefill_chunk=8)
    try:
        assert dep._deps == {}                   # nothing materialized yet
        prompt = [1, 2, 3, 4, 5]
        out0 = _consume(dep(prompt, 8, model_id="m0"))
        assert list(dep._deps) == ["m0"]         # m1 still cold
        out1 = _consume(dep(prompt, 8, model_id="m1"))
        snap = dep.registry.snapshot()
        assert snap["m0"]["swaps_in"] == 1 and snap["m1"]["swaps_in"] == 1
        assert snap["m0"]["pins"] == 0 and snap["m1"]["pins"] == 0

        for mid, cfg, seed, want in (("m0", cfg0, 0, out0),
                                     ("m1", cfg1, 1, out1)):
            solo = LLMDeployment(cfg, max_slots=2, max_len=64,
                                 block_size=4, prefill_chunk=8, seed=seed)
            try:
                assert _consume(solo(prompt, 8)) == want, mid
            finally:
                solo.close()

        with pytest.raises(ValueError, match="unknown model_id"):
            dep(prompt, 4, model_id="m7")
        # default model is the first registered
        assert _consume(dep(prompt, 8)) == out0

        ls = dep.load_state()
        assert set(ls["models"]) == {"m0", "m1"}
        assert all(rec["state"] == "hbm" for rec in ls["models"].values())
        assert ls["inflight"] == 0 and ls["kv_total"] > 0
        st = dep.stats()
        assert st["models"]["m0"]["swaps_in"] == 1
        dep.check_health()
    finally:
        dep.close()
    snap = dep.registry.snapshot()
    assert all(not rec["resident"] for rec in snap.values())


def test_multiplex_pin_survives_stream_and_unpins_on_error():
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment

    dep = MultiplexedLLMDeployment({"m0": _f32_cfg()}, max_slots=2,
                                   max_len=64, block_size=4,
                                   prefill_chunk=8)
    try:
        gen = dep([1, 2, 3], 6, model_id="m0")
        first = next(gen)
        assert isinstance(first, int)
        # mid-stream the model is pinned: un-evictable
        assert dep.registry.snapshot()["m0"]["pins"] == 1
        _consume(gen)
        assert dep.registry.snapshot()["m0"]["pins"] == 0
        # abandoned generator: closing it must still unpin
        gen2 = dep([1, 2, 3], 6)
        next(gen2)
        gen2.close()
        assert dep.registry.snapshot()["m0"]["pins"] == 0
    finally:
        dep.close()


def test_multiplex_speculative_matches_plain():
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment

    cfg = _f32_cfg()
    prompt = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    plain = MultiplexedLLMDeployment({"m0": cfg}, max_slots=2,
                                     max_len=96, block_size=4,
                                     prefill_chunk=8)
    try:
        want = _consume(plain(prompt, 16))
    finally:
        plain.close()
    spec = MultiplexedLLMDeployment({"m0": cfg}, speculative=True,
                                    spec_k=4, spec_accept_floor=0.0,
                                    max_slots=2, max_len=96,
                                    block_size=4, prefill_chunk=8)
    try:
        assert _consume(spec(prompt, 16)) == want
        # speculation actually ran (acceptance itself is weight-luck on
        # a random debug model — exactness above is the guarantee)
        assert spec._deps["m0"].engine.stats["spec_proposed"] > 0
    finally:
        spec.close()


# ---------------------------------------------------------------------------
# chaos: close mid-stream / mid-swap-in — no leaked blocks, no stranded refs
# ---------------------------------------------------------------------------

@pytest.fixture
def rt():
    import ray_tpu

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_multiplex_chaos_close_mid_swap_frees_everything(rt):
    """Kill-the-replica chaos, in-process: weights live in the ARENA
    store (real refs), a budgeted registry is mid-swap-churn with one
    stream in flight, and close() lands mid-stream. Afterwards: every
    weight ref is out of the store (no stranded arena bytes), nothing
    stays resident, and the drained engine's pool accounts for every
    block."""
    import jax

    from ray_tpu import models
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment
    from ray_tpu.util.state import object_store_tier

    cfg = _f32_cfg()
    one = models.params_bytes(models.init_params(jax.random.PRNGKey(0),
                                                 cfg))
    dep = MultiplexedLLMDeployment(
        {"m0": {"config": cfg, "seed": 0},
         "m1": {"config": cfg, "seed": 1}},
        budget_bytes=one + 1, max_slots=2, max_len=64, block_size=4,
        prefill_chunk=8)
    refs = [e["ref"] for e in dep.registry._entries.values()]
    assert all(r is not None for r in refs)      # store-backed, not host
    assert all(object_store_tier(r) == "shm" for r in refs)

    # stream on m0 holds its pin while a CONCURRENT m1 request forces the
    # budget: the swap-in must shed (m0 is pinned), never evict mid-decode
    gen = dep([1, 2, 3, 4], 8, model_id="m0")
    assert isinstance(next(gen), int)
    shed = []

    def hit_m1():
        try:
            _consume(dep([5, 6, 7], 4, model_id="m1"))
        except RequestShedError as e:
            shed.append(e.reason)

    t = threading.Thread(target=hit_m1)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive()
    assert shed == ["model_budget"]
    assert dep.registry.snapshot()["m0"]["resident"]

    # consume one more token, then close mid-stream (the "kill")
    next(gen)
    dep.close()
    assert dep.registry.snapshot()["m0"]["pins"] == 1  # stream never ended
    # no stranded refs: registry.free() deleted every weight object from
    # the arena (directory + segment). What MAY remain is this process's
    # own view-liveness pin from the get() — drop the views and release
    # it, exactly what the store does for any freed-after-get object
    import gc

    from ray_tpu.core.runtime import _get_runtime

    snap = dep.registry.snapshot()
    assert all(not rec["resident"] for rec in snap.values())
    store = _get_runtime().store
    if store._arena is not None:
        assert all(not store._arena.contains(r.id.binary()) for r in refs)
    # the abandoned stream's engine still aliases the weight views —
    # drop it (the real kill reclaims the whole process) and the pins
    # become releasable
    gen.close()
    del gen, dep
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        gc.collect()
        for r in refs:
            store.release(r.id)
        if all(object_store_tier(r) == "unknown" for r in refs):
            break
        time.sleep(0.1)
    assert all(object_store_tier(r) == "unknown" for r in refs)


def test_multiplex_clean_drain_no_block_leak():
    """The non-chaos control: after streams complete and the deployment
    closes, each engine's free count + trie pins == total blocks."""
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment

    dep = MultiplexedLLMDeployment({"m0": _f32_cfg()}, max_slots=2,
                                   max_len=64, block_size=4,
                                   prefill_chunk=8)
    try:
        for _ in range(3):
            _consume(dep([1, 2, 3, 4, 5, 6], 6))
        eng = dep._deps["m0"].engine
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if eng.pool.free_count + len(eng.prefix) == eng.pool.num_blocks:
                break
            time.sleep(0.05)
        assert eng.pool.free_count + len(eng.prefix) == eng.pool.num_blocks
        assert eng.prefix.stats()["hits"] >= 1   # trie served the repeats
    finally:
        dep.close()


# ---------------------------------------------------------------------------
# routing: model residency fold + prefix affinity
# ---------------------------------------------------------------------------

class _Id:
    def __init__(self, b):
        self._b = b

    def binary(self):
        return self._b


class _Rep:
    def __init__(self, b):
        self._actor_id = _Id(b)


def _handle_with_loads(loads, n=2):
    from ray_tpu.serve.handle import DeploymentHandle

    h = DeploymentHandle("d")
    h._replicas = [_Rep(bytes([97 + i])) for i in range(n)]
    h._depths = [0] * n
    h._depth_ts = time.monotonic() + 3600
    h._delta = {i: 0 for i in range(n)}
    h._has_loads = True
    h._route_state["kv_next"] = time.monotonic() + 3600
    h._route_state["kv_loads"] = loads
    return h


def test_handle_model_residency_steers_routing():
    now = time.time()
    base = {"kv_free": 10, "kv_total": 10, "ts": now}
    h = _handle_with_loads({
        b"a": dict(base, models={"mx": {"state": "host"}}),
        b"b": dict(base, models={"mx": {"state": "hbm"}}),
    })
    # without a model_id: no penalty, scores tie
    assert h._scores()[0] == h._scores()[1]
    h2 = h.options(model_id="mx")
    assert h2._model_id == "mx"
    scores = h2._scores()
    assert scores[0] > scores[1]             # non-resident pays the weight
    assert {h2._pick_replica() for _ in range(20)} == {1}
    # a replica with NO models digest (single-model deployment) is not
    # penalized — only a digest that lacks residency is
    h3 = _handle_with_loads({b"a": dict(base), b"b": dict(base)})
    h3 = h3.options(model_id="mx")
    assert h3._scores()[0] == h3._scores()[1]


def test_handle_model_id_injected_into_kwargs():
    """_issue stamps the handle's model_id as a request kwarg (the
    routing hint doubles as the model address) without clobbering an
    explicit caller choice."""
    sent = {}

    class _Call:
        def remote(self, method, args, kwargs):
            sent.clear()
            sent.update(kwargs)
            return "ref"

    class _RichRep:
        _actor_id = _Id(b"a")
        handle_request = _Call()

    h = _handle_with_loads({}, n=1)
    h = h.options(model_id="m1")
    h._replicas = [_RichRep()]
    h._refresh = lambda force=False: None
    h._issue(([1, 2, 3], 4), {})
    assert sent.get("model_id") == "m1"
    h._issue(([1, 2, 3], 4), {"model_id": "override"})
    assert sent.get("model_id") == "override"


def test_handle_prefix_affinity_direct_pick_and_margin():
    from ray_tpu.serve.kv_cache import prefix_key_digest

    now = time.time()
    prompt = list(range(16))
    key = prefix_key_digest(prompt[:4])      # block_size=4
    base = {"kv_free": 10, "kv_total": 10, "ts": now, "block_size": 4}
    h = _handle_with_loads({
        b"a": dict(base, prefix_digest=[]),
        b"b": dict(base, prefix_digest=[(key, 7)]),
    })
    h = h.options(prefix_hint=prompt)
    assert h._affinity_key() == key
    for _ in range(10):
        assert h._pick_replica() == 1        # digest holder wins outright
    # overload: push the affinity home's score past the margin — load wins
    h._route_state["kv_loads"][b"b"]["kv_free"] = 0
    h._delta[1] = 50
    picks = {h._pick_replica() for _ in range(20)}
    assert 0 in picks
    # cold prefix: no digest anywhere -> rendezvous-hash fallback: one
    # deterministic home per key (every handle agrees without
    # coordination), so the tenant's opening burst lands on one trie
    h2 = _handle_with_loads({b"a": dict(base), b"b": dict(base)})
    h2 = h2.options(prefix_hint=list(range(50, 66)))
    picks2 = {h2._pick_replica() for _ in range(10)}
    assert len(picks2) == 1
    # ...and a different key may pick a different home, but is equally
    # sticky
    h2b = _handle_with_loads({b"a": dict(base), b"b": dict(base)})
    h2b = h2b.options(prefix_hint=list(range(100, 116)))
    assert len({h2b._pick_replica() for _ in range(10)}) == 1
    # hint shorter than a block: affinity disarms
    h3 = _handle_with_loads({b"a": dict(base), b"b": dict(base)})
    h3 = h3.options(prefix_hint=[1, 2])
    assert h3._affinity_key() is None
    # precomputed digest string passes through
    h4 = _handle_with_loads({b"a": dict(base)}, n=1)
    h4 = h4.options(prefix_hint=key)
    assert h4._affinity_key() == key


def test_handle_affinity_knob_off(monkeypatch):
    from ray_tpu.serve.kv_cache import prefix_key_digest

    prompt = list(range(16))
    key = prefix_key_digest(prompt[:4])
    base = {"kv_free": 10, "kv_total": 10, "ts": time.time(),
            "block_size": 4}
    h = _handle_with_loads({
        b"a": dict(base), b"b": dict(base, prefix_digest=[(key, 9)])})
    h = h.options(prefix_hint=prompt)
    monkeypatch.setenv("RTPU_SERVE_PREFIX_AFFINITY", "0")
    picks = {h._pick_replica() for _ in range(30)}
    assert picks == {0, 1}                   # pure p2c again


# ---------------------------------------------------------------------------
# controller + deployment load-report plumbing
# ---------------------------------------------------------------------------

def test_controller_model_report():
    from ray_tpu.serve.controller import ServeController

    ctrl = ServeController.__new__(ServeController)
    ctrl._deployments = {}
    ctrl._version = 0
    ctrl._metrics = {}
    ctrl._deployments["mux"] = {"replicas": [], "target": 1}
    ctrl._deployments["plain"] = {"replicas": [], "target": 1}
    ctrl.report_replica_load("mux", b"a", {
        "inflight": 2,
        "models": {"m0": {"state": "hbm", "swaps_in": 3, "swaps_out": 1,
                          "inflight": 2}},
        "prefix_digest": [("k0", 5)]})
    ctrl.report_replica_load("plain", b"b", {"inflight": 0})
    rep = ctrl.model_report()
    assert list(rep) == ["mux"]              # model-less deployments skip
    rec = rep["mux"]["replicas"][b"a".hex()]
    assert rec["models"]["m0"]["swaps_in"] == 3
    assert rec["prefix_digest"] == [("k0", 5)]
    assert rec["inflight"] == 2 and rec["ts"] > 0


def test_multiplex_load_state_shape_for_routing():
    """What MultiplexedLLMDeployment publishes is exactly what the
    handle's residency fold and affinity pick read."""
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment

    dep = MultiplexedLLMDeployment(
        {"m0": _f32_cfg(), "m1": _f32_cfg("gpt2-debug")},
        max_slots=2, max_len=64, block_size=4, prefill_chunk=8)
    try:
        prompt = [7] * 12
        _consume(dep(prompt, 4, model_id="m0"))
        _consume(dep(prompt, 4, model_id="m0"))  # repeat seeds the trie
        ls = dep.load_state()
        assert ls["models"]["m0"]["state"] == "hbm"
        assert ls["models"]["m1"]["state"] in ("host", "spilled")
        assert ls["block_size"] == 4
        # the merged prefix digest carries the shared first block
        from ray_tpu.serve.kv_cache import prefix_key_digest

        keys = [k for k, _ in ls["prefix_digest"]]
        assert prefix_key_digest(prompt[:4]) in keys
    finally:
        dep.close()
