"""Platform services: state API, metrics, dashboard HTTP, job submission, CLI."""

import json
import time

import pytest

import ray_tpu


@pytest.fixture
def rt_plat():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_state_api_lists(rt_plat):
    from ray_tpu.util import state

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.options(name="state_test_actor").remote()
    ray_tpu.get(a.ping.remote())

    actors = state.list_actors()
    assert any(rec["name"] == "state_test_actor" for rec in actors)
    assert state.summarize_actors().get("ALIVE", 0) >= 1

    @ray_tpu.remote
    def work():
        return 2

    ray_tpu.get([work.remote() for _ in range(3)])
    tasks = state.list_tasks()
    assert len(tasks) >= 3
    summary = state.summarize_tasks()
    assert sum(v.get("FINISHED", 0) for v in summary.values()) >= 3

    ref = ray_tpu.put(123)
    objs = state.list_objects()
    assert any(o["object_id"] == ref.id.hex() for o in objs)

    workers = state.list_workers()
    assert len(workers) >= 1

    filtered = state.list_actors(filters=[("name", "=", "state_test_actor")])
    assert len(filtered) == 1


def test_metrics_prometheus_text():
    from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                      clear_registry, prometheus_text)

    clear_registry()
    c = Counter("rtpu_test_total", "test counter", tag_keys=("kind",))
    c.inc(2, tags={"kind": "a"})
    c.inc(3, tags={"kind": "b"})
    g = Gauge("rtpu_test_gauge", "test gauge")
    g.set(7.5)
    h = Histogram("rtpu_test_hist", "test hist", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)
    h.observe(50)

    text = prometheus_text()
    assert 'rtpu_test_total{kind="a"} 2.0' in text
    assert "rtpu_test_gauge 7.5" in text
    assert "rtpu_test_hist_count 3" in text
    assert "rtpu_test_hist_sum 55.5" in text
    clear_registry()


def test_dashboard_endpoints(rt_plat):
    import http.client

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", dash.port, timeout=10)
        conn.request("GET", "/api/summary/objects")
        resp = conn.getresponse()
        assert resp.status == 200
        data = json.loads(resp.read())["result"]
        assert "total" in data

        conn = http.client.HTTPConnection("127.0.0.1", dash.port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200

        # "/" serves the single-page UI (reference dashboard client role)
        conn = http.client.HTTPConnection("127.0.0.1", dash.port, timeout=10)
        conn.request("GET", "/")
        resp = conn.getresponse()
        assert resp.status == 200
        body = resp.read().decode()
        assert "<html" in body and "/api/nodes" in body
        # UI views: drill-down panel, timeline swimlanes, metric sparklines
        assert "detail" in body and "timeline" in body and "spark" in body

        # /api/timeline returns the driver's Chrome-trace events (the
        # fixture ran tasks, so X spans exist)
        @ray_tpu.remote
        def one():
            return 1

        ray_tpu.get(one.remote())
        conn = http.client.HTTPConnection("127.0.0.1", dash.port, timeout=10)
        conn.request("GET", "/api/timeline")
        resp = conn.getresponse()
        assert resp.status == 200
        events = json.loads(resp.read())["result"]
        assert isinstance(events, list)
        assert any(e.get("ph") == "X" and e.get("dur", 0) > 0
                   for e in events)
    finally:
        dash.stop()


def test_job_submission_lifecycle(rt_plat, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "job.py"
    script.write_text("print('hello from job'); print(6*7)\n")
    job_id = client.submit_job(entrypoint=f"python {script}")
    status = client.wait_until_finished(job_id, timeout=120)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "hello from job" in logs and "42" in logs
    infos = client.list_jobs()
    assert any(i.job_id == job_id for i in infos)


def test_job_failure_recorded(rt_plat, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="python -c 'import sys; sys.exit(3)'")
    status = client.wait_until_finished(job_id, timeout=120)
    assert status == JobStatus.FAILED
    assert client.get_job_info(job_id).return_code == 3


def test_job_stop_kills_entrypoint(rt_plat):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="sleep 600")
    # wait for the subprocess pgid to publish
    deadline = time.time() + 60
    while time.time() < deadline:
        info = client.get_job_info(job_id)
        if info.pgid:
            break
        time.sleep(0.1)
    assert info.pgid, "job never started"
    assert client.stop_job(job_id)
    assert client.get_job_status(job_id) == JobStatus.STOPPED
    # the entrypoint process group is gone
    import os, signal

    deadline = time.time() + 10
    gone = False
    while time.time() < deadline:
        try:
            os.killpg(info.pgid, 0)
            time.sleep(0.1)
        except ProcessLookupError:
            gone = True
            break
    assert gone, "entrypoint subprocess survived stop_job"


def test_cli_status_and_clean():
    from ray_tpu.scripts import main

    assert main(["status"]) == 0


def test_cli_stack_dumps_worker_stacks(rt_plat):
    """ray_tpu stack (reference `ray stack`): SIGUSR1 + faulthandler dumps
    every worker thread's python stack into the session log."""
    import io
    import time
    from contextlib import redirect_stdout

    import ray_tpu
    from ray_tpu.scripts import main as cli_main

    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(2)])  # workers fully booted

    @ray_tpu.remote
    def sleeper():
        time.sleep(6)
        return 1

    refs = [sleeper.remote() for _ in range(2)]
    time.sleep(1.0)  # sleepers running
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(["stack"])
    out = buf.getvalue()
    assert rc == 0
    assert "signaled" in out
    assert "Current thread" in out  # a real stack dump was captured
    assert "sleeper" in out or "time.sleep" in out or "execute" in out
    ray_tpu.get(refs, timeout=30)


def test_tracing_spans_propagate_to_workers(tmp_path):
    """W3C-propagated task spans (reference tracing_helper role): driver
    submit spans and worker execute spans share one trace id across the
    process boundary; actor calls traced too."""
    import ray_tpu
    from ray_tpu.util import tracing

    trace_file = str(tmp_path / "traces.jsonl")
    tracing.enable_tracing(trace_file)
    try:
        ray_tpu.init(num_cpus=2, ignore_reinit_error=True)

        @ray_tpu.remote
        def traced_task(x):
            return x + 1

        assert ray_tpu.get(traced_task.remote(1), timeout=60) == 2

        @ray_tpu.remote
        class TracedActor:
            def m(self):
                return "ok"

        a = TracedActor.remote()
        assert ray_tpu.get(a.m.remote(), timeout=60) == "ok"

        deadline = time.time() + 30
        spans = []
        while time.time() < deadline:
            spans = tracing.read_trace_file(trace_file)
            if (any(s["name"] == "execute::traced_task" for s in spans)
                    and any(s["name"] == "execute::m" for s in spans)):
                break
            time.sleep(0.3)
        submit = next(s for s in spans if s["name"] == "submit::traced_task")
        execute = next(s for s in spans
                       if s["name"] == "execute::traced_task")
        assert execute["trace_id"] == submit["trace_id"]
        assert execute["parent_span_id"] == submit["span_id"]
        assert execute["attributes"]["process.pid"] != \
            submit["attributes"]["process.pid"]
        assert any(s["name"] == "submit::m" for s in spans)

        # nested submissions join the ENCLOSING task's trace
        @ray_tpu.remote
        def inner(x):
            return x * 10

        @ray_tpu.remote
        def outer():
            return ray_tpu.get(inner.remote(4))

        assert ray_tpu.get(outer.remote(), timeout=60) == 40
        deadline = time.time() + 30
        while time.time() < deadline:
            spans = tracing.read_trace_file(trace_file)
            if any(s["name"] == "execute::inner" for s in spans):
                break
            time.sleep(0.3)
        outer_exec = next(s for s in spans if s["name"] == "execute::outer")
        inner_sub = next(s for s in spans if s["name"] == "submit::inner")
        inner_exec = next(s for s in spans if s["name"] == "execute::inner")
        assert inner_sub["trace_id"] == outer_exec["trace_id"]
        assert inner_sub["parent_span_id"] == outer_exec["span_id"]
        assert inner_exec["trace_id"] == outer_exec["trace_id"]
    finally:
        import os as _os

        _os.environ.pop("RTPU_TRACING", None)
        _os.environ.pop("RTPU_TRACE_FILE", None)
        tracing._state["enabled"] = None
        tracing._state["fd"] = None
        ray_tpu.shutdown()


def test_node_host_stats_reported(rt_plat):
    """Per-node host utilization (reference dashboard reporter module):
    nodes() carries a psutil sample; keys stay stable for the UI."""
    nodes = ray_tpu.nodes()
    stats = nodes[0].get("stats") or {}
    assert {"cpu_percent", "mem_used", "mem_total",
            "num_cpus"} <= set(stats)
    assert stats["mem_total"] > stats["mem_used"] > 0
