"""Data plane (r14): multi-threaded memcpy, compressed spill/restore,
chunk-parallel cross-node transfer."""

import os

import numpy as np
import pytest

from ray_tpu import _native
from ray_tpu.core import spill_codec
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import StoreClient, _spill_path


# ---------------------------------------------------------------------------
# LZ4 codec + spill file format
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not _native.pipe_engine_available(),
                    reason="native codec unavailable")
def test_lz4_roundtrip_shapes():
    import random

    random.seed(7)
    cases = [
        b"",
        b"a",
        b"abc" * 50_000,                     # highly repetitive
        os.urandom(200_000),                 # incompressible
        bytes(random.choices(b"abcd", k=300_000)),  # low entropy
        b"\x00" * 1_000_000,                 # RLE extreme
        os.urandom(13),                      # below the match minimum
    ]
    for data in cases:
        comp = _native.lz4_compress(data)
        assert comp is not None
        assert _native.lz4_decompress(comp, len(data)) == data
        out = bytearray(len(data))
        if data:
            assert _native.lz4_decompress_into(comp, out) == len(data)
            assert bytes(out) == data


def test_spill_file_roundtrip_and_ranges(tmp_path):
    payloads = [
        b"\x00" + b"ab" * 200_000,   # compressible (first byte 0x00 like
        b"\x00" + os.urandom(250_000),  # real serialized objects)
        b"",
    ]
    for i, payload in enumerate(payloads):
        p = str(tmp_path / f"s{i}")
        spill_codec.write_spill(p, payload)
        assert spill_codec.raw_size(p) == len(payload)
        assert spill_codec.read_bytes(p) == payload
        buf = bytearray(len(payload))
        assert spill_codec.read_into(p, buf, len(payload))
        assert bytes(buf) == payload
        if payload:
            assert spill_codec.read_range(p, 7, 1000) == payload[7:1007]
            assert spill_codec.read_range(p, len(payload) - 9, 50) == \
                payload[-9:]
            # block-crossing range (blocks are 4 MiB; small files are one
            # block, so also cover a multi-block file below)
    big = (b"\x00" + b"xy" * (3 << 20))  # > one 4 MiB block
    p = str(tmp_path / "multi")
    spill_codec.write_spill(p, big)
    off = (4 << 20) - 100
    assert spill_codec.read_range(p, off, 300) == big[off:off + 300]


def test_streaming_spill_write_matches_buffered_layout(tmp_path):
    """The spill path streams serialization.iter_serialized_blocks
    through the codec (peak extra heap = one block); the result must
    deserialize identically to the buffered write_into layout."""
    from ray_tpu.core import serialization

    value = {"a": np.arange(3 << 20, dtype=np.float64),  # 24 MiB buffer
             "b": b"tail" * 1000, "c": list(range(50))}
    data, buffers = serialization.serialize(value)
    size = serialization.serialized_size(data, buffers)
    # streamed chunks re-assemble to EXACTLY the write_into image
    ref = bytearray(size)
    serialization.write_into(memoryview(ref), data, buffers)
    streamed = b"".join(serialization.iter_serialized_blocks(
        data, buffers, spill_codec.BLOCK_RAW))
    assert streamed == bytes(ref)
    # and the codec file round-trips back to the value
    p = str(tmp_path / "stream")
    spill_codec.write_spill_stream(
        p, size, serialization.iter_serialized_blocks(
            data, buffers, spill_codec.BLOCK_RAW))
    assert spill_codec.raw_size(p) == size
    out = bytearray(size)
    assert spill_codec.read_into(p, out, size)
    got = serialization.read_from(memoryview(bytes(out)))
    assert np.array_equal(got["a"], value["a"])
    assert got["b"] == value["b"] and got["c"] == value["c"]


def test_legacy_raw_spill_files_still_read(tmp_path):
    payload = b"\x00" + os.urandom(50_000)
    p = str(tmp_path / "legacy")
    with open(p, "wb") as f:
        f.write(payload)  # headerless pre-r14 spill file
    assert not spill_codec.is_compressed(p)
    assert spill_codec.raw_size(p) == len(payload)
    assert spill_codec.read_bytes(p) == payload
    assert spill_codec.read_range(p, 5, 10) == payload[5:15]


def test_spill_compression_off_writes_raw(tmp_path, monkeypatch):
    monkeypatch.setenv("RTPU_SPILL_COMPRESSION", "off")
    payload = b"\x00" + b"zz" * 100_000
    p = str(tmp_path / "raw")
    n = spill_codec.write_spill(p, payload)
    assert n == len(payload)
    assert not spill_codec.is_compressed(p)
    assert spill_codec.read_bytes(p) == payload


def test_zlib_codec_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("RTPU_SPILL_COMPRESSION", "zlib")
    payload = b"\x00" + b"ab" * 100_000
    p = str(tmp_path / "z")
    n = spill_codec.write_spill(p, payload)
    assert n < len(payload) and spill_codec.is_compressed(p)
    assert spill_codec.read_bytes(p) == payload


# ---------------------------------------------------------------------------
# store-level: compressed spill -> read -> restore, metrics move
# ---------------------------------------------------------------------------


def _metric_total(name):
    from ray_tpu.util.metrics import registry_records

    total = 0.0
    for rec in registry_records():
        if rec["name"] == name:
            for _k, v in rec["samples"]:
                total += v if not isinstance(v, tuple) else v[2]
    return total


def test_compressed_spill_restore_roundtrip(monkeypatch):
    session = "dp-" + os.urandom(4).hex()
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(1 << 20))
    monkeypatch.setenv("RTPU_STORE_CAPACITY", str(1 << 20))
    monkeypatch.setenv("RTPU_STORE_PREFAULT_BYTES", "0")
    sc = StoreClient(session)
    try:
        oid = ObjectID.from_random()
        arr = np.tile(np.arange(512), 8192)  # 32 MiB, compressible
        comp0 = _metric_total(
            "rtpu_object_store_spill_compressed_bytes_total")
        inline, size = sc.put(oid, arr)
        assert inline is None
        path = _spill_path(session, oid)
        assert os.path.exists(path), "object did not spill"
        assert spill_codec.is_compressed(path)
        phys = os.stat(path).st_size
        assert phys < size // 4, "compression should win big here"
        assert _metric_total(
            "rtpu_object_store_spill_compressed_bytes_total") > comp0
        # bytes identical through every read path
        assert np.array_equal(sc.get(oid), arr)
        raw = sc.get_raw(oid)
        assert len(raw) == size
        assert sc.get_raw_chunk(oid, 123, 4567) == raw[123:123 + 4567]
        sc.release(oid)

        # restore: lift the shm pressure and promote back into the arena
        monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(4 << 30))
        sc2 = StoreClient(session)
        r0 = _metric_total("rtpu_object_store_restored_objects_total")
        assert sc2.restore_spilled(oid)
        assert not os.path.exists(path), "spill file kept after restore"
        assert _metric_total(
            "rtpu_object_store_restored_objects_total") > r0
        assert np.array_equal(sc2.get(oid), arr)
        sc2.release(oid)
    finally:
        StoreClient.cleanup_session(session)


def test_compressed_spill_served_without_restore_headroom(monkeypatch):
    """No shm headroom: the compressed spill is inflated to a HEAP pin
    and served, views staying valid until release."""
    session = "dp-" + os.urandom(4).hex()
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", str(1 << 20))
    monkeypatch.setenv("RTPU_STORE_CAPACITY", str(1 << 20))
    monkeypatch.setenv("RTPU_STORE_PREFAULT_BYTES", "0")
    sc = StoreClient(session)
    try:
        oid = ObjectID.from_random()
        arr = np.tile(np.arange(256), 4096)
        sc.put(oid, arr)
        assert spill_codec.is_compressed(_spill_path(session, oid))
        out = sc.get(oid)  # threshold still tiny: restore refused
        assert np.array_equal(out, arr)
        del out
        sc.release(oid)
    finally:
        StoreClient.cleanup_session(session)


# ---------------------------------------------------------------------------
# multi-threaded memcpy
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not _native.pipe_engine_available(),
                    reason="native copy unavailable")
def test_parallel_copy_exact():
    for n in (1 << 10, (1 << 20) + 13, 8 << 20):
        src = os.urandom(n)
        dst = bytearray(n)
        assert _native.parallel_copy(dst, src) == n
        assert bytes(dst) == src


@pytest.mark.skipif(not _native.pipe_engine_available(),
                    reason="native copy unavailable")
def test_store_put_uses_parallel_copy(monkeypatch):
    monkeypatch.setenv("RTPU_STORE_PARALLEL_COPY_BYTES", str(1 << 20))
    monkeypatch.setenv("RTPU_STORE_PREFAULT_BYTES", "0")
    from ray_tpu.core import serialization

    # the threshold is cached; reset so the env override applies
    monkeypatch.setattr(serialization, "_pcopy_min", None)
    session = "dp-" + os.urandom(4).hex()
    sc = StoreClient(session)
    try:
        before = _metric_total(
            "rtpu_object_store_parallel_copy_bytes_total")
        oid = ObjectID.from_random()
        arr = np.random.default_rng(0).standard_normal(1 << 21)  # 16 MiB
        sc.put(oid, arr)
        assert np.array_equal(sc.get(oid), arr)
        sc.release(oid)
        assert _metric_total(
            "rtpu_object_store_parallel_copy_bytes_total") >= \
            before + arr.nbytes
    finally:
        StoreClient.cleanup_session(session)
        monkeypatch.setattr(serialization, "_pcopy_min", None)


# ---------------------------------------------------------------------------
# chunk-parallel cross-node transfer (standalone harness; the cluster
# suite covers the in-situ RPC path)
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self, n):
        self.buf = bytearray(n)

    def write(self, off, data):
        self.buf[off:off + len(data)] = data


def test_pull_chunks_parallel_exact():
    from ray_tpu.cluster.adapter import pull_chunks

    src = os.urandom(9_000_000)
    calls = []

    def call(method, oid_b, off, ln, timeout=None):
        assert method == "pull_chunk"
        calls.append(off)
        return src[off:off + ln]

    w = _Writer(len(src))
    assert pull_chunks(call, b"o" * 16, len(src), w,
                       chunk=1 << 20, parallel=3)
    assert bytes(w.buf) == src
    assert sorted(calls) == list(range(0, len(src), 1 << 20))


def test_pull_chunks_short_chunk_fails_closed():
    from ray_tpu.cluster.adapter import pull_chunks

    src = os.urandom(3_000_000)

    def call(method, oid_b, off, ln, timeout=None):
        blob = src[off:off + ln]
        return blob[:-1] if off else blob  # later chunks come up short

    w = _Writer(len(src))
    assert not pull_chunks(call, b"o" * 16, len(src), w,
                           chunk=1 << 20, parallel=2)


def test_pull_chunks_serial_matches_parallel():
    from ray_tpu.cluster.adapter import pull_chunks

    src = os.urandom(2_500_000)

    def call(method, oid_b, off, ln, timeout=None):
        return src[off:off + ln]

    w1, w2 = _Writer(len(src)), _Writer(len(src))
    assert pull_chunks(call, b"o" * 16, len(src), w1,
                       chunk=1 << 20, parallel=1)
    assert pull_chunks(call, b"o" * 16, len(src), w2,
                       chunk=1 << 20, parallel=4)
    assert bytes(w1.buf) == bytes(w2.buf) == src
