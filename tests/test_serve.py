"""Serve: deploy, route, compose, batch, multiplex, autoscale, HTTP proxy."""

import asyncio
import json
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def rt_serve():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(rt_serve):
    @serve.deployment
    def doubler(x):
        return x * 2

    handle = serve.run(doubler.bind())
    assert handle.remote(21).result() == 42


def test_class_deployment_with_state_and_methods(rt_serve):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.start = start

        def __call__(self, x):
            return self.start + x

        def describe(self):
            return f"counter from {self.start}"

    handle = serve.run(Counter.bind(100))
    assert handle.remote(5).result() == 105
    assert handle.describe.remote().result() == "counter from 100"
    # both replicas registered
    assert serve.status()["Counter"]["num_replicas"] == 2


def test_model_composition(rt_serve):
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Combined:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = self.pre.remote(x).result()
            return y * 10

    handle = serve.run(Combined.bind(Preprocess.bind()))
    assert handle.remote(4).result() == 50


def test_load_balancing_across_replicas(rt_serve):
    import os

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self):
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {handle.remote().result() for _ in range(20)}
    assert len(pids) >= 2  # requests spread over replicas


def test_serve_batch_decorator():
    calls = []

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    async def process(items):
        calls.append(len(items))
        return [i * 2 for i in items]

    async def main():
        outs = await asyncio.gather(*[process(i) for i in range(10)])
        return outs

    outs = asyncio.new_event_loop().run_until_complete(main())
    assert outs == [i * 2 for i in range(10)]
    assert max(calls) > 1  # batching actually happened


def test_multiplexed_lru():
    loaded = []

    class Replica:
        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id):
            loaded.append(model_id)
            return f"model-{model_id}"

    r = Replica()

    async def main():
        a = await r.get_model("a")
        b = await r.get_model("b")
        a2 = await r.get_model("a")   # cache hit
        c = await r.get_model("c")    # evicts b
        b2 = await r.get_model("b")   # reload
        return a, b, a2, c, b2

    out = asyncio.new_event_loop().run_until_complete(main())
    assert out == ("model-a", "model-b", "model-a", "model-c", "model-b")
    assert loaded == ["a", "b", "c", "b"]


def test_autoscaling_scales_up(rt_serve):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 4,
        "target_ongoing_requests": 1.0})
    def work(x=0):
        return x

    handle = serve.run(work.bind())
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    # report high sustained load, then tick
    for _ in range(5):
        ray_tpu.get(ctrl.record_request_metrics.remote("work", 6.0))
    decisions = ray_tpu.get(ctrl.autoscale_tick.remote())
    assert decisions.get("work", 0) >= 2
    assert serve.status()["work"]["num_replicas"] >= 2


def test_http_proxy(rt_serve):
    import http.client

    @serve.deployment
    def echo(payload=None):
        return {"got": payload}

    handle = serve.run(echo.bind())
    proxy = serve.HTTPProxy(port=0)
    proxy.register("echo", handle)
    proxy.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", proxy.port, timeout=30)
        body = json.dumps({"a": 1})
        conn.request("POST", "/echo", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        data = json.loads(resp.read())
        assert data["result"]["got"] == {"a": 1}

        conn = http.client.HTTPConnection("127.0.0.1", proxy.port, timeout=30)
        conn.request("GET", "/")
        resp = conn.getresponse()
        assert json.loads(resp.read())["routes"] == ["echo"]
    finally:
        proxy.stop()


def test_streaming_deployment_handle(rt_serve):
    """handle.options(stream=True) yields results as the replica produces
    them (reference Serve streaming responses)."""
    import time as _t

    from ray_tpu import serve

    @serve.deployment
    class Tokens:
        def __call__(self, n):
            for i in range(int(n)):
                yield f"tok-{i}"
                _t.sleep(0.3)

    handle = serve.run(Tokens.bind(), name="stream_app")
    # warm: one full request
    assert list(handle.options(stream=True).remote(2)) == ["tok-0", "tok-1"]
    t0 = _t.monotonic()
    gen = handle.options(stream=True).remote(4)
    first = next(iter(gen))
    first_latency = _t.monotonic() - t0
    assert first == "tok-0"
    assert first_latency < 1.0, f"first token took {first_latency:.1f}s"
    rest = list(gen)
    assert rest == ["tok-1", "tok-2", "tok-3"]
    serve.delete("stream_app")


def test_http_proxy_streaming_chunks(rt_serve):
    import http.client
    import json as _json

    from ray_tpu import serve

    @serve.deployment
    class Chunks:
        def __call__(self, n):
            for i in range(int(n)):
                yield {"i": i}

    handle = serve.run(Chunks.bind(), name="chunks_app")
    proxy = serve.HTTPProxy(port=0)
    proxy.register("chunks", handle)
    proxy.start()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    conn.request("POST", "/chunks?stream=1", body=b"3")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "application/x-ndjson"
    lines = [l for l in resp.read().decode().strip().splitlines() if l]
    assert [_json.loads(l)["result"]["i"] for l in lines] == [0, 1, 2]
    conn.close()
    proxy.stop()
    serve.delete("chunks_app")


def test_router_uses_shared_queue_depths(rt_serve):
    """Two handles must share the replicas' true queue depths — the r1
    per-handle view let independent handles pile onto one replica."""
    import time as _t

    from ray_tpu import serve
    from ray_tpu.serve.handle import DeploymentHandle

    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Slow:
        def __call__(self):
            _t.sleep(1.0)
            return "ok"

    serve.run(Slow.bind(), name="depth_app")
    h1 = serve.get_deployment_handle("Slow")
    h2 = serve.get_deployment_handle("Slow")
    assert h1 is not h2
    # saturate replica views via h1, then h2 must see the load
    rs = [h1.remote() for _ in range(4)]
    _t.sleep(0.3)
    h2._refresh()
    load = h2._load_view()
    assert sum(load) >= 2, f"h2 blind to h1's load: {load}"
    for r in rs:
        r.result(timeout_s=60)
    serve.delete("depth_app")


def test_http_proxy_keepalive_and_methods(rt_serve):
    """HTTP/1.1 conformance the reference gets from uvicorn: keep-alive
    reuses one connection for several exchanges; chunked request bodies
    parse; disallowed methods 405; oversized bodies 413 (VERDICT r3 #10)."""
    import http.client

    from ray_tpu import serve

    @serve.deployment
    def echo2(payload=None):
        return {"got": payload}

    handle = serve.run(echo2.bind(), name="ka_app")
    proxy = serve.HTTPProxy(port=0)
    proxy.register("echo2", handle)
    proxy.start()
    try:
        # three exchanges over ONE connection
        conn = http.client.HTTPConnection("127.0.0.1", proxy.port,
                                          timeout=30)
        for i in range(3):
            conn.request("POST", "/echo2", body=json.dumps({"i": i}))
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Connection") == "keep-alive"
            assert json.loads(resp.read())["result"]["got"] == {"i": i}

        # chunked request body on the same connection
        conn.putrequest("POST", "/echo2")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        payload = json.dumps({"chunked": True}).encode()
        conn.send(f"{len(payload):x}\r\n".encode() + payload + b"\r\n")
        conn.send(b"0\r\n\r\n")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["result"]["got"] == {"chunked": True}

        # 405 keeps the connection alive
        conn.request("PATCH", "/echo2", body="{}")
        resp = conn.getresponse()
        assert resp.status == 405
        assert "Allow" in dict(resp.getheaders())
        resp.read()

        # still usable afterwards
        conn.request("GET", "/")
        assert json.loads(conn.getresponse().read())["routes"] == ["echo2"]
        conn.close()

        # 413: body over the cap is refused without reading it
        import ray_tpu.serve.proxy as proxy_mod

        old_cap = proxy_mod.MAX_BODY
        proxy_mod.MAX_BODY = 1024
        try:
            c2 = http.client.HTTPConnection("127.0.0.1", proxy.port,
                                            timeout=30)
            c2.request("POST", "/echo2", body=b"x" * 4096)
            assert c2.getresponse().status == 413
            c2.close()
        finally:
            proxy_mod.MAX_BODY = old_cap

        # malformed request line -> 400
        import socket

        s = socket.create_connection(("127.0.0.1", proxy.port), timeout=10)
        s.sendall(b"NONSENSE\r\n\r\n")
        assert b"400" in s.recv(200)
        s.close()
    finally:
        proxy.stop()
        serve.delete("ka_app")


def test_grpc_proxy_unary_and_stream(rt_serve):
    """gRPC ingress with the same routing as HTTP (reference gRPCProxy,
    proxy.py:534 role): unary predict, streaming predict, health, 404."""
    import grpc

    from ray_tpu import serve

    @serve.deployment
    def g_unary(n=None):
        return {"pong": True}

    @serve.deployment
    class GStream:
        def __call__(self, n):
            for i in range(int(n)):
                yield {"i": i}

    handle = serve.run(g_unary.bind(), name="grpc_app")
    shandle = serve.run(GStream.bind(), name="grpc_stream_app")
    gp = serve.GrpcProxy(port=0)
    gp.register("g", handle)
    gp.register("gs", shandle)
    gp.start()
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{gp.port}")
        predict = ch.unary_unary("/ray_tpu.serve.ServeAPI/Predict")
        stream = ch.unary_stream("/ray_tpu.serve.ServeAPI/PredictStream")
        healthz = ch.unary_unary("/ray_tpu.serve.ServeAPI/Healthz")
        listdep = ch.unary_unary("/ray_tpu.serve.ServeAPI/ListDeployments")

        assert json.loads(healthz(b"{}")) == {"status": "ok"}
        assert json.loads(listdep(b"{}"))["deployments"] == ["g", "gs"]

        out = json.loads(predict(json.dumps({"deployment": "g"}).encode()))
        assert out["result"] == {"pong": True}

        items = [json.loads(b)["result"] for b in stream(
            json.dumps({"deployment": "gs", "arg": 3}).encode())]
        assert items == [{"i": 0}, {"i": 1}, {"i": 2}]

        try:
            predict(json.dumps({"deployment": "nope"}).encode())
            raise AssertionError("expected NOT_FOUND")
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.NOT_FOUND
        ch.close()
    finally:
        gp.stop()
        serve.delete("grpc_app")
        serve.delete("grpc_stream_app")


def test_serve_config_deploy_and_rest(rt_serve, tmp_path, monkeypatch):
    """Declarative YAML deploy + dashboard REST surface (reference serve
    CLI `serve deploy` / dashboard serve module roles)."""
    import http.client
    import sys
    import textwrap

    mod = tmp_path / "demo_serve_app.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment
        class Hello:
            def __call__(self, payload=None):
                return {"hello": payload}

        app = Hello.bind()
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    cfg_path = tmp_path / "serve.yaml"
    cfg_path.write_text(textwrap.dedent("""
        applications:
          - name: hello
            import_path: demo_serve_app:app
            route_prefix: /hello
            deployments:
              - name: Hello
                num_replicas: 2
    """))
    from ray_tpu.serve.config_api import deploy_config, load_config

    cfg = load_config(str(cfg_path))
    assert deploy_config(cfg) == ["hello"]
    h = serve.get_deployment_handle("Hello")
    assert h.remote(payload=1).result(timeout_s=60) == {"hello": 1}
    # 2 replicas took effect (reconcile may lag a moment)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if serve.status()["Hello"]["num_replicas"] == 2:
            break
        time.sleep(0.2)
    assert serve.status()["Hello"]["num_replicas"] == 2

    # REST: GET status, then PUT a JSON config against the dashboard
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", dash.port, timeout=30)
        conn.request("GET", "/api/serve/applications")
        resp = conn.getresponse()
        assert resp.status == 200
        payload = json.loads(resp.read())["result"]
        assert "Hello" in payload["applications"]

        put_cfg = {"applications": [
            {"name": "hello2", "import_path": "demo_serve_app:app",
             "route_prefix": "/hello2"}]}
        conn.request("PUT", "/api/serve/applications",
                     body=json.dumps(put_cfg),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["result"]["deployed"] == ["hello2"]
    finally:
        stop_dashboard()


def test_http_proxy_sustained_load(rt_serve):
    """Load test of the data plane (VERDICT r3 weak #5): concurrent
    keep-alive clients; asserts correctness under load plus sane latency
    quantiles on this 2-vCPU box."""
    import http.client
    import threading

    @serve.deployment(num_replicas=2)
    def echo(payload=None):
        return {"n": payload}

    handle = serve.run(echo.bind())
    proxy = serve.HTTPProxy(port=0)
    proxy.register("echo", handle)
    proxy.start()
    n_clients, n_reqs = 4, 40
    latencies, errors = [], []
    lock = threading.Lock()

    def client(cid):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", proxy.port,
                                              timeout=60)
            for i in range(n_reqs):
                t0 = time.perf_counter()
                conn.request("POST", "/echo", body=json.dumps(cid * 1000 + i),
                             headers={"Connection": "keep-alive"})
                resp = conn.getresponse()
                data = json.loads(resp.read())
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
                    if (resp.status != 200
                            or data["result"]["n"] != cid * 1000 + i):
                        errors.append((cid, i, resp.status, data))
        except Exception as e:
            with lock:
                errors.append((cid, "exc", str(e)))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    proxy.stop()
    assert not errors, errors[:5]
    lat = sorted(latencies)
    p50 = lat[len(lat) // 2]
    p99 = lat[int(len(lat) * 0.99)]
    rps = len(lat) / wall
    print(f"serve load: {rps:.0f} rps, p50={p50*1e3:.1f}ms, "
          f"p99={p99*1e3:.1f}ms")
    # generous bounds for a 2-vCPU CI box; the point is no collapse
    assert p50 < 0.5 and p99 < 5.0 and rps > 20


def test_llm_continuous_batching_deployment(rt_serve):
    """VERDICT r4 #8 done-criterion: 8 concurrent prompts of different
    lengths share one slot engine, token streams interleave (every
    stream's first token lands before the earliest stream finishes), the
    deployment reports aggregate stats, and each greedy stream is token-
    exact vs the sequential models.generate reference."""
    import dataclasses
    import threading

    import numpy as np

    import jax

    from ray_tpu import models
    from ray_tpu.models import transformer as T
    from ray_tpu.serve import LLMDeployment

    # f32 for token-exact greedy parity: in bf16 the tiny debug model
    # produces exact top-2 logit TIES, and the paged engine's gather-
    # based attention rounds a ULP differently than the dense reference
    # kernels — a tie-break flip, not a numerics bug (ISSUE 12)
    cfg = dataclasses.replace(models.get_config("llama-debug"),
                              dtype="float32", param_dtype="float32")
    app = serve.deployment(
        LLMDeployment,
        ray_actor_options={"max_concurrency": 16, "num_cpus": 0},
    ).bind(cfg, max_slots=8, max_len=64, seed=0)
    handle = serve.run(app, name="llm_cb")

    rng = np.random.default_rng(0)
    lens = (3, 5, 7, 9, 4, 6, 8, 10)
    prompts = [rng.integers(0, 256, p).tolist() for p in lens]
    list(handle.options(stream=True).remote(prompts[0], 2))  # warm/compile

    results = [None] * 8
    first_ts = [None] * 8
    last_ts = [None] * 8

    def worker(i):
        toks = []
        for tok in handle.options(stream=True).remote(prompts[i], 8):
            if first_ts[i] is None:
                first_ts[i] = time.monotonic()
            toks.append(tok)
        last_ts[i] = time.monotonic()
        results[i] = toks

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(r is not None and len(r) == 8 for r in results), results
    # interleaving: engine-level evidence (deterministic on a loaded
    # 2-vCPU box, unlike wall-clock overlap of sub-100ms streams) — the
    # slot engine actually held many requests in flight at once
    stats = handle.options(method_name="stats",
                           stream=False).remote().result()
    assert stats["max_concurrent"] >= 6, stats
    assert stats["tokens_generated"] >= 8 * 8

    # greedy parity: each stream equals the sequential generate reference
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    for i, pr in enumerate(prompts):
        g = T.generate(params, jax.numpy.asarray(
            np.asarray(pr, np.int32)[None]), cfg, max_new_tokens=8)
        want = [int(x) for x in np.asarray(g[0, len(pr):])]
        assert results[i] == want, (i, results[i], want)
    serve.delete("llm_cb")


def test_request_trace_chain_and_critical_path(rt_serve):
    """ISSUE 7: one handle request produces the full route -> (queue gap)
    -> actor-call execute -> replica-execute span chain under ONE trace
    id, and summarize_critical_path(trace_id) attributes the request's
    end-to-end time to segments that sum to it exactly."""
    from ray_tpu.util import state, tracing

    tracing.enable_tracing()
    try:
        @serve.deployment
        def traced_echo(x):
            time.sleep(0.05)
            return x

        handle = serve.run(traced_echo.bind())
        assert handle.remote(7).result() == 7

        def chain():
            # keep issuing so worker span pushes fire promptly; each
            # request produces its own complete chain
            handle.remote(1).result()
            spans = state.list_spans()
            reqs = [s for s in spans
                    if s["name"] == "serve.handle::request"]
            for req in reversed(reqs):
                trace = [s for s in spans
                         if s["trace_id"] == req["trace_id"]]
                names = {s["name"] for s in trace}
                if ("serve.handle::route" in names
                        and "serve.replica::execute" in names
                        and any(n.startswith("execute::")
                                for n in names)):
                    return trace
            return None

        deadline = time.monotonic() + 60
        trace = None
        while time.monotonic() < deadline and trace is None:
            trace = chain()
            if trace is None:
                time.sleep(0.3)
        assert trace is not None, "no complete request span chain arrived"

        res = state.summarize_critical_path(
            trace_id=trace[0]["trace_id"])
        segs = res["segments"]
        assert segs, res
        # segments reconcile exactly against the end-to-end time
        total = sum(s["ms"] for s in segs.values())
        assert total == pytest.approx(res["end_to_end_ms"], abs=0.01)
        # the replica's user code (50ms sleep) is attributed, not lost in
        # a gap — generous bound for a loaded 2-vCPU box
        replica = [v["ms"] for k, v in segs.items()
                   if k.startswith("serve.replica::execute")]
        assert replica and replica[0] >= 30.0, segs
        # end-to-end is the request span: at least the replica sleep
        assert res["end_to_end_ms"] >= 40.0
    finally:
        tracing.disable_tracing()
        from ray_tpu.util import tracing as _t
        _t._reset_for_tests()
        import os as _os
        _os.environ.pop("RTPU_TRACING", None)


def test_compiled_deployment_steady_state_and_replica_death(rt_serve):
    """compiled=True routes steady-state requests through a per-replica
    compiled DAG (no per-call task submission); killing a replica falls
    back to a normally-routed call with no caller-visible error, and the
    controller reconciles a replacement."""
    from conftest import poll_until

    @serve.deployment(num_replicas=2, compiled=True)
    class Echo:
        def __init__(self, base):
            self.base = base

        def __call__(self, x):
            return self.base + x

        def describe(self):
            return "echo"

    handle = serve.run(Echo.bind(100))
    # steady state: many requests, all correct, DAGs built per replica
    results = [handle.remote(i) for i in range(30)]
    assert [r.result(timeout_s=60) for r in results] == [
        100 + i for i in range(30)]
    assert handle._dags, "compiled path built no DAGs"
    # non-default method CLONE stays on the compiled plane (options()
    # must carry _compiled; the response type proves the routing)
    from ray_tpu.serve.handle import CompiledDeploymentResponse

    resp = handle.describe.remote()
    assert isinstance(resp, CompiledDeploymentResponse), type(resp)
    assert resp.result(timeout_s=60) == "echo"

    # replica death: requests keep succeeding (broken-DAG fallback
    # re-routes + reports), controller replaces the dead replica
    victim = handle._replicas[0]
    ray_tpu.kill(victim)
    vals = [handle.remote(i).result(timeout_s=60) for i in range(20)]
    assert vals == [100 + i for i in range(20)]

    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    deps = poll_until(
        lambda: (ray_tpu.get(ctrl.list_deployments.remote())
                 if ray_tpu.get(
                     ctrl.list_deployments.remote())["Echo"][
                         "num_replicas"] == 2 else None),
        timeout=60, desc="controller reconciled replacement replica")
    assert deps["Echo"]["num_replicas"] == 2
