"""Core robustness: runtime_env, spilling, memory monitor, retries."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.memory_monitor import MemoryMonitor, system_memory
from ray_tpu.core.object_store import StoreClient


@pytest.fixture
def rt_rob():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_task_runtime_env_env_vars(rt_rob):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("RTPU_TEST_VAR")

    assert ray_tpu.get(read_env.remote()) is None
    with_env = read_env.options(
        runtime_env={"env_vars": {"RTPU_TEST_VAR": "hello"}})
    assert ray_tpu.get(with_env.remote()) == "hello"
    # env is restored for subsequent tasks on the same worker
    assert ray_tpu.get(read_env.remote()) is None


def test_task_runtime_env_working_dir(rt_rob, tmp_path):
    (tmp_path / "marker.txt").write_text("found")

    @ray_tpu.remote
    def read_marker():
        return open("marker.txt").read()

    task = read_marker.options(runtime_env={"working_dir": str(tmp_path)})
    assert ray_tpu.get(task.remote()) == "found"


def test_bad_working_dir_fails_task_not_worker(rt_rob):
    @ray_tpu.remote
    def fine():
        return "ok"

    bad = fine.options(runtime_env={"working_dir": "/does/not/exist"})
    from ray_tpu.core.exceptions import TaskError

    with pytest.raises(TaskError):
        ray_tpu.get(bad.remote(), timeout=30)
    # worker survived; subsequent tasks run normally
    assert ray_tpu.get(fine.remote(), timeout=30) == "ok"


def test_runtime_env_sys_path_restored(rt_rob, tmp_path):
    (tmp_path / "probe_mod.py").write_text("VALUE = 'from_tmp'\n")

    @ray_tpu.remote
    def uses_wd():
        import probe_mod

        return probe_mod.VALUE

    task = uses_wd.options(runtime_env={"working_dir": str(tmp_path)})
    assert ray_tpu.get(task.remote()) == "from_tmp"

    @ray_tpu.remote
    def path_has(entry):
        import sys

        return entry in sys.path

    # run enough probes to cover every pool worker
    checks = ray_tpu.get([path_has.remote(str(tmp_path)) for _ in range(8)])
    assert not any(checks)


def test_actor_runtime_env_persistent(rt_rob):
    @ray_tpu.remote
    class EnvActor:
        def get(self):
            return os.environ.get("RTPU_ACTOR_VAR")

    a = EnvActor.options(
        runtime_env={"env_vars": {"RTPU_ACTOR_VAR": "persistent"}}).remote()
    assert ray_tpu.get(a.get.remote()) == "persistent"
    assert ray_tpu.get(a.get.remote()) == "persistent"


def test_spilling_to_disk(monkeypatch):
    import uuid

    session = uuid.uuid4().hex[:12]
    monkeypatch.setenv("RTPU_SPILL_THRESHOLD", "1")   # spill everything big
    monkeypatch.setenv("RTPU_NATIVE_STORE", "0")      # force the file path
    client = StoreClient(session)
    try:
        oid = ObjectID.from_random()
        data = np.arange(50_000, dtype=np.float64)
        inline, size = client.put(oid, data)
        assert inline is None                         # too big to inline
        assert size >= data.nbytes
        assert client.contains_spilled(oid)           # landed on disk
        assert not os.path.exists(
            f"/dev/shm/rtpu-{session}-{oid.hex()}")
        back = client.get(oid)
        np.testing.assert_array_equal(back, data)
        del back
        client.release(oid)
        client.delete(oid)
        assert not client.contains_spilled(oid)
    finally:
        StoreClient.cleanup_session(session)


def test_memory_monitor_fires_on_threshold():
    fired = []
    mon = MemoryMonitor(usage_threshold=0.0,     # always over
                        on_pressure=lambda mem: fired.append(mem))
    assert mon.check()
    assert fired and fired[0]["total"] > 0
    mon2 = MemoryMonitor(usage_threshold=1.01)   # never over
    assert not mon2.check()


def test_system_memory_sane():
    mem = system_memory()
    assert mem["total"] > (1 << 28)
    assert 0.0 <= mem["used_fraction"] <= 1.0


def test_actor_restart_after_death(rt_rob):
    @ray_tpu.remote
    class Fragile:
        def __init__(self):
            self.count = 0

        def incr(self):
            self.count += 1
            return self.count

        def crash(self):
            import os as _os

            _os._exit(1)

    a = Fragile.options(max_restarts=1).remote()
    assert ray_tpu.get(a.incr.remote(), timeout=30) == 1
    a.crash.remote()
    # restarted actor: fresh state, same handle keeps working
    deadline = __import__("time").time() + 30
    value = None
    while __import__("time").time() < deadline:
        try:
            value = ray_tpu.get(a.incr.remote(), timeout=10)
            break
        except Exception:
            __import__("time").sleep(0.2)
    assert value == 1, f"actor did not restart cleanly (got {value})"


def test_task_retry_after_worker_death(rt_rob, tmp_path):
    marker = tmp_path / "attempted"

    @ray_tpu.remote
    def flaky(marker_path):
        import os as _os

        if not _os.path.exists(marker_path):
            open(marker_path, "w").close()
            _os._exit(1)          # simulate worker crash
        return "recovered"

    ref = flaky.options(max_retries=2).remote(str(marker))
    assert ray_tpu.get(ref, timeout=60) == "recovered"


def test_lineage_reconstruction_driver_get(rt_rob):
    """Delete a task result's segment behind the store's back: get() must
    re-execute the producer and return the value (reference
    object_recovery_manager.h:41 / task_manager.h:468)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core.runtime import _get_runtime

    calls = []

    @ray_tpu.remote
    def produce(tag):
        import os
        return np.full(1 << 15, 7.5)  # 256 KiB: store segment, not inline

    ref = produce.remote("x")
    first = ray_tpu.get(ref)
    assert first.sum() == 7.5 * (1 << 15)

    rt_obj = _get_runtime()
    rt_obj.store.delete(ref.id)            # lose the segment
    rt_obj.gcs.objects[ref.id].inline = None
    again = ray_tpu.get(ref, timeout=60)   # must reconstruct via lineage
    assert again.sum() == 7.5 * (1 << 15)


def test_lineage_reconstruction_as_dependency(rt_rob):
    """A worker hitting a lost dependency asks the driver to re-execute the
    producer, then the dependent task completes."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core.runtime import _get_runtime

    @ray_tpu.remote
    def produce():
        return np.arange(1 << 15, dtype=np.float64)

    @ray_tpu.remote
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    _get_runtime().store.delete(ref.id)    # lose it before consumption
    expect = float(np.arange(1 << 15, dtype=np.float64).sum())
    assert ray_tpu.get(consume.remote(ref), timeout=90) == expect


def test_lineage_absent_for_put_objects(rt_rob):
    """ray_tpu.put objects have no lineage: losing them is a real error
    (reference: puts are not reconstructable)."""
    import numpy as np
    import pytest as _pytest

    import ray_tpu
    from ray_tpu.core.runtime import _get_runtime

    ref = ray_tpu.put(np.zeros(1 << 15))
    _get_runtime().store.delete(ref.id)
    with _pytest.raises((FileNotFoundError, OSError)):
        ray_tpu.get(ref, timeout=10)


def test_chaos_random_worker_kills_under_load(rt_rob):
    """Fault-injection soak (reference WorkerKillerActor pattern,
    python/ray/_private/test_utils.py:1560 role): an external killer
    SIGKILLs random busy workers while a burst of retryable tasks runs;
    every task must still complete with the right answer."""
    import random
    import signal
    import threading
    import time as _t

    from ray_tpu.core.runtime import _get_runtime

    @ray_tpu.remote(max_retries=4)
    def work(i):
        import time as _tt

        _tt.sleep(0.15)
        return i * i

    # warm the pool so the killer has victims from the start
    ray_tpu.get([work.remote(i) for i in range(8)])

    rt = _get_runtime()
    stop = threading.Event()
    kills = []

    def killer():
        rng = random.Random(0)
        while not stop.is_set():
            _t.sleep(0.4)
            with rt.lock:
                busy = [ws for ws in rt.workers.values()
                        if ws.kind == "pool" and ws.status == "busy"
                        and ws.proc.poll() is None]
            if busy:
                victim = rng.choice(busy)
                try:
                    victim.proc.kill()
                    kills.append(victim.worker_id.hex()[:8])
                except Exception:
                    pass

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    try:
        refs = [work.remote(i) for i in range(60)]
        results = ray_tpu.get(refs, timeout=180)
    finally:
        stop.set()
        t.join(timeout=5)
    assert results == [i * i for i in range(60)]
    assert kills, "the killer never fired; the soak proved nothing"


def _build_tiny_wheel(wheel_dir, name="rtpu_testpkg", version="0.1"):
    """Hand-rolled wheel (no network, no build backend): a wheel is a zip
    with the package + dist-info metadata."""
    import base64
    import hashlib
    import zipfile

    os.makedirs(wheel_dir, exist_ok=True)
    whl = os.path.join(wheel_dir, f"{name}-{version}-py3-none-any.whl")
    files = {
        f"{name}/__init__.py": f"MAGIC = 'wheel-{version}'\n",
        f"{name}-{version}.dist-info/METADATA":
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n",
        f"{name}-{version}.dist-info/WHEEL":
            "Wheel-Version: 1.0\nGenerator: rtpu-test\nRoot-Is-Purelib: "
            "true\nTag: py3-none-any\n",
    }
    record_rows = []
    for path, text in files.items():
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(text.encode()).digest()).rstrip(b"=").decode()
        record_rows.append(f"{path},sha256={digest},{len(text.encode())}")
    record_rows.append(f"{name}-{version}.dist-info/RECORD,,")
    with zipfile.ZipFile(whl, "w") as zf:
        for path, text in files.items():
            zf.writestr(path, text)
        zf.writestr(f"{name}-{version}.dist-info/RECORD",
                    "\n".join(record_rows) + "\n")
    return whl


def test_pip_runtime_env_venv_isolation_and_cache(rt_rob, tmp_path,
                                                  monkeypatch):
    """VERDICT r4 #5 done-criteria: a pip runtime_env installs a wheel
    into a cached per-hash venv; the task imports it, the driver env is
    untouched, and the second use hits the cache (no reinstall)."""
    import importlib

    wheel_dir = str(tmp_path / "wheels")
    _build_tiny_wheel(wheel_dir)
    env_root = str(tmp_path / "pip-envs")
    monkeypatch.setenv("RTPU_PIP_ENV_DIR", env_root)

    renv = {"pip": {"packages": ["rtpu_testpkg==0.1"],
                    "pip_args": ["--no-index", "--find-links", wheel_dir]},
            # workers inherit the cache root via env_vars (the fixture's
            # workers predate the monkeypatch)
            "env_vars": {"RTPU_PIP_ENV_DIR": env_root}}

    @ray_tpu.remote
    def use_pkg():
        import rtpu_testpkg

        return os.getpid(), rtpu_testpkg.MAGIC, rtpu_testpkg.__file__

    pkg_pid, magic, path = ray_tpu.get(
        use_pkg.options(runtime_env=renv).remote(), timeout=120)
    assert magic == "wheel-0.1"
    assert env_root in path  # imported from the venv, not the image

    # driver env untouched
    with pytest.raises(ImportError):
        importlib.import_module("rtpu_testpkg")

    # a task WITHOUT the env cannot see the package (undo worked). The
    # assertion is only meaningful on the worker that APPLIED the env, so
    # retry until the scheduler lands the probe on that same pid (any
    # other worker is trivially isolated). poll_until, not a fixed-count
    # loop: under suite load the probe can land elsewhere for many
    # seconds straight (r10 flake — 2 vCPUs, every pool worker busy),
    # and transient ConnectionErrors must retry rather than fail.
    @ray_tpu.remote
    def cannot_import():
        try:
            import rtpu_testpkg  # noqa: F401
            return os.getpid(), "leaked"
        except ImportError:
            return os.getpid(), "isolated"

    from conftest import poll_until

    def _probe_venv_worker():
        pid, status = ray_tpu.get(cannot_import.remote(), timeout=60)
        return status if pid == pkg_pid else None

    status = poll_until(_probe_venv_worker, timeout=90, interval=0.05,
                        desc=f"probe landing on pip-env worker {pkg_pid}")
    assert status == "isolated"

    # second use hits the cache: .ready mtime unchanged, and fast
    envs = [d for d in os.listdir(env_root) if d.startswith("pipenv-")
            and not d.endswith(".lock")]
    assert len(envs) == 1
    ready = os.path.join(env_root, envs[0], ".ready")
    mtime = os.path.getmtime(ready)
    _, magic2, _ = ray_tpu.get(
        use_pkg.options(runtime_env=renv).remote(), timeout=60)
    assert magic2 == "wheel-0.1"
    assert os.path.getmtime(ready) == mtime  # no reinstall

    # same requirements in a different order -> same env URI (hash of the
    # SORTED spec), still one venv on disk
    from ray_tpu.runtime_env import normalize_pip_env

    a = normalize_pip_env(["x==1", "y==2"])
    b = normalize_pip_env(["y==2", "x==1"])
    assert a["uri"] == b["uri"]

    # conda stays rejected loudly
    @ray_tpu.remote
    def nope():
        return 1

    with pytest.raises(ValueError, match="conda"):
        nope.options(runtime_env={"conda": ["x"]}).remote()
