"""STORE-backend collectives between actors, XLA group on local devices."""

import numpy as np
import pytest


def test_store_collective_between_actors(rt_module):
    rt = rt_module
    from ray_tpu.collective import create_collective_group

    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def setup(self):
            import ray_tpu.collective as col
            col.init_collective_group(self.world, self.rank, "store", "g1")
            return True

        def do_allreduce(self):
            import ray_tpu.collective as col
            out = col.allreduce(np.full((4,), float(self.rank + 1)), "g1")
            return out

        def do_bcast_gather(self):
            import ray_tpu.collective as col
            b = col.broadcast(np.full((2,), float(self.rank)), 1, "g1")
            g = col.allgather(np.array([self.rank]), "g1")
            return b, [np.asarray(x) for x in g]

        def do_p2p(self):
            import ray_tpu.collective as col
            if self.rank == 0:
                col.send(np.array([42.0]), 1, "g1")
                return None
            if self.rank == 1:
                return col.recv(0, "g1")
            return None

    world = 3
    create_collective_group([], world, list(range(world)), "store", "g1")
    members = [rt.remote(Member).remote(r, world) for r in range(world)]
    assert all(rt.get([m.setup.remote() for m in members]))

    outs = rt.get([m.do_allreduce.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 6.0))

    outs = rt.get([m.do_bcast_gather.remote() for m in members])
    for b, g in outs:
        np.testing.assert_allclose(b, np.full((2,), 1.0))
        np.testing.assert_allclose(np.concatenate(g), [0, 1, 2])

    outs = rt.get([m.do_p2p.remote() for m in members])
    np.testing.assert_allclose(outs[1], [42.0])


def test_xla_group_local_devices():
    import jax
    from ray_tpu.collective.collective import XlaGroup
    from ray_tpu.collective.types import ReduceOp

    n = len(jax.local_devices())
    g = XlaGroup(n, 0, "local")
    tensors = [np.full((8, 128), float(i)) for i in range(n)]
    out = g.allreduce(tensors)
    expect = sum(range(n))
    for o in out:
        np.testing.assert_allclose(o, np.full((8, 128), float(expect)))

    gathered = g.allgather([np.full((1, 128), float(i)) for i in range(n)])
    assert np.asarray(gathered[0]).shape == (n, 128)
