"""STORE-backend collectives between actors, XLA group on local devices."""

import numpy as np
import pytest


def test_store_collective_between_actors(rt_module):
    rt = rt_module
    from ray_tpu.collective import create_collective_group

    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def setup(self):
            import ray_tpu.collective as col
            col.init_collective_group(self.world, self.rank, "store", "g1")
            return True

        def do_allreduce(self):
            import ray_tpu.collective as col
            out = col.allreduce(np.full((4,), float(self.rank + 1)), "g1")
            return out

        def do_bcast_gather(self):
            import ray_tpu.collective as col
            b = col.broadcast(np.full((2,), float(self.rank)), 1, "g1")
            g = col.allgather(np.array([self.rank]), "g1")
            return b, [np.asarray(x) for x in g]

        def do_p2p(self):
            import ray_tpu.collective as col
            if self.rank == 0:
                col.send(np.array([42.0]), 1, "g1")
                return None
            if self.rank == 1:
                return col.recv(0, "g1")
            return None

    world = 3
    create_collective_group([], world, list(range(world)), "store", "g1")
    members = [rt.remote(Member).remote(r, world) for r in range(world)]
    assert all(rt.get([m.setup.remote() for m in members]))

    outs = rt.get([m.do_allreduce.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 6.0))

    outs = rt.get([m.do_bcast_gather.remote() for m in members])
    for b, g in outs:
        np.testing.assert_allclose(b, np.full((2,), 1.0))
        np.testing.assert_allclose(np.concatenate(g), [0, 1, 2])

    outs = rt.get([m.do_p2p.remote() for m in members])
    np.testing.assert_allclose(outs[1], [42.0])


def test_xla_group_local_devices():
    import jax
    from ray_tpu.collective.collective import XlaGroup
    from ray_tpu.collective.types import ReduceOp

    n = len(jax.local_devices())
    g = XlaGroup(n, 0, "local")
    tensors = [np.full((8, 128), float(i)) for i in range(n)]
    out = g.allreduce(tensors)
    expect = sum(range(n))
    for o in out:
        np.testing.assert_allclose(o, np.full((8, 128), float(expect)))

    gathered = g.allgather([np.full((1, 128), float(i)) for i in range(n)])
    assert np.asarray(gathered[0]).shape == (n, 128)


def test_xla_group_full_verb_matrix():
    """Verb parity with the reference device-collective surface
    (python/ray/util/collective/collective.py:311-594) on the 8-device
    CPU mesh: reduce, broadcast, permute (send/recv), alltoall."""
    import jax

    from ray_tpu.collective.collective import XlaGroup
    from ray_tpu.collective.types import ReduceOp

    n = jax.device_count()
    assert n == 8
    g = XlaGroup(n, 0, "matrix")
    tensors = [np.full((4,), float(i + 1), np.float32) for i in range(n)]

    # reduce: only the root holds the sum; others keep their input
    out = g.reduce(tensors, root_rank=2, op=ReduceOp.SUM)
    np.testing.assert_allclose(out[2], np.full((4,), sum(range(1, n + 1))))
    for i in (0, 1, 3, 7):
        np.testing.assert_allclose(out[i], tensors[i])

    # reduce with MAX
    out = g.reduce(tensors, root_rank=0, op=ReduceOp.MAX)
    np.testing.assert_allclose(out[0], np.full((4,), float(n)))

    # broadcast from root 3: everyone has root's tensor
    out = g.broadcast(tensors, root_rank=3)
    for i in range(n):
        np.testing.assert_allclose(out[i], tensors[3])

    # send/recv as ppermute: 1 -> 6, 0 -> 7; everyone else unchanged
    out = g.permute(tensors, [(1, 6), (0, 7)])
    np.testing.assert_allclose(out[6], tensors[1])
    np.testing.assert_allclose(out[7], tensors[0])
    np.testing.assert_allclose(out[0], tensors[0])
    np.testing.assert_allclose(out[5], tensors[5])

    # send() sugar
    out = g.send(tensors, dst_rank=4, src_rank=2)
    np.testing.assert_allclose(out[4], tensors[2])

    # alltoall: device i ends with everyone's chunk i
    chunk_lists = [[np.full((2,), 10 * i + j, np.float32) for j in range(n)]
                   for i in range(n)]
    out = g.alltoall(chunk_lists)
    for i in range(n):
        for j in range(n):
            np.testing.assert_allclose(out[i][j], chunk_lists[j][i])

    # existing verbs still in place
    out = g.allreduce(tensors, op=ReduceOp.MEAN)
    np.testing.assert_allclose(out[0], np.full((4,), (n + 1) / 2))


def test_xla_distributed_group_two_processes(rt_module):
    """Verb matrix across TWO actor PROCESSES x 4 virtual CPU devices each,
    in-XLA over one global jax.distributed mesh (VERDICT r3 #7 done
    criterion; reference NCCLGroup role). Rendezvous rides the named
    coordinator actor."""
    rt = rt_module
    from ray_tpu.collective import create_collective_group

    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def setup(self):
            import jax

            from ray_tpu.collective.collective import init_collective_group

            g = init_collective_group(self.world, self.rank,
                                      "xla_distributed", "gd1")
            return (jax.process_count(), jax.device_count(),
                    jax.local_device_count())

        def verbs(self):
            import numpy as np

            from ray_tpu.collective.collective import get_collective_group
            from ray_tpu.collective.types import ReduceOp

            g = get_collective_group("gd1")
            nloc = 4
            base = self.rank * nloc
            mine = [np.full((2,), float(base + i)) for i in range(nloc)]
            out = {}
            out["allreduce"] = g.allreduce(mine)  # sum over 8 global devs
            out["allgather"] = g.allgather(mine)
            out["bcast"] = g.broadcast(mine, root_rank=5)
            out["reduce"] = g.reduce(mine, root_rank=2)
            out["rscatter"] = g.reducescatter(
                [np.arange(8, dtype=np.float64) for _ in range(nloc)])
            chunks = [[np.full((1,), float(base + i) * 10 + j)
                       for j in range(8)] for i in range(nloc)]
            out["alltoall"] = g.alltoall(chunks)
            g.barrier()
            return out

    world = 2
    create_collective_group([], world, [0, 1], "xla_distributed", "gd1")
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    members = [
        rt.remote(Member).options(
            runtime_env={"env_vars": env}).remote(r, world)
        for r in range(world)
    ]
    infos = rt.get([m.setup.remote() for m in members], timeout=180)
    assert infos == [(2, 8, 4), (2, 8, 4)]

    outs = rt.get([m.verbs.remote() for m in members], timeout=180)
    total = sum(range(8))  # device d holds value d
    for rank, out in enumerate(outs):
        base = rank * 4
        for arr in out["allreduce"]:
            np.testing.assert_allclose(arr, np.full((2,), float(total)))
        for arr in out["allgather"]:
            np.testing.assert_allclose(
                arr, np.repeat(np.arange(8.0), 2).reshape(8, 2)
                .reshape(-1))
        for arr in out["bcast"]:
            np.testing.assert_allclose(arr, np.full((2,), 5.0))
        for i, arr in enumerate(out["reduce"]):
            want = float(total) if base + i == 2 else float(base + i)
            np.testing.assert_allclose(arr, np.full((2,), want))
        for i, arr in enumerate(out["rscatter"]):
            np.testing.assert_allclose(arr, [float(base + i) * 8])
        for i, got_chunks in enumerate(out["alltoall"]):
            want = [float(s) * 10 + (base + i) for s in range(8)]
            np.testing.assert_allclose(
                np.concatenate(got_chunks), want)
