"""Scale-envelope guard (ISSUE 4 satellite, VERDICT Weak #7).

Push the single-node scheduler well past its steady-state shape — 10k
queued no-op tasks, then a 64-actor herd — at deadlines scaled to this
2-vCPU box, and assert the built-in metrics return to a sane idle state
afterwards (queue drained, nothing leaked in flight). The reference runs
these as release benchmarks (``release/benchmarks/single_node.json``);
here they are a ``slow``-marked regression fence.
"""

import time

import pytest

import ray_tpu


def _drain_poll(rt, deadline_s, desc):
    """Wait until the scheduler is idle: empty ready queue, no in-flight
    specs on any live worker."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        with rt.lock:
            ready = len(rt.ready_tasks)
            inflight = sum(len(ws.inflight_specs)
                           for ws in rt.workers.values()
                           if ws.status != "dead")
        if ready == 0 and inflight == 0:
            return
        time.sleep(0.2)
    raise AssertionError(
        f"{desc}: scheduler not idle after {deadline_s}s "
        f"(ready={ready}, inflight={inflight})")


@pytest.mark.slow
def test_scale_envelope_10k_tasks_and_64_actors():
    from ray_tpu.core.runtime import _get_runtime
    from ray_tpu.util.metrics import prometheus_text

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        rt = _get_runtime()

        @ray_tpu.remote
        def noop(i):
            return i

        # 10k queued no-ops: the queue must build AND fully drain.
        # ~5k tasks/s measured on this box -> generous 240s deadline.
        t0 = time.monotonic()
        refs = [noop.remote(i) for i in range(10_000)]
        out = ray_tpu.get(refs, timeout=240)
        assert out[0] == 0 and out[-1] == 9_999 and len(out) == 10_000
        took = time.monotonic() - t0
        _drain_poll(rt, 30, "post-10k-tasks")

        # 64 actors (4x the 16-actor bench herd): all must come up,
        # answer one call each, and die cleanly. ~17-26 actors/s
        # measured -> 180s deadline leaves a wide margin under load.
        @ray_tpu.remote
        class Echo:
            def ping(self, i):
                return i

        actors = [Echo.options(num_cpus=0).remote() for _ in range(64)]
        got = ray_tpu.get([a.ping.remote(i) for i, a in enumerate(actors)],
                          timeout=180)
        assert got == list(range(64))
        for a in actors:
            ray_tpu.kill(a)
        _drain_poll(rt, 60, "post-64-actors")

        # built-in metrics agree the envelope was traversed and closed:
        # sampled gauges back at 0, counters saw the volume
        text = prometheus_text()

        def sample(name):
            for line in text.splitlines():
                if line.startswith(name + " "):
                    return float(line.rsplit(" ", 1)[1])
            raise AssertionError(f"{name} not on /metrics:\n{text[:800]}")

        assert sample("rtpu_scheduler_ready_queue_depth") == 0
        assert sample("rtpu_scheduler_inflight_tasks") == 0
        submitted = sample(
            'rtpu_scheduler_tasks_submitted_total{type="task"}')
        assert submitted >= 10_000
        assert sample("rtpu_scheduler_tasks_dispatched_total") >= 10_000
        assert sample(
            'rtpu_scheduler_tasks_submitted_total{type="actor_create"}'
        ) >= 64
        # no leaked arg-pin entries for finished tasks (a small residue
        # from in-flight janitor timing is tolerated, not 10k)
        assert sample("rtpu_refcount_arg_pin_entries") < 100
        print(f"10k tasks in {took:.1f}s "
              f"({10_000 / took:.0f}/s), 64 actors ok")
    finally:
        ray_tpu.shutdown()
