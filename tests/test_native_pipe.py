"""Native driver engine (r14): GIL-free control pipe + fallback contract.

Three layers:
- engine-level: the C++ pipe over a raw socketpair (framing, batch
  coalescing, packed refpin bookkeeping, EOF, buffer growth);
- runtime-level: a live driver with the engine on vs the kill switch,
  exercising the exact A/B boundary bench.py measures;
- fallback-level: the pure-Python reader parsing the packed RTP1 frames
  workers ship, so a driver without the .so still interoperates.
"""

import os
import pickle
import socket
import struct
import threading
import time

import pytest

import ray_tpu
from ray_tpu import _native
from conftest import poll_until

pytestmark = pytest.mark.skipif(
    not _native.pipe_engine_available(),
    reason="native pipe engine unavailable (no .so on this box)")


# ---------------------------------------------------------------------------
# engine level
# ---------------------------------------------------------------------------


def _pipe_pair():
    from multiprocessing.connection import Connection

    a, b = socket.socketpair()
    drv = _native.NativePipe(a.fileno(), coalesce_us=0)
    peer = Connection(b.detach())
    return a, drv, peer


def test_single_and_batched_frames_roundtrip():
    a, drv, peer = _pipe_pair()
    try:
        msg = pickle.dumps(("exec", {"task": 1}))
        assert drv.send(msg)
        assert peer.recv_bytes() == msg

        # a burst: whatever coalesces ships as RTB1 batch frames the
        # worker-side unpack understands; order and content are exact
        msgs = [pickle.dumps(("reply", i, "ok", None)) for i in range(64)]
        for m in msgs:
            drv.send(m)
        received = []
        while len(received) < len(msgs):
            buf = peer.recv_bytes()
            if buf[:4] == b"RTB1":
                cnt = int.from_bytes(buf[4:8], "big")
                off = 8
                for _ in range(cnt):
                    ln = int.from_bytes(buf[off:off + 4], "big")
                    off += 4
                    received.append(buf[off:off + ln])
                    off += ln
            else:
                received.append(buf)
        assert received == msgs
        st = drv.stats()
        assert st["sent_msgs"] == len(msgs) + 1
        assert st["sent_frames"] >= 1
    finally:
        drv.close()
        a.close()


def test_drain_returns_assembled_messages_and_split_frames():
    a, drv, peer = _pipe_pair()
    try:
        peer.send_bytes(pickle.dumps(("cast", "put", (b"x" * 20, None, 5))))
        # a frame split across writes must reassemble
        payload = pickle.dumps(("cast", "split", b"y" * 10000))
        raw = struct.pack("!i", len(payload)) + payload
        fd = peer.fileno()
        os.write(fd, raw[:50])
        threading.Timer(0.2, lambda: os.write(fd, raw[50:])).start()
        recs = []
        deadline = time.time() + 10
        while len(recs) < 2 and time.time() < deadline:
            r = drv.drain(timeout=0.5)
            assert r is not None
            recs += r
        assert [t for t, _ in recs] == [0, 0]
        assert pickle.loads(recs[1][1])[1] == "split"
    finally:
        drv.close()
        a.close()


def test_refpin_frames_never_reach_python_uncoalesced():
    a, drv, peer = _pipe_pair()
    try:
        oid1, oid2, oid3 = b"A" * 16, b"B" * 16, b"C" * 16
        # oid1: two +1s -> ONE surfaced transition; oid2: +1 then -1 ->
        # both transitions surface; oid3: +1/-1 within one frame -> both
        frame = b"RTP1" + b"".join(
            struct.pack("<16sb", oid, d)
            for oid, d in [(oid1, 1), (oid1, 1), (oid2, 1), (oid2, -1),
                           (oid3, 1), (oid3, -1)])
        peer.send_bytes(frame)
        recs = []
        deadline = time.time() + 5
        while not recs and time.time() < deadline:
            recs = [r for r in (drv.drain(timeout=0.5) or [])
                    if r[0] == 1]
        assert recs, "no refpin transition record surfaced"
        trans = []
        for _, p in recs:
            for oid, d in struct.iter_unpack("<16sb", p):
                trans.append((oid, d))
        assert (oid1, 1) in trans
        assert trans.count((oid1, 1)) == 1  # second +1 coalesced away
        assert (oid2, 1) in trans and (oid2, -1) in trans
        st = drv.stats()
        assert st["refpin_deltas"] == 6
        # death drain: only oid1 still borrowed
        assert drv.drain_pins() == [(oid1, 2)]
        assert drv.drain_pins() == []  # drained == cleared
    finally:
        drv.close()
        a.close()


def test_big_message_grows_drain_buffer_and_eof():
    a, drv, peer = _pipe_pair()
    big = pickle.dumps(("cast", "blob", b"z" * (3 << 20)))
    threading.Thread(target=lambda: peer.send_bytes(big),
                     daemon=True).start()
    got = []
    deadline = time.time() + 10
    while not got and time.time() < deadline:
        got = drv.drain(timeout=0.5) or []
    assert got and got[0][1] == big
    peer.close()
    r = []
    while r == []:
        r = drv.drain(timeout=0.2)
    assert r is None  # EOF after everything was delivered
    assert not drv.send(b"late")  # sends after close report failure
    drv.close()
    a.close()


# ---------------------------------------------------------------------------
# runtime level: the A/B boundary
# ---------------------------------------------------------------------------


def _run_workload():
    @ray_tpu.remote
    def mul(x):
        return x * 3

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def hold(self, ref):
            self.ref = ref  # worker-side borrow -> refpin traffic
            return True

        def read(self):
            return ray_tpu.get(self.ref)

    assert ray_tpu.get([mul.remote(i) for i in range(40)]) == \
        [3 * i for i in range(40)]
    h = Holder.remote()
    ref = ray_tpu.put(b"payload" * 2000)
    assert ray_tpu.get(h.hold.remote([ref])) is True
    assert ray_tpu.get(h.read.remote()) == [b"payload" * 2000]
    return h


def test_native_pipe_on_attaches_engine_and_counts(monkeypatch):
    monkeypatch.setenv("RTPU_NATIVE_PIPE", "1")
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        from ray_tpu.core.runtime import _get_runtime

        _run_workload()
        rt = _get_runtime()
        # only DIALED-BACK workers: the engine attaches in _accept_loop,
        # so a replenishment spawn still mid-boot legitimately has none
        live = [ws for ws in rt.workers.values()
                if ws.status != "dead" and ws.conn is not None]
        assert live and all(ws.npipe is not None for ws in live)
        totals = {}
        for ws in live:
            for k, v in ws.npipe.stats().items():
                totals[k] = totals.get(k, 0) + v
        assert totals["sent_msgs"] > 0 and totals["recv_msgs"] > 0
        # metric reconciliation: the rtpu_pipe_* counters advance from
        # the native counts at exposition time
        from ray_tpu.util.metrics import registry_records

        sent = recv = 0
        for rec in registry_records():
            if rec["name"] == "rtpu_pipe_messages_total":
                for key, v in rec["samples"]:
                    if dict(key).get("direction") == "sent":
                        sent += v
                    else:
                        recv += v
        assert sent > 0 and recv > 0
    finally:
        ray_tpu.shutdown()


def test_kill_switch_restores_python_path(monkeypatch):
    monkeypatch.setenv("RTPU_NATIVE_PIPE", "0")
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        from ray_tpu.core.runtime import _get_runtime

        _run_workload()
        rt = _get_runtime()
        live = [ws for ws in rt.workers.values() if ws.status != "dead"]
        assert live and all(ws.npipe is None for ws in live)
    finally:
        ray_tpu.shutdown()


def test_python_fallback_reader_parses_packed_refpins(monkeypatch):
    """Driver without the .so + workers shipping RTP1 frames: the
    Python reader's _apply_refpin_frame keeps borrow tracking exact
    (the two sides never need to agree on the engine)."""
    monkeypatch.setenv("RTPU_NATIVE_PIPE", "1")
    monkeypatch.setattr(_native, "pipe_engine_available", lambda: False)
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        h = _run_workload()
        live = [ws for ws in rt.workers.values() if ws.status != "dead"]
        assert live and all(ws.npipe is None for ws in live)
        # the holder's borrow arrived via a packed frame -> ws.pinned
        poll_until(
            lambda: any(ws.pinned for ws in rt.workers.values()),
            timeout=30, desc="packed refpin parsed by fallback reader")
        del h
    finally:
        ray_tpu.shutdown()


def test_worker_death_drains_native_borrow_table(monkeypatch):
    monkeypatch.setenv("RTPU_NATIVE_PIPE", "1")
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()

        @ray_tpu.remote
        class Holder:
            def hold(self, ref):
                self.ref = ref
                return True

        h = Holder.remote()
        ref = ray_tpu.put(b"x" * 50000)
        assert ray_tpu.get(h.hold.remote([ref])) is True
        oid = ref.id.binary()
        # driver ref + worker borrow
        poll_until(lambda: rt._pin_total.get(oid, 0) >= 2, timeout=30,
                   desc="borrow pin lands")
        ray_tpu.kill(h)
        # death drained the native table: only the driver's pin remains
        poll_until(lambda: rt._pin_total.get(oid, 0) == 1, timeout=30,
                   desc="borrow pin released on death")
        assert ray_tpu.get(ref) == b"x" * 50000
    finally:
        ray_tpu.shutdown()
