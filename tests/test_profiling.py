"""Profiling plane (ISSUE 9): the sampling profiler, cross-process
collection into the head ProfileStore, speedscope/collapsed export,
live stack dumps, and object-memory forensics.

The multi-NODE collection path (heartbeat -> GCS profile store) is
covered in test_cluster.py.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import profiling, state


def _cleanup_profiling():
    os.environ.pop("RTPU_PROFILING", None)
    os.environ.pop("RTPU_PROFILE_HZ", None)
    os.environ.pop("RTPU_PROFILE_TABLE_MAX", None)
    profiling._reset_for_tests()


@pytest.fixture
def clean_profiling():
    _cleanup_profiling()
    yield
    _cleanup_profiling()


def _wait_for(pred, timeout=45.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return pred()


def _burn(seconds):
    t = time.monotonic() + seconds
    x = 0
    while time.monotonic() < t:
        x += 1
    return x


# ---------------------------------------------------------------------------
# recording plane (no runtime needed)
# ---------------------------------------------------------------------------


def test_disabled_is_noop(clean_profiling):
    assert profiling.profiling_enabled() is False
    assert profiling.ensure_sampler() is None
    assert profiling.drain_batches() == []
    assert profiling.sampler_stats() == {}


def test_sampler_captures_busy_and_idle(clean_profiling, monkeypatch):
    monkeypatch.setenv("RTPU_PROFILING", "1")
    profiling._reset_for_tests()
    monkeypatch.setenv("RTPU_PROFILING", "1")
    assert profiling.profiling_enabled() is True
    assert profiling.ensure_sampler() is not None

    # one thread burning CPU, one parked on an Event (idle leaf in
    # threading.py wait)
    park = threading.Event()
    burner = threading.Thread(target=_burn, args=(0.5,), name="burner")
    parker = threading.Thread(target=park.wait, args=(3.0,),
                              name="parker")
    burner.start()
    parker.start()
    time.sleep(0.45)
    batches = profiling.drain_batches()
    d2 = profiling.drain_batches()  # immediately: at most ~1 tick landed
    park.set()
    burner.join()
    parker.join()
    assert len(batches) == 1
    b = batches[0]
    assert b["pid"] == os.getpid()
    assert b["total"] > 0
    # busy: the burner's loop frame attributed by name
    assert any(t == "burner" and any("_burn" in f for f in stack)
               for t, stack, n in b["samples"]), b["samples"]
    # idle: the parked thread classified out of the busy signal
    assert any(t == "parker" for t, stack, n in b["idle"]), \
        [t for t, _, _ in b["idle"]]
    assert not any(t == "parker" for t, stack, n in b["samples"])
    # drained exactly once: the adjacent second drain saw at most a
    # tick or two of fresh samples, never the 0.45s window again
    n2 = sum(x["total"] + x["idle_total"] for x in d2)
    assert n2 < (b["total"] + b["idle_total"]) / 2, (n2, b)


def test_disarm_stashes_tail_window(clean_profiling, monkeypatch):
    monkeypatch.setenv("RTPU_PROFILING", "1")
    profiling._reset_for_tests()
    monkeypatch.setenv("RTPU_PROFILING", "1")
    s = profiling.ensure_sampler()
    s.record_for_tests("t", ["root (a.py:1)", "leaf (a.py:9)"])
    profiling.disable_profiling()
    assert profiling.profiling_enabled() is False
    # the stopped sampler's final window (the synthetic sample, plus
    # whatever real ticks landed before the stop) is NOT lost
    batches = profiling.drain_batches()
    assert batches
    assert any(t == "t" and stack == ["root (a.py:1)", "leaf (a.py:9)"]
               for t, stack, n in batches[0]["samples"])
    assert profiling.drain_batches() == []


def test_table_bound_drops(clean_profiling):
    # non-started sampler: deterministic — no live ticks compete for
    # table slots with the synthetic inserts
    s = profiling._Sampler(hz=67.0, table_max=64, start=False)
    for i in range(100):
        s.record_for_tests("t", [f"f{i} (x.py:{i})"])
    st = s.stats()
    assert st["busy_keys"] == 64
    assert st["dropped"] == 36
    b = s.drain()
    assert b["dropped"] == 36
    assert b["total"] == 64
    # the drop settled the bound; the next window starts clean
    s.record_for_tests("t", ["g (y.py:1)"])
    assert s.stats()["dropped"] == 0


def test_merge_top_self_collapsed_and_speedscope(clean_profiling):
    batches = [
        {"pid": 1, "t0": 0.0, "t1": 1.0, "hz": 67.0, "dropped": 0,
         "total": 5, "idle_total": 1,
         "samples": [["MainThread", ["a (m.py:1)", "b (m.py:9)"], 3],
                     ["MainThread", ["a (m.py:1)"], 2]],
         "idle": [["rx", ["r (m.py:4)", "wait (threading.py:300)"], 1]],
         "node_id": "n1", "component": "driver"},
        {"pid": 2, "t0": 0.0, "t1": 1.0, "hz": 67.0, "dropped": 2,
         "total": 4, "idle_total": 0,
         "samples": [["MainThread", ["a (m.py:1)", "b (m.py:9)"], 4]],
         "idle": [],
         "node_id": "n1", "component": "worker", "worker_id": "w1"},
    ]
    merged = profiling.merge_batches(batches)
    assert set(merged["processes"]) == {"driver@n1/1", "worker@n1/2"}
    assert merged["total"] == 9
    assert merged["dropped"] == 2

    top = profiling.top_self(merged)
    assert top[0]["function"] == "b (m.py:9)"  # 7 leaf samples
    assert top[0]["self_samples"] == 7
    top_w = profiling.top_self(merged, component="worker")
    assert top_w[0]["self_samples"] == 4 and len(top_w) == 1

    text = profiling.collapsed_text(merged)
    assert "driver@n1/1;MainThread;a (m.py:1);b (m.py:9) 3" in text
    # idle excluded unless asked
    assert "wait (threading.py:300)" not in text
    assert "wait (threading.py:300)" in profiling.collapsed_text(
        merged, include_idle=True)

    doc = profiling.speedscope_doc(merged)
    # one sampled profile per BUSY (process, thread) — idle threads are
    # classified out so they don't drown the on-CPU signal; weights sum
    # to that thread's sample count; frame indices all valid
    assert len(doc["profiles"]) == 2
    nframes = len(doc["shared"]["frames"])
    for p in doc["profiles"]:
        assert p["type"] == "sampled"
        assert sum(p["weights"]) == p["endValue"]
        assert len(p["samples"]) == len(p["weights"])
        assert all(0 <= i < nframes for s in p["samples"] for i in s)
    by_name = {p["name"]: p for p in doc["profiles"]}
    assert by_name["driver@n1/1 MainThread"]["endValue"] == 5
    assert by_name["worker@n1/2 MainThread"]["endValue"] == 4


def test_speedscope_excludes_idle_threads(clean_profiling):
    # wait-dominated threads are classified out of the speedscope view
    # (they'd drown the on-CPU signal); they remain countable in the
    # merge and visible via collapsed_text(include_idle=True)
    merged = profiling.merge_batches([
        {"pid": 1, "t0": 0, "t1": 1, "hz": 67.0, "dropped": 0,
         "total": 0, "idle_total": 2, "samples": [],
         "idle": [["rx", ["r (m.py:4)"], 2]], "component": "driver",
         "node_id": "n1"}])
    assert profiling.speedscope_doc(merged)["profiles"] == []
    assert merged["idle_total"] == 2
    assert "r (m.py:4) 2" in profiling.collapsed_text(
        merged, include_idle=True)


def test_profile_store_since_cursor(clean_profiling):
    ps = profiling.ProfileStore(cap=100)
    ps.ingest([{"pid": i} for i in range(5)], {"node_id": "n1"})
    batch, start = ps.since(0)
    assert start == 0 and len(batch) == 5
    assert all(b["node_id"] == "n1" for b in batch)
    batch2, start2 = ps.since(start + len(batch))
    assert batch2 == [] and start2 == 5
    ps.ingest([{"pid": 99}])
    batch3, start3 = ps.since(5)
    assert [b["pid"] for b in batch3] == [99] and start3 == 5


def test_current_stacks_needs_no_arming(clean_profiling):
    park = threading.Event()
    t = threading.Thread(target=park.wait, args=(5.0,), name="stackee")
    t.start()
    try:
        stacks = profiling.current_stacks()
        assert "stackee" in stacks
        assert "wait (" in stacks["stackee"].split(";")[-1]
    finally:
        park.set()
        t.join()


def test_idle_sleep_classifies_idle(clean_profiling, monkeypatch):
    monkeypatch.setenv("RTPU_PROFILING", "1")
    profiling._reset_for_tests()
    monkeypatch.setenv("RTPU_PROFILING", "1")
    profiling.ensure_sampler()
    t = threading.Thread(target=profiling.idle_sleep, args=(0.4,),
                         name="idler")
    t.start()
    time.sleep(0.3)
    t.join()
    b = profiling.drain_batches()[0]
    assert any(tn == "idler" for tn, _, _ in b["idle"])
    assert not any(tn == "idler" for tn, _, _ in b["samples"])


# ---------------------------------------------------------------------------
# collection through a live runtime (workers push over the pipe)
# ---------------------------------------------------------------------------


@pytest.fixture
def profiled_rt(clean_profiling, monkeypatch):
    monkeypatch.setenv("RTPU_PROFILING", "1")
    monkeypatch.setenv("RTPU_PROFILE_PUSH_INTERVAL_S", "0.2")
    profiling._reset_for_tests()
    monkeypatch.setenv("RTPU_PROFILING", "1")
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_worker_profiles_reach_head_merge(profiled_rt):
    @ray_tpu.remote
    def spin(sec):
        t = time.monotonic() + sec
        x = 0
        while time.monotonic() < t:
            x += 1
        return x

    ray_tpu.get([spin.remote(0.1) for _ in range(4)], timeout=60)

    def merged_ready():
        # keep work flowing so worker pushes fire
        ray_tpu.get([spin.remote(0.3) for _ in range(2)], timeout=60)
        prof = state.profile()
        comps = {p["component"] for p in prof["processes"].values()}
        if "worker" not in comps or "driver" not in comps:
            return None
        top_w = prof["top_self_by_component"]["worker"]
        if not any("spin" in r["function"] for r in top_w):
            return None
        return prof

    prof = _wait_for(merged_ready)
    assert prof, "worker profile batches never reached the head merge"
    # worker batches carry their origin labels
    wprocs = [k for k, p in prof["processes"].items()
              if p["component"] == "worker"]
    assert wprocs and all(k.startswith("worker@") for k in wprocs)
    # speedscope export over the live merge validates its shape contract
    doc = state.export_speedscope()
    assert doc["profiles"]
    for p in doc["profiles"]:
        assert sum(p["weights"]) == p["endValue"]


def test_profile_seconds_temp_arms_and_disarms(clean_profiling):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def spin(sec):
            t = time.monotonic() + sec
            x = 0
            while time.monotonic() < t:
                x += 1
            return x

        ray_tpu.get(spin.remote(0.05), timeout=60)
        assert profiling.profiling_enabled() is False
        done = threading.Event()

        def drive():
            while not done.is_set():
                try:
                    ray_tpu.get([spin.remote(0.3) for _ in range(2)],
                                timeout=60)
                except Exception:
                    return

        th = threading.Thread(target=drive)
        th.start()
        try:
            prof = state.profile(seconds=1.5)
        finally:
            done.set()
            th.join()
        # temporary arming is undone after the window
        assert profiling.profiling_enabled() is False
        assert prof["total_samples"] > 0
        comps = {p["component"] for p in prof["processes"].values()}
        assert "worker" in comps, prof["processes"]
    finally:
        ray_tpu.shutdown()


def test_live_stack_dump_reaches_workers(clean_profiling):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote(), timeout=60) == 1
        dump = state.stack(timeout=5.0)
        assert len(dump) == 1  # single node
        procs = next(iter(dump.values()))
        # the head process itself plus >= 1 worker answered
        assert any(k.startswith("driver/") for k in procs), procs.keys()
        wkeys = [k for k in procs if k.startswith("worker:")]
        assert wkeys
        wstacks = procs[wkeys[0]]
        # the worker main loop is parked in its exec-queue get
        assert "MainThread" in wstacks
        assert "wait (" in wstacks["MainThread"].split(";")[-1] or \
            "get (" in wstacks["MainThread"].split(";")[-1]
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# object-memory forensics (`ray_tpu memory` / state.diff_objects)
# ---------------------------------------------------------------------------


def test_memory_summary_reasons_owner_age(clean_profiling):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        profiling.enable_profiling()  # call sites recorded while armed
        ref = ray_tpu.put(b"z" * 200_000)
        rows = {r["object_id"]: r for r in state.memory_summary()}
        row = rows[ref.id.hex()]
        assert row["size"] >= 200_000
        assert row["owner"] == "driver"
        assert "create-ref" in row["reasons"]
        assert row["age_s"] is not None and row["age_s"] < 60
        assert row["call_site"] and "test_profiling" in row["call_site"]

        # a task RESULT is owned by its worker and reconstructable
        @ray_tpu.remote
        def produce():
            return b"r" * 100_000

        rref = produce.remote()
        ray_tpu.wait([rref], timeout=60)
        rows = {r["object_id"]: r for r in state.memory_summary()}
        rrow = rows[rref.id.hex()]
        assert rrow["owner"].startswith("worker:")
        assert "lineage" in rrow["reasons"]
        profiling.disable_profiling()
    finally:
        ray_tpu.shutdown()


def test_diff_objects_flags_planted_leak(clean_profiling):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        state.snapshot_objects()
        leaked = [ray_tpu.put(b"L" * 150_000)]  # intentionally held
        diff = state.diff_objects()
        sus = [r for r in diff["leak_suspects"]
               if r["object_id"] == leaked[0].id.hex()]
        assert sus, diff["leak_suspects"]
        assert "create-ref" in sus[0]["reasons"]
        assert sus[0]["pins"] >= 1
        assert diff["net_bytes"] >= 150_000

        # dropping the ref clears it from the next diff's population
        del leaked
        import gc

        gc.collect()
        diff2 = state.diff_objects()
        assert all(r["object_id"] != sus[0]["object_id"]
                   for r in diff2["added"])
    finally:
        ray_tpu.shutdown()


def test_store_report_occupancy(clean_profiling):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        keep = ray_tpu.put(b"s" * 500_000)  # noqa: F841 — stays in shm
        rep = state.store_report()
        assert rep["backend"] in ("arena", "file")
        assert rep["capacity_bytes"] > 0
        if rep["backend"] == "arena":
            assert rep["arena_used_bytes"] >= 500_000
            assert "fragmentation_pct" in rep
            assert rep["largest_free_bytes"] <= rep["free_bytes"]
    finally:
        ray_tpu.shutdown()
