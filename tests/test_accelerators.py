"""TPU accelerator layer with a fake topology provider (no hardware).

Mirrors the reference's mock strategy
(``python/ray/tests/accelerators/test_tpu.py``): fake device listings, GKE
env vars, and metadata lookups; assert env-var effects of visibility
restriction and pod-slice resource derivation.
"""

import os

import pytest

from ray_tpu.accelerators.tpu import (
    TPU_CHIPS_PER_HOST_BOUNDS_ENV,
    TPU_HOST_BOUNDS_ENV,
    TPU_VISIBLE_CHIPS_ENV,
    TPUAcceleratorManager,
    TpuTopologyProvider,
    detect_num_tpu_chips,
)


class FakeProvider(TpuTopologyProvider):
    def __init__(self, devices=(), accel_type=None, metadata=None,
                 worker=0):
        self._devices = list(devices)
        self._accel_type = accel_type
        self._metadata = metadata or {}
        self._worker = worker

    def list_accel_devices(self):
        return self._devices

    def jax_local_chip_count(self):
        return 0

    def gke_accelerator_type(self):
        return self._accel_type

    def gce_metadata(self, key):
        return self._metadata.get(key)

    def worker_id(self):
        return self._worker


def test_detect_chips_from_devices(monkeypatch):
    monkeypatch.delenv(TPU_VISIBLE_CHIPS_ENV, raising=False)
    p = FakeProvider(devices=["/dev/accel0", "/dev/accel1", "/dev/accel2",
                              "/dev/accel3"])
    assert detect_num_tpu_chips(p) == 4


def test_detect_chips_respects_visibility(monkeypatch):
    monkeypatch.setenv(TPU_VISIBLE_CHIPS_ENV, "0,1")
    assert detect_num_tpu_chips(FakeProvider(devices=["/dev/accel0"] * 4)) == 2


@pytest.mark.parametrize("ids,chip_bounds,host_bounds", [
    (["0"], "1,1,1", "1,1,1"),
    (["0", "1"], "1,2,1", "1,1,1"),
    (["0", "1", "2", "3"], "2,2,1", "1,1,1"),
])
def test_visibility_env_vars(monkeypatch, ids, chip_bounds, host_bounds):
    for var in (TPU_VISIBLE_CHIPS_ENV, TPU_CHIPS_PER_HOST_BOUNDS_ENV,
                TPU_HOST_BOUNDS_ENV):
        monkeypatch.delenv(var, raising=False)
    mgr = TPUAcceleratorManager(FakeProvider())
    mgr.set_current_process_visible_accelerator_ids(ids)
    assert os.environ[TPU_VISIBLE_CHIPS_ENV] == ",".join(ids)
    assert os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] == chip_bounds
    assert os.environ[TPU_HOST_BOUNDS_ENV] == host_bounds


def test_invalid_chip_subset_not_set(monkeypatch):
    monkeypatch.delenv(TPU_VISIBLE_CHIPS_ENV, raising=False)
    mgr = TPUAcceleratorManager(FakeProvider())
    mgr.set_current_process_visible_accelerator_ids(["0", "1", "2"])
    assert TPU_VISIBLE_CHIPS_ENV not in os.environ


def test_pod_type_from_gke_env():
    mgr = TPUAcceleratorManager(FakeProvider(accel_type="v5litepod-16"))
    assert mgr.get_current_node_accelerator_type() == "v5litepod-16"


def test_pod_type_from_metadata():
    mgr = TPUAcceleratorManager(FakeProvider(
        metadata={"accelerator-type": "v4-16"}))
    assert mgr.get_current_node_accelerator_type() == "v4-16"


def test_pod_type_invalid_rejected():
    mgr = TPUAcceleratorManager(FakeProvider(accel_type="tpu-weird-3"))
    assert mgr.get_current_node_accelerator_type() is None


@pytest.mark.parametrize("pod_type,workers", [
    ("v4-16", 2),          # 16 cores = 8 chips / 4 per host
    ("v4-8", 1),
    ("v5litepod-16", 4),   # 16 chips / 4 per host
    ("v5litepod-256", 64),
    ("v5p-16", 2),         # 16 chips / 8 per host
])
def test_pod_worker_count(pod_type, workers):
    mgr = TPUAcceleratorManager(FakeProvider(accel_type=pod_type))
    assert mgr.get_current_pod_worker_count() == workers


def test_public_helpers_and_fan_out():
    import ray_tpu
    from ray_tpu.util.accelerators import fan_out_per_host, \
        pod_head_resource

    assert pod_head_resource("v5litepod-16") == "TPU-v5litepod-16-head"
    ray_tpu.shutdown()   # a leaked runtime would lack the custom resource
    ray_tpu.init(num_cpus=4, resources={"my-slice": 4})
    try:
        def hostname_task():
            import os as _os

            return _os.getpid()

        refs = fan_out_per_host(hostname_task, "my-slice", 4)
        pids = ray_tpu.get(refs, timeout=60)
        assert len(pids) == 4
    finally:
        ray_tpu.shutdown()


def test_pod_slice_head_resources(monkeypatch):
    monkeypatch.setenv("TPU_NAME", "my-slice")
    head = TPUAcceleratorManager(FakeProvider(accel_type="v5litepod-16",
                                              worker=0))
    res = head.get_extra_resources()
    assert res == {"my-slice": 1.0, "TPU-v5litepod-16-head": 1.0}

    worker = TPUAcceleratorManager(FakeProvider(accel_type="v5litepod-16",
                                                worker=3))
    res = worker.get_extra_resources()
    assert res == {"my-slice": 1.0}
