"""Concurrent actors (max_concurrency) + async actor methods."""

import time

import pytest

import ray_tpu


@pytest.fixture
def rt_async():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_concurrent_actor_overlaps_calls(rt_async):
    @ray_tpu.remote
    class Sleeper:
        def nap(self, dt):
            import time as _t

            start = _t.monotonic()
            _t.sleep(dt)
            return (start, _t.monotonic())

    s = Sleeper.options(max_concurrency=4).remote()
    ray_tpu.get(s.nap.remote(0.01), timeout=60)   # actor fully started
    t0 = time.monotonic()
    refs = [s.nap.remote(0.5) for _ in range(4)]
    spans = ray_tpu.get(refs, timeout=60)
    elapsed = time.monotonic() - t0
    # 4 overlapping 0.5s naps finish way under the 2s serial time
    assert elapsed < 1.6, f"calls serialized: {elapsed:.2f}s"
    # spans genuinely overlap
    starts = sorted(a for a, _ in spans)
    ends = sorted(b for _, b in spans)
    assert starts[-1] < ends[0] + 0.5


def test_serial_actor_stays_ordered(rt_async):
    @ray_tpu.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, x):
            self.log.append(x)
            return list(self.log)

    s = Seq.remote()
    outs = ray_tpu.get([s.add.remote(i) for i in range(5)])
    assert outs[-1] == [0, 1, 2, 3, 4]


def test_async_actor_method(rt_async):
    @ray_tpu.remote
    class AsyncActor:
        async def compute(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.options(max_concurrency=2).remote()
    assert ray_tpu.get([a.compute.remote(i) for i in range(4)],
                       timeout=60) == [0, 2, 4, 6]


def test_concurrent_actor_death_fails_all_inflight(rt_async):
    @ray_tpu.remote
    class Crasher:
        def slow(self, dt):
            import time as _t

            _t.sleep(dt)
            return "done"

        def die(self):
            import os as _os

            _os._exit(1)

    c = Crasher.options(max_concurrency=4).remote()
    slow_refs = [c.slow.remote(5.0) for _ in range(2)]
    time.sleep(0.3)          # let the slow calls start
    c.die.remote()
    from ray_tpu.core.exceptions import ActorDiedError

    with pytest.raises(ActorDiedError):
        ray_tpu.get(slow_refs, timeout=60)
