"""Telemetry plane: metric registration semantics, Prometheus exposition
correctness, metrics federation, the task-lifecycle flight recorder, and
train step telemetry (ISSUE 3)."""

import json
import re
import time

import pytest

import ray_tpu


# ---------------------------------------------------------------------------
# metric registry semantics (satellite: silent name-collision fix)
# ---------------------------------------------------------------------------


def test_metric_reregistration_merges_samples():
    """Re-creating a metric with an existing name must NOT orphan the
    previously recorded samples — both instances share one store."""
    from ray_tpu.util.metrics import Counter, clear_registry, prometheus_text

    clear_registry()
    c1 = Counter("reg_merge_total", "first registration")
    c1.inc(2)
    c2 = Counter("reg_merge_total", "second registration")
    c2.inc(3)
    # both instances observe the merged value
    assert dict(c1._samples()) == dict(c2._samples())
    text = prometheus_text()
    assert "reg_merge_total 5.0" in text
    # later increments through the FIRST instance still land too
    c1.inc(1)
    assert "reg_merge_total 6.0" in prometheus_text()
    clear_registry()


def test_metric_type_mismatch_raises():
    from ray_tpu.util.metrics import Counter, Gauge, clear_registry

    clear_registry()
    Counter("reg_clash_total", "a counter")
    with pytest.raises(ValueError, match="already registered"):
        Gauge("reg_clash_total", "now a gauge?")
    clear_registry()


def test_histogram_boundary_mismatch_raises():
    from ray_tpu.util.metrics import Histogram, clear_registry

    clear_registry()
    Histogram("reg_hist", "h", boundaries=[1, 10])
    with pytest.raises(ValueError, match="boundaries"):
        Histogram("reg_hist", "h", boundaries=[2, 20])
    # identical boundaries merge fine
    h2 = Histogram("reg_hist", "h", boundaries=[1, 10])
    h2.observe(5)
    clear_registry()


# ---------------------------------------------------------------------------
# Prometheus exposition correctness (satellite)
# ---------------------------------------------------------------------------


def test_prometheus_histogram_cumulative_buckets():
    from ray_tpu.util.metrics import Histogram, clear_registry, prometheus_text

    clear_registry()
    h = Histogram("expo_hist", "latency", boundaries=[0.1, 1, 10])
    for v in (0.05, 0.5, 0.5, 5, 50, 500):
        h.observe(v)
    text = prometheus_text()
    lines = [line for line in text.splitlines()
             if line.startswith("expo_hist")]
    # cumulative le buckets, +Inf == count, exact sum
    assert 'expo_hist_bucket{le="0.1"} 1' in lines
    assert 'expo_hist_bucket{le="1"} 3' in lines
    assert 'expo_hist_bucket{le="10"} 4' in lines
    assert 'expo_hist_bucket{le="+Inf"} 6' in lines
    assert "expo_hist_count 6" in lines
    assert "expo_hist_sum 556.05" in lines
    # buckets are monotonically non-decreasing in exposition order
    cums = [float(line.rsplit(" ", 1)[1]) for line in lines
            if line.startswith("expo_hist_bucket")]
    assert cums == sorted(cums)
    clear_registry()


def test_prometheus_label_escaping():
    from ray_tpu.util.metrics import Counter, clear_registry, prometheus_text

    clear_registry()
    c = Counter("expo_esc_total", "escapes", tag_keys=("path",))
    nasty = 'he said "hi"\\there\nnewline'
    c.inc(1, tags={"path": nasty})
    text = prometheus_text()
    assert ('expo_esc_total{path="he said \\"hi\\"\\\\there\\nnewline"} 1.0'
            in text)
    # literal newline must never appear inside a label value
    for line in text.splitlines():
        if line.startswith("expo_esc_total{"):
            assert "\n" not in line
    clear_registry()


def test_prometheus_single_type_header_with_federation():
    """Local + remote samples of the same metric group under ONE
    HELP/TYPE header (the text format forbids repeating it)."""
    from ray_tpu.util.metrics import (Counter, FederationStore,
                                      clear_registry, prometheus_text,
                                      registry_records)

    clear_registry()
    c = Counter("fed_shared_total", "d")
    c.inc(1)
    store = FederationStore()
    store.ingest("w1", {"worker_id": "aaaa", "node_id": "n1",
                        "component": "worker"}, registry_records())
    text = prometheus_text(extra=store.export())
    assert text.count("# TYPE fed_shared_total counter") == 1
    assert "fed_shared_total 1.0" in text
    assert ('fed_shared_total{component="worker",node_id="n1",'
            'worker_id="aaaa"} 1.0') in text
    clear_registry()


# ---------------------------------------------------------------------------
# task-lifecycle flight recorder + single-node worker federation
# ---------------------------------------------------------------------------


@pytest.fixture
def rt_telemetry(monkeypatch):
    monkeypatch.setenv("RTPU_METRICS_PUSH_INTERVAL_S", "0.2")
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_flight_recorder_phases_and_summary(rt_telemetry):
    import numpy as np

    @ray_tpu.remote
    def work(xs):
        time.sleep(0.02)
        return len(xs)

    # big enough to take the store-segment path (inline args would skip
    # the arg_fetch phase)
    ref = ray_tpu.put(np.zeros(500_000))
    assert ray_tpu.get([work.remote(ref) for _ in range(6)],
                       timeout=120) == [500_000] * 6

    from ray_tpu.core.runtime import _get_runtime
    from ray_tpu.util.state import list_task_events, summarize_tasks

    ring = list_task_events()
    recs = [r for r in ring if r["name"] == "work"]
    assert len(recs) >= 6
    for rec in recs:
        ph = rec["phases"]
        # every lifecycle phase is present and sane
        for key in ("queue", "lease", "arg_fetch", "execute",
                    "store_result", "total"):
            assert key in ph, ph
            assert ph[key] >= 0
        assert ph["execute"] >= 0.015  # the sleep is visible
        assert ph["total"] >= ph["execute"]
        assert rec["status"] == "ok"
        assert rec["worker_id"]

    summary = summarize_tasks()
    phases = summary["work"]["phases"]
    assert phases["execute"]["count"] >= 6
    assert phases["execute"]["p50_ms"] >= 15
    assert phases["execute"]["p99_ms"] >= phases["execute"]["p50_ms"]
    assert phases["queue"]["p50_ms"] >= 0

    # built-in phase histograms feed /metrics
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert 'rtpu_task_phase_seconds_bucket' in text
    assert 'phase="execute"' in text
    assert "rtpu_tasks_finished_total" in text

    # the driver's ring is bounded
    assert _get_runtime().task_ring.maxlen is not None


def test_timeline_contains_nested_lifecycle_slices(rt_telemetry, tmp_path):
    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    assert ray_tpu.get([traced.remote() for _ in range(3)],
                       timeout=60) == [1, 1, 1]

    out = tmp_path / "trace.json"
    events = ray_tpu.timeline(str(out))
    # loadable Chrome-trace JSON: an array of complete ("X") events with
    # microsecond timestamps and durations
    loaded = json.loads(out.read_text())
    assert isinstance(loaded, list) and loaded
    tasks = [e for e in loaded if e["name"] == "traced" and e["ph"] == "X"]
    assert len(tasks) >= 3
    for e in tasks:
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
    nested = [e for e in loaded if e.get("cat") == "task_phase"
              and e["name"].startswith("traced:")]
    assert {e["name"] for e in nested} >= {"traced:execute"}
    # each nested slice nests INSIDE its task slice on the same lane
    for e in nested:
        parent = next(p for p in tasks if p["tid"] == e["tid"]
                      and p["ts"] <= e["ts"] + 1
                      and e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1000)
        assert parent["ph"] == "X"
    assert events == loaded


def test_worker_metrics_federate_to_driver(rt_telemetry):
    """Samples recorded INSIDE worker processes (built-ins + user metrics
    created in tasks) appear on the driver's exposition with worker_id/
    node_id/component labels."""

    @ray_tpu.remote
    def busy(i):
        from ray_tpu.util.metrics import Counter

        Counter("user_task_metric_total", "created inside a task").inc()
        time.sleep(0.05)
        return i

    assert ray_tpu.get([busy.remote(i) for i in range(8)],
                       timeout=120) == list(range(8))

    from conftest import poll_until
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    import urllib.request

    url = f"http://127.0.0.1:{dash.port}/metrics"
    try:
        def scrape():
            txt = urllib.request.urlopen(url, timeout=5).read().decode()
            wids = set(re.findall(
                r'rtpu_worker_tasks_total\{[^}]*worker_id="(\w+)"', txt))
            return txt if (len(wids) >= 2
                           and "user_task_metric_total{" in txt) else None

        txt = poll_until(scrape, timeout=30,
                         desc=">=2 worker origins on /metrics")
    finally:
        stop_dashboard()
    assert 'component="worker"' in txt
    assert re.search(r'rtpu_worker_tasks_total\{[^}]*node_id="\w+"', txt)
    # worker exec-time histogram federated too
    assert "rtpu_worker_task_exec_seconds_bucket{" in txt


# ---------------------------------------------------------------------------
# job submission REST (ISSUE 4 satellite, reference job_head.py role)
# ---------------------------------------------------------------------------


def test_job_rest_submit_status_logs_stop(rt_telemetry):
    import json
    import urllib.request

    from conftest import poll_until
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    dash = start_dashboard(port=0)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        def post(path, body=None):
            def once():
                req = urllib.request.Request(
                    base + path,
                    data=json.dumps(body or {}).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                return json.loads(
                    urllib.request.urlopen(req, timeout=15).read())
            return poll_until(once, timeout=30, desc=f"POST {path}")

        def get(path):
            def once():
                return json.loads(urllib.request.urlopen(
                    base + path, timeout=15).read())
            return poll_until(once, timeout=30, desc=f"GET {path}")

        # submit -> terminal SUCCEEDED -> logs round-trip
        job_id = post("/api/jobs", {
            "entrypoint": "echo rest-job-output"})["result"]["job_id"]

        def done():
            info = get(f"/api/jobs/{job_id}")["result"]
            return info if info["status"] in ("SUCCEEDED", "FAILED",
                                              "STOPPED") else None

        info = poll_until(done, timeout=90, desc="job terminal")
        assert info["status"] == "SUCCEEDED"
        logs = get(f"/api/jobs/{job_id}/logs")["result"]["logs"]
        assert "rest-job-output" in logs
        assert any(j["job_id"] == job_id
                   for j in get("/api/jobs")["result"])

        # a long-running job stops via the REST stop route
        jid2 = post("/api/jobs",
                    {"entrypoint": "sleep 60"})["result"]["job_id"]

        def running():
            info = get(f"/api/jobs/{jid2}")["result"]
            return info["status"] == "RUNNING" or None

        poll_until(running, timeout=90, desc="job running")
        assert post(f"/api/jobs/{jid2}/stop")["result"]["stopped"]

        def stopped():
            return get(f"/api/jobs/{jid2}")["result"][
                "status"] == "STOPPED" or None

        poll_until(stopped, timeout=90, desc="job stopped")

        # unknown job ids are 404s, and a metrics scrape on the SAME
        # threaded server works while job routes are in use
        try:
            urllib.request.urlopen(base + "/api/jobs/nope", timeout=15)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        txt = urllib.request.urlopen(base + "/metrics",
                                     timeout=15).read().decode()
        assert "rtpu_scheduler_ready_queue_depth" in txt
    finally:
        stop_dashboard()


# ---------------------------------------------------------------------------
# train step telemetry
# ---------------------------------------------------------------------------


def test_step_telemetry_records_metrics():
    from ray_tpu.train.telemetry import StepTelemetry
    from ray_tpu.util.metrics import clear_registry, prometheus_text

    clear_registry()
    t = StepTelemetry()
    t.record_step(0.1, tokens=1000, loss=2.5)
    t.record_step(0.2, tokens=1000, mfu=0.31)
    t.record_compile(3.0)
    snap = t.snapshot()
    assert snap["steps"] == 2
    assert snap["tokens_per_s"] == 5000.0
    assert snap["mfu"] == 0.31
    assert snap["compiles"] == 1
    text = prometheus_text()
    assert "rtpu_train_step_seconds_count 2" in text
    assert "rtpu_train_tokens_per_s 5000.0" in text
    assert "rtpu_train_mfu 0.31" in text
    assert "rtpu_train_compile_total 1.0" in text
    assert "rtpu_train_loss 2.5" in text
    clear_registry()


def test_step_telemetry_on_report_interval():
    from ray_tpu.train.telemetry import StepTelemetry

    t = StepTelemetry()
    t.on_report({"loss": 1.0})          # first report: arms the clock
    time.sleep(0.05)
    t.on_report({"loss": 0.5, "tokens_per_s": 100.0})
    snap = t.snapshot()
    assert snap["steps"] == 1
    assert snap["step_time_s"] >= 0.04
    assert snap["loss"] == 0.5
    assert snap["tokens_per_s"] > 0


def test_train_loop_helper_records_compile_event():
    import jax

    if not hasattr(jax, "set_mesh"):
        pytest.skip("jax too old for TrainLoopHelper (no jax.set_mesh)")
    import jax.numpy as jnp
    import optax

    from ray_tpu.train import TrainLoopHelper
    from ray_tpu.train.telemetry import get_step_telemetry
    from ray_tpu.parallel import MeshConfig

    helper = TrainLoopHelper.create(
        lambda: {"w": jnp.ones((4, 4))},
        {"w": (None, None)},
        lambda p, b: ((p["w"] * b["x"]).sum() ** 2, {}),
        optax.sgd(1e-2),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )
    before = get_step_telemetry().snapshot().get("compiles", 0)
    batch = {"x": jnp.ones((8, 4))}
    helper.run_steps(batch, 2)   # fresh scanned program -> compile event
    helper.run_steps(batch, 2)   # cached -> no new event
    after = get_step_telemetry().snapshot().get("compiles", 0)
    assert after == before + 1
