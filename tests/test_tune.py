"""Tune: search spaces, function/class trainables, schedulers, PBT, Tuner."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig
from ray_tpu.tune import (
    ASHAScheduler, PopulationBasedTraining, Trainable, TuneConfig, Tuner,
)
from ray_tpu.tune.search import generate_variants


@pytest.fixture
def rt_tune(tmp_path):
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield str(tmp_path)
    ray_tpu.shutdown()


def test_generate_variants_grid_and_samples():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0.0, 1.0),
        "opt": "adam",
    }
    variants = list(generate_variants(space, num_samples=3, seed=0))
    assert len(variants) == 6
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert all(0.0 <= v["wd"] <= 1.0 for v in variants)
    assert all(v["opt"] == "adam" for v in variants)


def test_function_trainable_tuner(rt_tune):
    def objective(config):
        for i in range(3):
            tune.report({"score": config["x"] ** 2 + i})

    tuner = Tuner(
        objective,
        param_space={"x": tune.grid_search([-2, 0, 3])},
        tune_config=TuneConfig(metric="score", mode="min"),
        run_config=RunConfig(storage_path=rt_tune),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result("score", mode="min")
    assert best.config["x"] == 0
    assert best.metrics["score"] == 2  # final report: 0 + 2


def test_class_trainable_with_checkpointing(rt_tune):
    class Quad(Trainable):
        def setup(self, config):
            self.x = config["start"]

        def step(self):
            self.x *= 0.5
            return {"val": self.x}

        def save_checkpoint(self, d):
            return {"x": self.x}

        def load_checkpoint(self, data, d):
            self.x = data["x"]

    tuner = Tuner(
        Quad,
        param_space={"start": 8.0},
        tune_config=TuneConfig(
            scheduler=tune.ASHAScheduler(metric="val", mode="min", max_t=4),
            checkpoint_at_end=True),
        run_config=RunConfig(storage_path=rt_tune),
    )
    grid = tuner.fit()
    res = grid[0]
    assert res.metrics["val"] == pytest.approx(8.0 * 0.5 ** 4)
    assert res.checkpoint is not None
    assert os.path.exists(os.path.join(res.checkpoint.path,
                                       "trainable_state.pkl"))


def test_asha_stops_bad_trials(rt_tune):
    def objective(config):
        for i in range(16):
            # trial quality fixed by config: lower "quality" = higher loss
            tune.report({"loss": 10.0 - config["quality"] + 0.01 * i})

    # Best trial first + sequential execution makes rung decisions
    # deterministic: later (worse) trials get cut at the first rung.
    tuner = Tuner(
        objective,
        param_space={"quality": tune.grid_search([8, 5, 3, 1])},
        tune_config=TuneConfig(
            scheduler=ASHAScheduler(metric="loss", mode="min", max_t=16,
                                    grace_period=2, reduction_factor=2),
            max_concurrent_trials=1),
        run_config=RunConfig(storage_path=rt_tune),
    )
    grid = tuner.fit()
    df_iters = {r.config["quality"]: r.metrics.get("training_iteration", 0)
                for r in (grid[i] for i in range(len(grid)))}
    # the best trial survives to max_t; the worst should be cut early
    assert df_iters[8] == 16
    assert df_iters[1] < 16


def test_pbt_exploits_and_mutates(rt_tune):
    class Learner(Trainable):
        def setup(self, config):
            self.score = 0.0

        def step(self):
            self.score += self.config["rate"]
            return {"score": self.score}

        def save_checkpoint(self, d):
            return {"score": self.score}

        def load_checkpoint(self, data, d):
            self.score = data["score"]

        def reset_config(self, c):
            self.config = c
            return True

    stopper = lambda tid, res: res.get("training_iteration", 0) >= 12

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"rate": (0.1, 2.0)}, seed=0)
    tuner = Tuner(
        Learner,
        param_space={"rate": tune.uniform(0.1, 2.0)},
        tune_config=TuneConfig(num_samples=4, scheduler=pbt),
        run_config=RunConfig(storage_path=rt_tune),
    )
    # install stopper through controller: use run() path instead
    from ray_tpu.tune.tune_controller import TuneController
    from ray_tpu.tune.search import generate_variants as gv

    controller = TuneController(
        tuner.trainable_cls,
        list(gv({"rate": tune.uniform(0.1, 2.0)}, 4, seed=1)),
        run_config=RunConfig(storage_path=rt_tune),
        scheduler=pbt,
        stopper=stopper,
    )
    trials = controller.run()
    assert all(t.status == "TERMINATED" for t in trials)
    scores = [t.last_result.get("score", 0) for t in trials]
    assert max(scores) > 0


def test_stoppers_and_loggers(rt_tune):
    import csv
    import os

    def objective(config):
        for i in range(100):
            tune.report({"loss": 1.0})   # flat: plateau after grace

    csv_cb = tune.CSVLoggerCallback()
    json_cb = tune.JsonLoggerCallback()
    tuner = Tuner(
        objective,
        run_config=RunConfig(
            storage_path=rt_tune,
            stop=tune.CombinedStopper(
                tune.TrialPlateauStopper("loss", num_results=3, std=0.0,
                                         grace_period=3),
                tune.MaximumIterationStopper(50)),
            callbacks=[csv_cb, json_cb]),
    )
    grid = tuner.fit()
    res = grid[0]
    # plateau stopper cut it long before 100 iterations
    assert res.metrics["training_iteration"] <= 5
    with open(os.path.join(res.path, "progress.csv")) as f:
        rows = list(csv.DictReader(f))
    assert rows and rows[0]["loss"] == "1.0"
    assert os.path.exists(os.path.join(res.path, "result.json"))


def test_metric_threshold_stopper(rt_tune):
    def objective(config):
        for i in range(50):
            tune.report({"score": float(i)})

    grid = Tuner(
        objective,
        run_config=RunConfig(
            storage_path=rt_tune,
            stop=tune.MetricThresholdStopper("score", 10.0, mode="max")),
    ).fit()
    assert grid[0].metrics["score"] == 10.0


def test_searcher_simple_bayes(rt_tune):
    def objective(config):
        tune.report({"loss": (config["x"] - 0.7) ** 2})

    search = tune.SimpleBayesSearch(
        {"x": tune.uniform(0.0, 1.0)}, metric="loss", mode="min",
        n_initial=3, seed=0)
    tuner = Tuner(
        objective,
        tune_config=TuneConfig(num_samples=8, search_alg=search,
                               metric="loss", mode="min"),
        run_config=RunConfig(storage_path=rt_tune),
    )
    grid = tuner.fit()
    best = grid.get_best_result("loss", mode="min")
    assert best.metrics["loss"] < 0.2


def test_function_trainable_restore_survives_setup(tmp_path):
    """restore() state must not be wiped by the lazy setup() on first
    train_step (ADVICE r1: PBT exploit / failure retry silently restarted
    function trainables from scratch)."""
    from ray_tpu.tune.trainable import wrap_function

    ckpt_dir = tmp_path / "checkpoint_000007"
    ckpt_dir.mkdir()
    (ckpt_dir / "state.txt").write_text("42")

    seen = {}

    def fn(config):
        ckpt = tune.get_checkpoint()
        seen["path"] = ckpt.path if ckpt else None
        tune.report({"score": 1.0})

    trial_dir = tmp_path / "trial"
    trial_dir.mkdir()
    tr = wrap_function(fn)({}, trial_dir=str(trial_dir))
    # controller order: restore() first, setup() lazily on first train_step
    tr.restore(str(ckpt_dir))
    result = tr.train_step()
    assert result["score"] == 1.0
    assert seen["path"] == str(ckpt_dir)


def test_asha_credits_rungs_on_crossing():
    """Trials that report past a rung (never exactly at it) must still be
    evaluated there (ADVICE r1: exact-equality check silently disabled
    early stopping for every-k reporters)."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    sched = ASHAScheduler(metric="loss", mode="min", max_t=100,
                          grace_period=10, reduction_factor=2)
    assert sched.levels == [10, 20, 40, 80]

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    good, bad = T("good"), T("bad")
    # both skip t=10 and report at t=15: rung 10 must still fire
    assert sched.on_trial_result(
        good, {"loss": 1.0, "training_iteration": 15}) == CONTINUE
    assert sched.on_trial_result(
        bad, {"loss": 5.0, "training_iteration": 15}) == STOP
    assert sched.rungs[10] == [1.0, 5.0]
    # re-reporting below the next rung must not double-credit rung 10
    assert sched.on_trial_result(
        good, {"loss": 0.5, "training_iteration": 16}) == CONTINUE
    assert sched.rungs[10] == [1.0, 5.0]
    # crossing two rungs at once credits only the HIGHEST (no back-filling
    # lower rungs with late, better-trained values)
    assert sched.on_trial_result(
        good, {"loss": 0.4, "training_iteration": 45}) == CONTINUE
    assert sched.rungs[40] == [0.4] and 20 not in sched.rungs


def test_hyperband_brackets_and_stops():
    """HyperBand assigns trials to brackets with different grace periods
    and stops bottom performers at rung milestones."""
    from ray_tpu.tune import HyperBandScheduler

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    sched = HyperBandScheduler(metric="score", mode="max", max_t=27,
                               reduction_factor=3)
    trials = [T(f"t{i}") for i in range(8)]
    for t in trials:
        sched.on_trial_add(t)
    brackets = {sched._bracket_of[t.trial_id] for t in trials}
    assert len(brackets) >= 2  # bracket diversity is the point
    # bracket 0 is the conservative run-to-completion bracket: no rungs
    assert sched._levels(0) == []
    # an aggressive bracket halves early
    assert sched._levels(3) == [1, 3, 9]
    # same bracket with rungs, different scores: the worse one stops
    s2 = [t for t in trials if sched._bracket_of[t.trial_id] == 2]
    assert len(s2) >= 2
    a, b = s2[0], s2[1]
    level = sched._levels(2)[0]
    assert sched.on_trial_result(a, {"training_iteration": level,
                                     "score": 10.0}) == "CONTINUE"
    assert sched.on_trial_result(b, {"training_iteration": level,
                                     "score": 1.0}) == "STOP"
    # past max_t everything stops
    assert sched.on_trial_result(a, {"training_iteration": 27,
                                     "score": 99.0}) == "STOP"


def test_tpe_search_concentrates_on_optimum():
    """TPE proposals after warmup concentrate near the best region of a
    quadratic objective (vs the uniform prior)."""
    import random as _random

    from ray_tpu.tune.search import TPESearch, Uniform

    space = {"x": Uniform(0.0, 10.0)}
    tpe = TPESearch(space, metric="loss", mode="min", n_initial=10,
                    n_candidates=16, seed=0)
    rng = _random.Random(0)
    # seed observations: loss = (x-2)^2
    for i in range(30):
        cfg = tpe.suggest(f"w{i}")
        loss = (cfg["x"] - 2.0) ** 2
        tpe.on_trial_complete(f"w{i}", {"loss": loss, "config": cfg})
    proposals = [tpe.suggest(f"p{i}")["x"] for i in range(20)]
    near = sum(1 for x in proposals if abs(x - 2.0) < 2.5)
    assert near >= 14, proposals  # uniform would give ~10


def test_bohb_search_with_hyperband_e2e(rt_tune):
    """BOHB = TPESearch feeding on partial results + HyperBandScheduler,
    end to end through the Tuner."""
    from ray_tpu import tune
    from ray_tpu.tune import BOHBSearch, HyperBandScheduler
    from ray_tpu.tune.search import Uniform

    def objective(config):
        for i in range(9):
            tune.report({"loss": (config["x"] - 3.0) ** 2 + 1.0 / (i + 1)})

    search = BOHBSearch({"x": Uniform(0.0, 10.0)}, metric="loss",
                        mode="min", n_initial=4, seed=0)
    tuner = tune.Tuner(
        objective,
        tune_config=tune.TuneConfig(
            num_samples=8, search_alg=search,
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=9, reduction_factor=3),
            max_concurrent_trials=4),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 30.0
    # partial results reached the model: ONE observation per trial,
    # holding that trial's LATEST (highest-budget) metric
    assert len(search.observations) == 8
    for cfg, loss in search.observations:
        first_iter = (cfg["x"] - 3.0) ** 2 + 1.0
        assert loss <= first_iter + 1e-9
