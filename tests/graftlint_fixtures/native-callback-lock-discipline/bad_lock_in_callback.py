# graftlint: path=ray_tpu/core/runtime.py
"""Offender: a native drain callback takes the driver's ref lock."""
import threading


class DriverRuntime:
    def __init__(self):
        self._ref_lock = threading.Lock()
        self._pins = {}

    def _native_cb_refpins(self, ws, payload):
        with self._ref_lock:
            self._pins[payload] = self._pins.get(payload, 0) + 1
