# graftlint: path=ray_tpu/core/runtime.py
"""Offender: the callback reaches a lock one call away."""
import threading


class DriverRuntime:
    def __init__(self):
        self._ref_lock = threading.Lock()
        self._pins = {}

    def _apply_pin(self, payload):
        with self._ref_lock:
            self._pins[payload] = self._pins.get(payload, 0) + 1

    def _native_cb_refpins(self, ws, payload):
        self._apply_pin(payload)
