# graftlint: path=ray_tpu/core/runtime.py
"""Compliant: the callback only queues; the reader loop's drain point
applies the transitions under the ref lock."""
import threading
from collections import deque


class DriverRuntime:
    def __init__(self):
        self._ref_lock = threading.Lock()
        self._native_pin_q = deque()
        self._pins = {}

    def _native_cb_refpins(self, ws, payload):
        self._native_pin_q.append((ws, payload))

    def _drain_native_pins(self):
        while True:
            try:
                ws, payload = self._native_pin_q.popleft()
            except IndexError:
                return
            with self._ref_lock:
                self._pins[payload] = self._pins.get(payload, 0) + 1
