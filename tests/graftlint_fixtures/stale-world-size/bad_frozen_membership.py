"""Positive fixture: world_size/rank frozen into state that outlives
the training session — module globals, class attributes, def-time
defaults, and a closure cell."""
from ray_tpu.train import get_context

WORLD_SIZE = get_context().world_size          # module state


class LRSchedule:
    ranks = get_context().get_world_size()     # class state

    def scale(self, lr, ws=get_context().world_size):  # def-time default
        return lr * ws


def make_step(ctx):
    rank = ctx.get_world_rank()                # frozen into a closure

    def step(batch):
        return batch[rank]

    return step
