"""Negative fixture: membership is re-read from the TrainContext at use
time; the CONTEXT object may be captured (its fields are re-stamped per
session), and reads passed as plain call arguments are fine."""
from ray_tpu.train import get_context


def train_loop(config):
    ctx = get_context()
    for _ in range(config["epochs"]):
        ws = ctx.get_world_size()              # fresh read each epoch
        do_step(config["lr"] * ws, ctx.world_rank)


def make_step(ctx):
    def step(batch):
        # re-read inside the closure: always the current membership
        return batch[ctx.get_world_rank()]

    return step


def do_step(lr, rank):
    return lr, rank
