"""Positive fixture: dynamic event names and convention violations."""

from ray_tpu.util import events


def report(kind: str) -> None:
    # BAD: non-literal name — a dynamic funnel hides which code path
    # emitted the event
    events.emit("worker_" + kind, pid=1)
    # BAD: f-string name is still non-literal for events (no prefix form)
    events.record(f"death_{kind}")
    # BAD: literal but violates the flat lower_snake convention
    events.emit("Worker::Death", pid=2)
