"""Negative fixture: literal lower_snake names, one call site each.

(The catalog-membership and stale-entry checks only run when
util/events.py is part of the linted project / whole-package scope, so
a standalone fixture exercises the literal + convention + uniqueness
contracts.)
"""

from ray_tpu.util import events as _events


def on_spawn(pid: int) -> None:
    _events.emit("demo_worker_spawn", pid=pid)


def on_death(pid: int, cause: str):
    return _events.record("demo_worker_death", pid=pid, cause=cause)
