# graftlint: path=ray_tpu/serve/foo.py
"""Negative fixture: every failure exit either follows a release (the
roll_back-closure shape of llm._claim_blocks counts — the release is
lexically inside the claim..exit interval) or sits on the claim-failed
branch (``if fresh is None:``), where nothing is held."""


def admit(pool, req):
    fresh = pool.alloc(4)
    if fresh is None:
        return False
    if req.deadline_passed:
        pool.release_all(fresh)
        return False
    req.table = fresh
    return True


def admit_with_rollback(pool, trie, req):
    blocks, matched, cow = trie.match(req.prompt)
    fresh = pool.alloc(4 - len(blocks))

    def roll_back():
        pool.release_all(blocks)
        if cow is not None:
            pool.release(cow)

    if fresh is None:
        roll_back()
        return False
    req.table = blocks + fresh
    return True
