# graftlint: path=ray_tpu/serve/foo.py
"""Positive fixture: a failure exit after ``pool.alloc()`` without
releasing the claim must fire — an un-admitted request holding blocks
leaks pool capacity until process death."""


def admit(pool, req):
    fresh = pool.alloc(4)
    if fresh is None:
        return False
    if req.deadline_passed:
        return False  # leaks the 4 claimed blocks
    req.table = fresh
    return True
