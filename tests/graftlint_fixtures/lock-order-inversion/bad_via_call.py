"""Offender one call away: other() holds b and calls a helper that
acquires a, while one() nests a->b directly."""
import threading


class ViaCall:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.x = 0

    def one(self):
        with self.a_lock:
            with self.b_lock:
                self.x = 1

    def other(self):
        with self.b_lock:
            self._helper()

    def _helper(self):
        with self.a_lock:
            self.x = 2
