"""Offender: a->b in one method, b->a in another (deadlock candidate)."""
import threading


class TwoLocks:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.x = 0

    def one(self):
        with self.a_lock:
            with self.b_lock:
                self.x = 1

    def other(self):
        with self.b_lock:
            with self.a_lock:
                self.x = 2
