"""Offender: a->b->c in one method vs c->a in another — the inversion is
between NON-adjacent locks in the chain (a,c)."""
import threading


class Chain:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.c_lock = threading.Lock()
        self.x = 0

    def one(self):
        with self.a_lock:
            with self.b_lock:
                with self.c_lock:
                    self.x = 1

    def other(self):
        with self.c_lock:
            with self.a_lock:
                self.x = 2
