# graftlint: path=ray_tpu/core/fake_helper.py
"""Offender: a try/except-guarded import is STILL module scope — every
zygote worker boot pays it."""
try:
    import jax
except ImportError:
    jax = None
