# graftlint: path=ray_tpu/core/fake_helper.py
"""Offender: module-scope jax import in a zygote-imported core module."""
import os

import jax.numpy as jnp


def norm(x):
    return jnp.linalg.norm(x) + len(os.sep)
