# graftlint: path=ray_tpu/core/fake_helper.py
"""Compliant: a TYPE_CHECKING import never runs at worker boot."""
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import jax


def norm(x: "jax.Array"):
    import jax.numpy as jnp

    return jnp.linalg.norm(x)
