# graftlint: path=ray_tpu/core/fake_helper.py
"""Compliant: jax deferred to the function that needs it."""
import os


def norm(x):
    import jax.numpy as jnp

    return jnp.linalg.norm(x) + len(os.sep)
