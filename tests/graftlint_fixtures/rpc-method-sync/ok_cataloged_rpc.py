# graftlint: path=ray_tpu/cluster/foo.py
"""Negative fixture: cataloged RPC literals are clean — including the
indirect-sender shapes (method literal at arg index 1) and the dynamic
``"kv_" + op`` dispatch (cataloged via GCS_RPC_DYNAMIC_PREFIXES, so the
extractor must not flag the non-literal first argument)."""


def dump_actors(gcs):
    return gcs.call("actor_list")


def reserve(self, nid, spec):
    return self._pg_call(nid, "pg_prepare", spec)


def kv_op(gcs, op, *args):
    return gcs.call("kv_" + op, *args)


def forward(self, peer, spec):
    return self._call_with_attempt(peer, "submit_spec", spec)
