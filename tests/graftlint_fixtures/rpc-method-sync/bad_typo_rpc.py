# graftlint: path=ray_tpu/cluster/foo.py
"""Positive fixture: an RPC literal that names no cataloged GCS or peer
method (here a typo of ``actor_list``) must fire — it would fail at
runtime with method-not-found."""


def dump_actors(gcs):
    return gcs.call("actor_lst")
