# graftlint: path=ray_tpu/cluster/gcs_server.py
"""Positive fixture: a ``rpc_*`` method not in the GCS_RPC catalog must
fire — the catalog is the review surface for wire-protocol changes, so
a new method lands as a protocol.py diff hunk alongside the code."""


class GcsServer:
    def rpc_frobnicate(self, ctx):
        return None
