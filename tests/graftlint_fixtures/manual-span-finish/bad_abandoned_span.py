# graftlint: path=ray_tpu/serve/foo.py
"""Positive fixture: a manual span started and then abandoned must fire
— nothing ever records it, so the request latency decomposition
silently loses a term (worse than crashing)."""

from ray_tpu.util import tracing


def handle(req):
    ms = tracing.manual_span("serve.foo::request", {"route": req.route})
    return req.execute()
