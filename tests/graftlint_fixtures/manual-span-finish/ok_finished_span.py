# graftlint: path=ray_tpu/serve/foo.py
"""Negative fixture: finished and handed-off manual spans are clean —
finish-in-finally (with the None guard for disabled tracing), storage
onto an object the caller finishes, and pass-through to a consumer."""

from ray_tpu.util import tracing


def handle(req):
    ms = tracing.manual_span("serve.foo::request", {"route": req.route})
    try:
        return req.execute()
    finally:
        if ms is not None:
            ms.finish()


def start_stream(req):
    span = tracing.manual_span("serve.foo::stream")
    req.span = span  # the request teardown path finishes it
    return req


def enqueue(req, sink):
    pending = tracing.manual_span("serve.foo::queue")
    sink.admit(req, pending)
