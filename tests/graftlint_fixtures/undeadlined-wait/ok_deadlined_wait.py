# graftlint: path=ray_tpu/cluster/fake_client.py
"""Compliant: every wait carries a deadline and loops."""
import threading


class Client:
    def __init__(self):
        self.reply_event = threading.Event()
        self.stopped = False

    def call(self, timeout=60.0):
        import time

        deadline = time.monotonic() + timeout
        while not self.reply_event.wait(0.5):
            if time.monotonic() > deadline:
                raise TimeoutError("peer wedged")
