# graftlint: path=ray_tpu/cluster/fake_client.py
"""Offender: a cluster-plane thread parked forever on a bare wait."""
import threading


class Client:
    def __init__(self):
        self.reply_event = threading.Event()

    def call(self):
        self.reply_event.wait()
