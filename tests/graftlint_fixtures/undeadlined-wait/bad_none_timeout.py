# graftlint: path=ray_tpu/cluster/fake_client.py
"""Offender: wait(None)/wait(timeout=None) still parks forever."""
import threading


class Client:
    def __init__(self):
        self.reply_event = threading.Event()
        self.done_ev = threading.Event()

    def call(self):
        self.reply_event.wait(None)

    def call2(self):
        self.done_ev.wait(timeout=None)
