"""Offender: sleeps and does pipe I/O while holding the lock."""
import threading
import time


class Stalls:
    def __init__(self, conn):
        self.lock = threading.Lock()
        self.conn = conn
        self.last = None

    def poll(self):
        with self.lock:
            time.sleep(0.5)
            self.last = self.conn.recv()
