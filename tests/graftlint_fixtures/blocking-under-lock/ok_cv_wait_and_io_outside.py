"""Compliant: the only wait under the lock is on a Condition built on
that lock (which releases it); I/O happens outside."""
import threading
import time


class Polite:
    def __init__(self, conn):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.conn = conn
        self.last = None

    def poll(self):
        msg = self.conn.recv()
        time.sleep(0.01)
        with self.lock:
            self.last = msg
            self.cv.wait(1.0)
