"""Negative fixture: generators re-enter through the manual-span API;
nested defs inside a span body are other frames."""

from ray_tpu.util import tracing


def stream(items):
    span = tracing.manual_span("demo.stream::tokens")
    try:
        for item in items:
            yield item
    finally:
        if span is not None:
            span.finish()


def run(fn):
    with tracing.span("demo.run::call"):
        # a nested generator DEF does not suspend this frame
        def inner():
            yield 1

        return fn(inner)
