"""Positive fixture: generator suspends inside a thread-local span."""

from ray_tpu.util import tracing


def stream(items):
    with tracing.span("demo.stream::tokens"):
        for item in items:
            # suspended here, the span context leaks onto whatever this
            # thread runs next
            yield item
