"""Offender: a suppression with no reason, and one naming a bogus rule."""
import os

CORES = os.cpu_count()  # graftlint: disable=layering-seam
FLAGS = os.environ  # graftlint: disable=not-a-real-rule -- misspelled
HOME = os.curdir  # graftlint: disable=all
