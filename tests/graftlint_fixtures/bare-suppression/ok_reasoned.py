"""Compliant: every suppression carries its justification."""
import os

# graftlint: disable=layering-seam -- example only; this line is clean
CORES = os.cpu_count()
