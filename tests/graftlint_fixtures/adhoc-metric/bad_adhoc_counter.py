# graftlint: path=ray_tpu/core/fake_sched.py
"""Offender: an ad-hoc metrics Counter in core/ (skips metric_defs)."""
from ray_tpu.util import metrics

TASKS = metrics.Counter("rtpu_fake_tasks_total", "ad-hoc!")
