# graftlint: path=ray_tpu/core/fake_sched.py
"""Compliant: built-ins come from metric_defs.get; collections.Counter
is not a metric (the old regex flagged it)."""
from collections import Counter

from ray_tpu.util import metric_defs

TASKS = metric_defs.get("rtpu_scheduler_tasks_submitted_total")
WORDS = Counter()
