# graftlint: path=ray_tpu/core/worker.py
"""Compliant: only _recv_loop reads the pipe; replies arrive via events
the reader sets."""
import threading


class WorkerRuntime:
    def __init__(self, conn):
        self.conn = conn
        self.reply_ev = threading.Event()
        self.reply = None

    def _recv_loop(self):
        while True:
            msg = self.conn.recv()
            self.reply = msg
            self.reply_ev.set()

    def wait_reply(self, timeout):
        self.reply_ev.wait(timeout)
        return self.reply
