# graftlint: path=ray_tpu/core/worker.py
"""Offender: a second conn.recv() call site outside _recv_loop."""


class WorkerRuntime:
    def __init__(self, conn):
        self.conn = conn

    def _recv_loop(self):
        while True:
            msg = self.conn.recv()
            self._dispatch(msg)

    def _dispatch(self, msg):
        pass

    def wait_reply(self):
        return self.conn.recv()
