# graftlint: path=ray_tpu/core/fake_spawner.py
"""Compliant: workers get an explicit literal platform, never the
driver's env value."""
import os


def worker_env():
    env = {"PATH": os.environ.get("PATH", "")}
    env["JAX_PLATFORMS"] = "cpu"
    return env
