# graftlint: path=ray_tpu/core/fake_spawner.py
"""Offender: forwards the driver's JAX_PLATFORMS into a worker env."""
import os


def worker_env():
    env = {k: v for k, v in os.environ.items()
           if k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.setdefault("PATH", "/usr/bin")
    return env


def platform_flag():
    return os.environ.get("JAX_PLATFORMS", "")
