"""Offender: the timed block_until_ready pattern at MODULE scope (a
bench script, no enclosing function)."""
import time

import jax


def _work():
    return jax.numpy.zeros(8)


t0 = time.monotonic()
out = _work()
jax.block_until_ready(out)
ELAPSED = time.monotonic() - t0
