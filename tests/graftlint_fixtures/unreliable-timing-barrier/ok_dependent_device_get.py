"""Compliant: timing uses a device_get of a scalar data-dependent on all
the work (block_until_ready appears only in untimed warmup)."""
import time

import jax


def warmup(fn, x):
    jax.block_until_ready(fn(x))


def bench_step(fn, x):
    t0 = time.monotonic()
    out = fn(x)
    jax.device_get(out.sum())
    return time.monotonic() - t0
