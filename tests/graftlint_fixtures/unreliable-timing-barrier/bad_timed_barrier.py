"""Offender: block_until_ready as the completion barrier in timed code."""
import time

import jax


def bench_step(fn, x):
    t0 = time.monotonic()
    out = fn(x)
    jax.block_until_ready(out)
    return time.monotonic() - t0
