"""Negative fixture: literal names + literal-prefix f-strings are fine."""

from ray_tpu.util import tracing


def record(task):
    with tracing.span("demo.layer::thing", {"task": task}):
        pass
    # dynamic suffix behind a literal '<layer>::' prefix
    with tracing.span(f"demo.submit::{task}"):
        pass
    end = 2
    tracing.record_span("demo.layer::other", 1, end)
