"""Positive fixture: dynamic span names and convention violations."""

from ray_tpu.util import tracing


def record(section, name):
    # non-literal name: the catalog/analyzers can't grep it
    with tracing.span("phase_" + section):
        pass
    # f-string without a literal '<layer>::' prefix head
    with tracing.span(f"{section}::work"):
        pass
    # literal, but not '<layer>::<what>'
    with tracing.span("justaname"):
        pass
