# graftlint: path=ray_tpu/core/serialization.py
"""Offender: plain pickle tried before cloudpickle."""
import pickle

import cloudpickle


def serialize(obj):
    try:
        return pickle.dumps(obj)
    except Exception:
        return cloudpickle.dumps(obj)
