# graftlint: path=ray_tpu/core/serialization.py
"""Compliant: cloudpickle first (plain pickle serializes __main__
functions by reference and breaks workers)."""
import cloudpickle


def serialize(obj):
    return cloudpickle.dumps(obj)
