"""Offender: two call sites share one name; one site name is dynamic."""
from ray_tpu.util import failpoints


def send(msg):
    if failpoints.hit("fake.send"):
        return
    _push(msg)


def resend(msg, name):
    if failpoints.hit("fake.send"):
        return
    if failpoints.hit(name):
        return
    _push(msg)


def _push(msg):
    pass
