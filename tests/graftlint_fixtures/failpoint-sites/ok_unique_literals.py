"""Compliant: unique literal site names."""
from ray_tpu.util import failpoints


def send(msg):
    if failpoints.hit("fake.send"):
        return
    _push(msg)


def resend(msg):
    if failpoints.hit("fake.resend"):
        return
    _push(msg)


def _push(msg):
    pass
