# graftlint: path=ray_tpu/train/fake_step.py
"""Offender: a raw jax.jit in an ML-tier module — the compiled program
is invisible to the device plane (no name, no retrace detection)."""
import jax


def make_step(fn):
    return jax.jit(fn, donate_argnums=(0,))
