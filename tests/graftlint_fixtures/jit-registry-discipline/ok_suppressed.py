# graftlint: path=ray_tpu/serve/fake_engine.py
"""Compliant: a judged-intentional raw jit carries its reason in-tree."""
import jax


def make_probe(fn):
    # graftlint: disable=jit-registry-discipline -- one-shot warmup probe,
    # never called on the request path; registering it would pollute the
    # program table
    return jax.jit(fn)
