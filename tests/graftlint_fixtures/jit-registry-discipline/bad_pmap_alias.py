# graftlint: path=ray_tpu/rllib/fake_learner.py
"""Offender: an aliased ``from jax import pmap`` still resolves — rules
match symbols, not spellings."""
from jax import pmap as parallel_map


def make_update(fn):
    return parallel_map(fn, axis_name="dp")
