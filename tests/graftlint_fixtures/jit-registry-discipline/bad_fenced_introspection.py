# graftlint: path=ray_tpu/util/fake_probe.py
"""Offender: XLA introspection outside util/device_plane.py — each
cost_analysis() costs a lowering, each live_arrays() a full walk; the
registry already holds both."""
import jax


def probe(compiled):
    stats = compiled.cost_analysis()
    return stats, jax.live_arrays()
