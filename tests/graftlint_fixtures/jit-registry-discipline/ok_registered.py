# graftlint: path=ray_tpu/train/fake_step.py
"""Compliant: the jit goes through the device-plane registry wrapper —
named program, retrace detection, cost analysis for free."""
from ray_tpu.util.device_plane import registered_jit


def make_step(fn):
    return registered_jit(fn, name="train::fake_step", component="train",
                          donate_argnums=(0,))
