# graftlint: path=ray_tpu/util/fake_helper.py
"""Compliant: outside the ML tiers (models//train//serve//rllib) a raw
jax.jit is allowed — util-level helpers aren't registry material."""
import jax


def make_helper(fn):
    return jax.jit(fn)
