# graftlint: path=ray_tpu/serve/fake_router.py
"""Offender: an ML-layer module reaching into runtime internals."""


def depths(ids):
    from ray_tpu.core.runtime import _get_runtime

    return _get_runtime().actor_queue_depths(ids)
