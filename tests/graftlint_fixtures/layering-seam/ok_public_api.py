# graftlint: path=ray_tpu/serve/fake_router.py
"""Compliant: public API + util surface + public exception types."""
import ray_tpu
from ray_tpu.core.exceptions import ActorDiedError
from ray_tpu.util import state


def depths(ids):
    try:
        return state.actor_queue_depths(ids)
    except ActorDiedError:
        return [0 for _ in ids]


def put(x):
    return ray_tpu.put(x)
