# graftlint: path=ray_tpu/serve/__init__.py
"""Offender: a package __init__ reaching into runtime internals via a
RELATIVE import (resolves against the package itself)."""
from ..core.runtime import _get_runtime


def depths(ids):
    return _get_runtime().actor_queue_depths(ids)
