# graftlint: path=ray_tpu/serve/foo.py
"""Positive fixture: creating a shm ring whose name does not derive
from the runtime session id must fire — the shutdown sweep globs
``rtpu-chan-<session>-*``, so this segment leaks forever if the
creating process dies uncleanly."""

from ray_tpu.experimental.channel import Channel


def make_ring():
    return Channel("scratch-ring", capacity=1024, create=True)
