# graftlint: path=ray_tpu/serve/foo.py
"""Negative fixture: session-derived channel names are clean — through
transitive local dataflow (uid -> name), the aliased-class shape
(``cls = DeviceChannel if ... else Channel``), a same-module helper
function, and the attach side (create=False needs no sweep scope)."""

import uuid

from ray_tpu.experimental.channel import Channel
from ray_tpu.experimental.device_channel import DeviceChannel


def ring_name(src: str) -> str:
    from ray_tpu import get_runtime_context

    session = get_runtime_context().get_session_id()
    return f"{session}-kvx-{src}"


def make_rings(session_id: str, device: bool):
    uid = f"{session_id}-{uuid.uuid4().hex[:8]}"
    name = f"{uid}-0"
    cls = DeviceChannel if device else Channel
    return cls(name, capacity=1024, create=True)


def make_helper_ring(src: str):
    return DeviceChannel(ring_name(src), capacity=1024, create=True)


def attach_ring(name: str):
    return Channel(name, create=False)
