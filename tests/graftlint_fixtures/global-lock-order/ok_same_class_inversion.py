# graftlint: path=ray_tpu/core/foo.py
"""Negative fixture FOR THIS RULE: a plain two-lock inversion inside one
class is the per-class lock-order-inversion rule's finding (better
message, same deadlock) — the global rule must not duplicate it."""

import threading


class Engine:
    def __init__(self):
        self.lock = threading.Lock()
        self.io_lock = threading.Lock()

    def submit(self):
        with self.lock:
            with self.io_lock:
                pass

    def drain(self):
        with self.io_lock:
            with self.lock:
                pass
