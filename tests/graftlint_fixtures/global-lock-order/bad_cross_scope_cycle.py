# graftlint: path=ray_tpu/core/foo.py
"""Positive fixture: a lock-order cycle between a module-level function
and a class method — invisible to the per-class inversion rule (the two
acquisition sites live in different scopes), caught only by the merged
global graph."""

import threading

_pump_lock = threading.Lock()
_state_lock = threading.Lock()


def pump():
    with _pump_lock:
        with _state_lock:
            pass


class Flusher:
    def flush(self):
        with _state_lock:
            with _pump_lock:
                pass
