# graftlint: path=ray_tpu/core/foo.py
"""Negative fixture: every scope acquires in the same global order —
the graph is acyclic, no finding."""

import threading

_pump_lock = threading.Lock()
_state_lock = threading.Lock()


def pump():
    with _pump_lock:
        with _state_lock:
            pass


class Flusher:
    def flush(self):
        with _pump_lock:
            with _state_lock:
                pass
