"""Compliant: every post-init write happens under the lock (or in a
_locked caller-holds-the-lock helper)."""
import threading


class Tidy:
    def __init__(self):
        self.lock = threading.Lock()
        self.counter = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self.lock:
                self._bump_locked()

    def _bump_locked(self):
        self.counter += 1

    def bump(self):
        with self.lock:
            self.counter += 1
