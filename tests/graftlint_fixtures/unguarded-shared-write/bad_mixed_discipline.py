"""Offender: counter is lock-guarded in the thread loop, bare in bump()."""
import threading


class Racy:
    def __init__(self):
        self.lock = threading.Lock()
        self.counter = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self.lock:
                self.counter += 1

    def bump(self):
        self.counter += 1
