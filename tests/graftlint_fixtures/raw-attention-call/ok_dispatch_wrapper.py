"""Compliant: training code routes through ops.flash_attention (the
memory-efficient-VJP dispatcher)."""
import jax
from ray_tpu.ops import flash_attention


def loss(q, k, v):
    return flash_attention(q, k, v).sum()


def train_step(q, k, v):
    return jax.grad(loss)(q, k, v)
