"""Offender: alias-imported raw kernel called outside ops/, and a local
function reaching it handed to jax.grad."""
import jax
from ray_tpu.ops.flash_pallas import flash_attention_pallas as fap


def loss(q, k, v):
    return fap(q, k, v).sum()


def train_step(q, k, v):
    return jax.grad(loss)(q, k, v)
