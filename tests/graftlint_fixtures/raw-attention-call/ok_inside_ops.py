# graftlint: path=ray_tpu/ops/fake_dispatch.py
"""Compliant: ray_tpu/ops/ itself (impl + dispatch home) may call the
raw kernels."""
from ray_tpu.ops.flash_pallas import flash_attention_pallas


def flash_attention(q, k, v):
    return flash_attention_pallas(q, k, v)


def _fwd(q, k, v):
    return flash_attention_pallas(q, k, v)


def custom_vjp_machinery(q, k, v):
    import jax

    return jax.vjp(_fwd, q, k, v)
