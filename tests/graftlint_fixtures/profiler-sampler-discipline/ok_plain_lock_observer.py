"""Negative fixture: an observer-only sampler — plain private lock,
no failpoints, no spans; timed locks exist in the module but only
NON-sampler code touches them."""

import threading

from ray_tpu.util.contention import timed_lock


class StackSampler:
    def __init__(self):
        self._table_lock = threading.Lock()  # plain: sampler-private
        self._table = {}
        self._stop = threading.Event()

    def _sample_once(self):
        with self._table_lock:
            self._table["k"] = self._table.get("k", 0) + 1

    def _sample_loop(self):
        while not self._stop.is_set():
            self._sample_once()
            self._stop.wait(0.015)


class Runtime:
    """Instrumented runtime code MAY use timed locks — only the
    sampler's own scope is constrained."""

    def __init__(self):
        self.lock = timed_lock("driver.lock")

    def dispatch(self):
        with self.lock:
            return 1
