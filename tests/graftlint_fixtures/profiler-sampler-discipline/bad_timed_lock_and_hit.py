"""Positive fixture: a sampler loop that violates observer-only
discipline — acquires a timed lock, hits a failpoint, records a span,
and constructs a timed lock inside the loop."""

import threading

from ray_tpu.util import failpoints, tracing
from ray_tpu.util.contention import timed_lock


class StackSampler:
    def __init__(self):
        self.table_lock = timed_lock("sampler.table")
        self._stop = threading.Event()

    def _sample_once(self):
        failpoints.hit("sampler.tick")
        with tracing.span("profiling.demo::sample"):
            pass
        with self.table_lock:
            pass

    def _sample_loop(self):
        extra = timed_lock("sampler.extra")
        while not self._stop.is_set():
            self.table_lock.acquire()
            try:
                self._sample_once()
            finally:
                self.table_lock.release()
        return extra
