# graftlint: path=ray_tpu/core/runtime.py
"""Positive fixture: a dispatch arm for an op that is not in PIPE_CASTS
must fire — the regression shape of the r14 leftover ``refpin`` arm
removed by ISSUE 15 (single-transition casts were replaced by the
batched ``refpins`` op)."""


class Runtime:
    def worker_ref_delta(self, ws, oid, d):
        raise NotImplementedError

    def _handle_cast(self, ws, op, args):
        if op == "refpin":
            self.worker_ref_delta(ws, args[0], args[1])
        elif op == "refpins":
            for oid_b, d in args[0]:
                self.worker_ref_delta(ws, oid_b, d)
