# graftlint: path=ray_tpu/core/worker.py
"""Positive fixture: a worker cast op absent from PIPE_CASTS in
core/protocol.py must fire (typo'd/uncataloged pipe vocabulary)."""


class WorkerRuntime:
    def cast(self, op, *args):
        raise NotImplementedError

    def report(self, stats):
        self.cast("frobnicate", stats)
