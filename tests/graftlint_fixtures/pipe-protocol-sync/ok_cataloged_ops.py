# graftlint: path=ray_tpu/core/worker.py
"""Negative fixture: cataloged pipe ops (PIPE_CASTS / PIPE_REQS /
PIPE_WORKER_MSGS) are clean, including the tuple-send and IfExp-coalesce
shapes the extractor must see through."""


class WorkerRuntime:
    def cast(self, op, *args):
        raise NotImplementedError

    def request(self, op, *args):
        raise NotImplementedError

    def put(self, value):
        self.cast("put", value)

    def get(self, oid):
        return self.request("get", oid)

    def _flush(self, batch):
        self.conn.send(batch[0] if len(batch) == 1 else ("batch", batch))

    def _hello(self, wid):
        self.conn.send(("hello", wid))
