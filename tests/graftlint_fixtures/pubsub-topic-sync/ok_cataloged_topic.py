# graftlint: path=ray_tpu/cluster/foo.py
"""Negative fixture: cataloged channels are clean — via _publish, the
publish/subscribe RPCs, and a module-constant channel name (the
util/tracing.py shape the extractor must resolve)."""

CHANNEL = "tracing"


class Plane:
    def _publish(self, channel, payload):
        raise NotImplementedError

    def announce(self, payload):
        self._publish("nodes", payload)

    def push(self, gcs, payload):
        gcs.call("publish", CHANNEL, payload)

    def attach(self, gcs):
        gcs.call("subscribe", "objects")
