# graftlint: path=ray_tpu/cluster/gcs_server.py
"""Positive fixture: publishing a channel absent from PUBSUB_CHANNELS
must fire — nobody can be subscribed to a topic the catalog does not
know, so the payload vanishes."""


class GcsServer:
    def _publish(self, channel, payload):
        raise NotImplementedError

    def on_weather(self, payload):
        self._publish("weather", payload)
