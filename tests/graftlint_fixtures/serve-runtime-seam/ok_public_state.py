# graftlint: path=ray_tpu/serve/fake_router.py
"""Compliant: the public state surface + intra-tier privates."""
import ray_tpu
from ray_tpu.serve import handle as _handle_mod
from ray_tpu.util import state


def depths(ids):
    return state.actor_queue_depths(ids)


def loads(name):
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    return ray_tpu.get(ctrl.get_replica_loads.remote(name))


def dags():
    return dict(_handle_mod._dag_cache)
