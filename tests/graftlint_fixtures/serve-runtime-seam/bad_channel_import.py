# graftlint: path=ray_tpu/serve/fake_streamer.py
"""Offender: a serve module (not kv_transfer) riding the experimental
channel plane directly."""
from ray_tpu.experimental.device_channel import DeviceChannel


def ship(blob):
    ch = DeviceChannel("serve-side-channel", capacity=4)
    ch.put(blob)
