# graftlint: path=ray_tpu/serve/fake_router.py
"""Offender: reaching a util module's PRIVATE surface — the tempting
shortcut past the public state API."""
from ray_tpu.util import state


def depths(ids):
    return state._gcs().actor_queue_depths(ids)
