# graftlint: path=ray_tpu/serve/kv_transfer.py
"""Compliant: kv_transfer.py IS the sanctioned exception — same-host
KV-block shipping rides the experimental DeviceChannel rings."""
from ray_tpu.experimental.channel import ChannelFullError
from ray_tpu.experimental.device_channel import DeviceChannel


def ring(session):
    return DeviceChannel(f"rtpu-{session}-kv-ring", capacity=8)


def push(ch, blob):
    try:
        ch.put(blob)
    except ChannelFullError:
        return False
    return True
