# graftlint: path=ray_tpu/serve/fake_streamer.py
"""Compliant: catching a public channel exception TYPE is contract
surface (the compiled handle path does exactly this) — only transports
and channel classes are fenced to kv_transfer.py."""
from ray_tpu.experimental.channel import ChannelFullError


def push(ch, blob):
    try:
        ch.put(blob)
    except ChannelFullError:
        return False
    return True
