# graftlint: path=ray_tpu/serve/fake_router.py
"""Offender: routing code calling the private runtime accessor."""
from ray_tpu.core.runtime import _get_runtime


def depths(ids):
    return _get_runtime().actor_queue_depths(ids)
