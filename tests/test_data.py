"""Data library: transforms, execution, fusion, groupby, iterators, IO."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def rt_data():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_range_count_take(rt_data):
    ds = rdata.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches_and_fusion(rt_data):
    ds = (rdata.range(64, parallelism=4)
          .map_batches(lambda b: {"id": b["id"] * 2})
          .map_batches(lambda b: {"id": b["id"] + 1}))
    vals = [r["id"] for r in ds.take_all()]
    assert vals == [2 * i + 1 for i in range(64)]
    # the two map stages fuse into one
    from ray_tpu.data.execution import fuse_ops

    assert len(fuse_ops(ds._ops)) == 1


def test_map_filter_flat_map(rt_data):
    ds = rdata.range(10, parallelism=2).map(lambda r: {"x": int(r["id"]) * 10})
    ds = ds.filter(lambda r: r["x"] >= 50)
    ds = ds.flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    vals = [r["x"] for r in ds.take_all()]
    assert vals == [50, 51, 60, 61, 70, 71, 80, 81, 90, 91]


def test_shuffle_sort_repartition(rt_data):
    ds = rdata.range(50, parallelism=5).random_shuffle(seed=7)
    shuffled = [r["id"] for r in ds.take_all()]
    assert sorted(shuffled) == list(range(50))
    assert shuffled != list(range(50))

    ds2 = ds.sort("id", descending=True)
    assert [r["id"] for r in ds2.take(3)] == [49, 48, 47]

    ds3 = ds.repartition(3)
    assert ds3.num_blocks() == 3


def test_limit_and_union_zip(rt_data):
    a = rdata.range(10, parallelism=2).limit(4)
    assert a.count() == 4
    b = rdata.from_items([{"y": i} for i in range(4)])
    z = a.zip(b)
    rows = z.take_all()
    assert set(rows[0]) == {"id", "y"}
    u = a.union(a)
    assert u.count() == 8


def test_groupby_aggregates(rt_data):
    items = [{"k": i % 3, "v": float(i)} for i in range(12)]
    ds = rdata.from_items(items, parallelism=3)
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    assert means[0] == pytest.approx(np.mean([0, 3, 6, 9]))


def test_iter_batches_exact_sizes(rt_data):
    ds = rdata.range(100, parallelism=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32,
                                                   drop_last=True)]
    assert sizes == [32, 32, 32]


def test_streaming_split_covers_all(rt_data):
    ds = rdata.range(40, parallelism=4).materialize()
    its = ds.streaming_split(2)
    seen = []
    for it in its:
        for r in it.iter_rows():
            seen.append(r["id"])
    assert sorted(seen) == list(range(40))


def test_iter_jax_batches_sharded(rt_data):
    import jax

    from ray_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=4, fsdp=2))
    ds = rdata.range(64, parallelism=4)
    it = ds.iterator()
    batches = list(it.iter_jax_batches(batch_size=16, mesh=mesh))
    assert len(batches) == 4
    arr = batches[0]["id"]
    assert isinstance(arr, jax.Array)
    assert arr.shape == (16,)
    assert len(arr.sharding.device_set) == 8


def test_write_read_roundtrip(rt_data, tmp_path):
    ds = rdata.from_items([{"a": i, "b": float(i) / 2} for i in range(20)],
                          parallelism=2)
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rdata.read_parquet(pq_dir)
    assert back.count() == 20
    assert back.sum("a") == sum(range(20))

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    back_csv = rdata.read_csv(csv_dir)
    assert back_csv.count() == 20

    js_dir = str(tmp_path / "js")
    ds.write_json(js_dir)
    assert rdata.read_json(js_dir).count() == 20


def test_columns_schema_stats(rt_data):
    ds = rdata.from_items([{"a": 1, "b": 2.0}])
    assert set(ds.columns()) == {"a", "b"}
    assert ds.mean("b") == 2.0
    assert ds.min("a") == 1


def test_iteration_overlaps_producer(rt_data):
    """Data iteration must consume early blocks while later map tasks still
    run (streaming-generator-backed map stage, reference streaming
    exchange)."""
    import time

    # warm the pool so spawn latency doesn't mask the overlap
    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(4)])

    def slow_identity(batch):
        time.sleep(0.8)
        return batch

    ds = rdata.range(8, parallelism=8).map_batches(slow_identity)
    t0 = time.monotonic()
    it = iter(ds.iter_batches(batch_size=1))
    next(it)
    first_latency = time.monotonic() - t0
    total = sum(1 for _ in it) + 1
    wall = time.monotonic() - t0
    assert total == 8
    # 8 blocks x 0.8s; serialized-with-drain would hold the first batch
    # until everything finished (~wall); streaming must hand it over well
    # before the end (generous ratio: 2-vCPU box, CLAUDE.md margins rule)
    assert first_latency < wall * 0.75, (
        f"first batch at {first_latency:.1f}s of {wall:.1f}s total")


def test_arrow_roundtrip(rt_data):
    import pyarrow as pa

    table = pa.table({"x": [1, 2, 3, 4], "y": [0.5, 1.5, 2.5, 3.5]})
    ds = rdata.from_arrow(table)
    out = ds.map_batches(lambda b: {"x": b["x"] * 2, "y": b["y"]}).to_arrow()
    assert out.column("x").to_pylist() == [2, 4, 6, 8]
    assert out.column("y").to_pylist() == [0.5, 1.5, 2.5, 3.5]


def test_arrow_tensor_columns(rt_data):
    import pyarrow as pa

    ds = rdata.from_items([{"vec": np.arange(3, dtype=np.float32) + i}
                           for i in range(4)])
    table = ds.to_arrow()
    assert isinstance(table, pa.Table)
    assert table.column("vec").to_pylist()[0] == [0.0, 1.0, 2.0]


def test_actor_pool_map_operator(rt_data):
    """map_batches with a class runs on a warm actor pool: per-actor init
    happens once per actor, not once per block (reference
    ActorPoolMapOperator role; VERDICT r3 #5)."""
    import ray_tpu.data as rd

    class AddConst:
        def __init__(self):
            import os
            # identity proves warm reuse: the same pid serves many blocks
            self._pid = os.getpid()

        def __call__(self, batch):
            batch["pid"] = np.full(len(batch["id"]), self._pid)
            return batch

    ds = rd.range(64, parallelism=8).map_batches(
        AddConst, compute=rd.ActorPoolStrategy(min_size=1, max_size=2))
    out = ds.take_all()
    assert sorted(r["id"] for r in out) == list(range(64))
    pids = {r["pid"] for r in out}
    # 8 blocks over a <=2-actor pool: far fewer distinct pids than blocks
    assert 1 <= len(pids) <= 2


def test_distributed_shuffle_and_sort(rt_data):
    import ray_tpu.data as rd

    ds = rd.range(1000, parallelism=10)
    shuffled = ds.random_shuffle(seed=7).take_all()
    assert sorted(r["id"] for r in shuffled) == list(range(1000))
    assert [r["id"] for r in shuffled] != list(range(1000))

    ds2 = rd.from_items([{"k": int(v)} for v in
                         np.random.default_rng(0).permutation(500)])
    out = ds2.sort("k").take_all()
    assert [r["k"] for r in out] == list(range(500))
    outd = ds2.sort("k", descending=True).take_all()
    assert [r["k"] for r in outd] == list(range(499, -1, -1))


def test_repartition_balances_rows(rt_data):
    import ray_tpu.data as rd

    ds = rd.range(100, parallelism=7).repartition(4)
    blocks = [b for b in ds.iter_blocks()]
    sizes = [len(b["id"]) for b in blocks if len(b["id"])]
    assert sum(sizes) == 100
    assert max(sizes) - min(sizes) <= 1 or len(sizes) == 4


def test_groupby_runs_distributed_driver_stays_thin(rt_data):
    """groupby aggregation over data larger than any single block never
    concatenates the dataset in the driver: only aggregated rows return
    (VERDICT r3 #5 done criterion — driver RSS stays flat)."""
    import ray_tpu.data as rd

    def _hwm():
        # VmHWM is absent on some sandboxed kernels (gVisor): ru_maxrss is
        # the same peak-RSS number (kB on Linux) and exists everywhere
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1])
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)

    # warm the pipeline machinery first so baseline includes fixed costs
    warm = rd.range(1000, parallelism=2).groupby("id").count()
    warm.take_all()

    # the arena's background prefault commits up to 512 MB into THIS
    # process's RSS; under suite load it can spill past the baseline
    # sample and masquerade as a driver concat — wait it out first
    import threading

    for t in threading.enumerate():
        if t.name == "rtpu-arena-prefault":
            t.join(timeout=60)

    n_rows = 2_000_000  # 16 MB/block x 8 blocks = 128 MB of float64
    base = _hwm()
    ds = rd.range(n_rows, parallelism=8).add_column(
        "g", lambda b: b["id"] % 10)
    out = ds.groupby("g").sum("id")
    rows = out.take_all()
    assert len(rows) == 10
    total = sum(r["sum(id)"] for r in rows)
    assert total == n_rows * (n_rows - 1) / 2
    delta_kb = _hwm() - base
    # old path: >=128MB concat in the driver. New path: only agg rows.
    assert delta_kb < (64 << 10), f"driver ballooned {delta_kb} kB"


def test_groupby_string_keys_stable_across_workers(rt_data):
    """String group keys must hash identically in every worker process
    (Python hash() is per-process salted): each key appears EXACTLY once
    in the aggregated output."""
    import ray_tpu.data as rd

    names = ["alpha", "beta", "gamma", "delta", "epsilon"]
    rows = [{"name": names[i % 5], "v": float(i)} for i in range(500)]
    out = rd.from_items(rows).groupby("name").count().take_all()
    assert sorted(r["name"] for r in out) == sorted(names)
    assert all(r["count()"] == 100 for r in out)


def test_optimizer_golden_plans():
    """Rule-based plan rewrites (reference golden-plan optimizer tests):
    redundant shuffles drop, limits fuse, map chains fuse — and the rules
    compose across passes."""
    from ray_tpu.data import (EliminateRedundantShuffles, FuseLimits,
                              Optimizer, plan_summary)
    from ray_tpu.data.execution import LimitOp, MapOp, ShuffleOp

    def m(name):
        return MapOp(name=name, fn=lambda b: [b])

    plan = [
        m("a"),
        ShuffleOp(name="s1", kind="random_shuffle"),
        ShuffleOp(name="s2", kind="repartition", args={"n": 4}),
        m("b"),
        m("c"),
        LimitOp(name="l1", limit=100),
        LimitOp(name="l2", limit=10),
    ]
    out = Optimizer().optimize(plan)
    # rs->repartition must NOT collapse (a repartition is order-preserving
    # and cannot stand in for a shuffle)
    assert plan_summary(out) == [
        "map:a", "shuffle:random_shuffle", "shuffle:repartition",
        "map:b->c", "limit:10"], plan_summary(out)

    # same-kind exchanges DO collapse: rep->rep keeps the last
    rep2 = [ShuffleOp(name="r1", kind="repartition", args={"n": 8}),
            ShuffleOp(name="r2", kind="repartition", args={"n": 2})]
    assert plan_summary(Optimizer().optimize(rep2)) == [
        "shuffle:repartition"]
    # a SEEDED trailing shuffle keeps its predecessor (deterministic
    # output depends on the full chain)
    seeded = [ShuffleOp(name="s1", kind="random_shuffle"),
              ShuffleOp(name="s2", kind="random_shuffle",
                        args={"seed": 7})]
    assert len(Optimizer().optimize(seeded)) == 2

    # composition: dropping the middle shuffle exposes maps to fusion
    plan2 = [m("x"), ShuffleOp(name="s", kind="random_shuffle"),
             ShuffleOp(name="s2", kind="random_shuffle")]
    out2 = Optimizer().optimize(plan2)
    assert plan_summary(out2) == ["map:x", "shuffle:random_shuffle"]

    # custom rule list is honored (no fusion)
    out3 = Optimizer(rules=[FuseLimits()]).optimize(plan)
    assert plan_summary(out3)[-1] == "limit:10"
    assert "map:b" in plan_summary(out3)  # maps NOT fused

    # an empty rule set is the identity
    assert plan_summary(Optimizer(rules=[]).optimize(plan)) == \
        plan_summary(plan)
    assert EliminateRedundantShuffles().name == "EliminateRedundantShuffles"


def test_backpressure_policies_bound_concurrency(rt_data):
    """A ConcurrencyCap policy bounds a map stage's in-flight tasks; the
    pipeline still completes correctly (reference backpressure_policy)."""
    import ray_tpu.data as rd
    from ray_tpu.data import ConcurrencyCapBackpressurePolicy, ExecutionOptions

    ds = rd.range(40, parallelism=8).map(lambda r: {"v": r["id"] * 2})
    ds._options = ExecutionOptions(
        max_in_flight=8,
        backpressure_policies=(ConcurrencyCapBackpressurePolicy(2),))
    vals = sorted(r["v"] for r in ds.iter_rows())
    assert vals == [i * 2 for i in range(40)]


def test_redundant_shuffle_dropped_end_to_end(rt_data):
    """The optimizer rewrite holds under real execution: double shuffle
    produces the same multiset of rows as one."""
    import ray_tpu.data as rd

    ds = rd.range(30, parallelism=4).random_shuffle().random_shuffle()
    assert sorted(r["id"] for r in ds.iter_rows()) == list(range(30))


def test_read_images_tensor_column(rt_data, tmp_path):
    """read_images with size stacks into an [N, H, W, C] tensor column
    (TPU-ingest layout); without size, per-image object arrays."""
    from PIL import Image

    for i in range(3):
        Image.new("RGB", (10 + i, 8 + i), (i * 40, 0, 0)).save(
            tmp_path / f"img{i}.png")
    from ray_tpu import data

    ds = data.read_images(str(tmp_path), size=(16, 12))
    rows = ds.take_all()
    assert len(rows) == 3
    batch = next(iter(ds.iter_batches(batch_size=3)))
    assert batch["image"].shape == (3, 16, 12, 3)
    assert batch["image"].dtype == np.uint8

    ds2 = data.read_images(str(tmp_path))
    first = ds2.take_all()[0]["image"]
    assert first.shape[-1] == 3  # native size preserved


def test_topology_overlaps_fast_and_slow_stages(rt_data):
    """VERDICT r4 #9 golden test: a fast CPU-decode stage and a slow
    (actor-pool) TPU-ingest stage run CONCURRENTLY under the per-operator
    topology, and the fast stage cannot run unboundedly ahead — its
    output buffering is capped by the bounded inter-op queue."""
    import time as _t

    from ray_tpu.data import execution as ex

    n_blocks = 8
    blocks = [{"i": np.array([i])} for i in range(n_blocks)]

    def fast(block):
        t0 = _t.monotonic()
        _t.sleep(0.05)
        return [{**block, "fast_iv": np.array([t0, _t.monotonic()])}]

    def slow(block):
        t0 = _t.monotonic()
        _t.sleep(0.2)
        return [{**block, "slow_iv": np.array([t0, _t.monotonic()])}]

    def make_ops():
        return [
            ex.MapOp("fast_decode", fast),
            ex.MapOp("slow_ingest", slow,
                     compute=ex.ActorPoolStrategy(
                         min_size=2, max_size=2,
                         max_tasks_in_flight_per_actor=2)),
        ]

    opts = ex.ExecutionOptions(max_in_flight=2, optimizer=_NoopOptimizer())
    # warm: workers pay a one-time first-by-ref-arg cost (~0.3s each) and
    # the actor pool spawns — never time cold (CLAUDE.md)
    list(ex.execute_streaming(iter(blocks[:2]), make_ops(), opts))
    t0 = _t.monotonic()
    out = [ray_tpu.get(r) for r in
           ex.execute_streaming(iter(blocks), make_ops(), opts)]
    wall = _t.monotonic() - t0
    assert len(out) == n_blocks
    assert sorted(int(b["i"][0]) for b in out) == list(range(n_blocks))

    # concurrency: some fast-stage interval overlaps some slow-stage
    # interval (the pipeline genuinely runs both stages at once)
    fast_ivs = [b["fast_iv"] for b in out]
    slow_ivs = [b["slow_iv"] for b in out]
    overlap = any(f[0] < s[1] and s[0] < f[1]
                  for f in fast_ivs for s in slow_ivs)
    assert overlap, (fast_ivs, slow_ivs)
    # No wall-clock bound: the interval-overlap check above already
    # proves the stages ran concurrently, and any duration assertion
    # would violate CLAUDE.md's determinism rule under the box's 2-4x
    # load swings. (Warm pipelined runs measure ~0.9s vs 2.0s serial.)
    del wall

    # bounded buffering: the slow stage's input queue never exceeded the
    # inter-op bound (fast stage was backpressured, not unbounded)
    stats = ex._LAST_TOPOLOGY_STATS
    bound = max(2, 2 * opts.max_in_flight)
    assert stats["max_inq"]["slow_ingest"] <= bound, stats
    assert stats["dispatches"] == {"fast_decode": 8, "slow_ingest": 8}, stats


class _NoopOptimizer:
    def optimize(self, ops):
        return ops
