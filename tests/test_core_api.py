"""Core API tests: tasks, objects, actors, wait, errors.

Modeled on the reference's ``python/ray/tests/test_basic.py`` coverage.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.exceptions import ActorDiedError, GetTimeoutError, TaskError


def test_put_get(rt):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42


def test_put_get_large_numpy(rt):
    x = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(x)
    y = ray_tpu.get(ref)
    np.testing.assert_array_equal(x, y)


def test_simple_task(rt):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_arg(rt):
    @ray_tpu.remote
    def double(x):
        return x * 2

    r1 = double.remote(10)
    r2 = double.remote(r1)
    assert ray_tpu.get(r2) == 40


def test_task_large_arg_and_result(rt):
    @ray_tpu.remote
    def f(x):
        return x + 1.0

    x = np.ones((512, 512), dtype=np.float32)
    out = ray_tpu.get(f.remote(x))
    assert out.shape == (512, 512)
    assert float(out[0, 0]) == 2.0


def test_multiple_returns(rt):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(rt):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom!")

    with pytest.raises(TaskError) as ei:
        ray_tpu.get(boom.remote())
    assert "boom!" in str(ei.value)


def test_dependency_error_propagates(rt):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom!")

    @ray_tpu.remote
    def use(x):
        return x

    with pytest.raises(TaskError):
        ray_tpu.get(use.remote(boom.remote()))


def test_wait(rt):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(20)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, rest = ray_tpu.wait([f, s], num_returns=1, timeout=15)
    assert ready == [f]
    assert rest == [s]


def test_get_timeout(rt):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_nested_tasks(rt):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(5)) == 11


def test_actor_basic(rt):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    assert ray_tpu.get(c.inc.remote(5)) == 16
    assert ray_tpu.get(c.value.remote()) == 16


def test_actor_ordering(rt):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return list(self.items)

    a = Appender.remote()
    refs = [a.add.remote(i) for i in range(20)]
    final = ray_tpu.get(refs[-1])
    assert final == list(range(20))


def test_named_actor(rt):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    s = Store.options(name="kv_store").remote()
    ray_tpu.get(s.set.remote("a", 1))
    s2 = ray_tpu.get_actor("kv_store")
    assert ray_tpu.get(s2.get.remote("a")) == 1


def test_actor_error(rt):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "ok"

    b = Bad.remote()
    with pytest.raises(TaskError):
        ray_tpu.get(b.fail.remote())
    # actor survives method errors
    assert ray_tpu.get(b.ok.remote()) == "ok"


def test_kill_actor(rt):
    @ray_tpu.remote
    class Sleeper:
        def ping(self):
            return "pong"

    s = Sleeper.remote()
    assert ray_tpu.get(s.ping.remote()) == "pong"
    ray_tpu.kill(s)
    with pytest.raises((ActorDiedError, TaskError)):
        ray_tpu.get(s.ping.remote(), timeout=10)


def test_actor_passing_handles(rt):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.inc.remote())

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(bump.remote(c)) == 2


def test_parallelism(rt):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    # warm the pool first so worker spawn latency doesn't skew the timing
    ray_tpu.get([sleepy.remote(0.01) for _ in range(4)])
    start = time.time()
    refs = [sleepy.remote(1.0) for _ in range(4)]
    ray_tpu.get(refs)
    elapsed = time.time() - start
    assert elapsed < 3.5, f"4x1s tasks took {elapsed:.1f}s — not parallel"


def test_cluster_resources(rt):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0


def test_placement_group(rt):
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert ray_tpu.get(pg.ready.remote() if hasattr(pg.ready, "remote") else pg.ready()) is True

    @ray_tpu.remote
    def where():
        return 1

    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    r = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    assert ray_tpu.get(r) == 1
    remove_placement_group(pg)
    avail = ray_tpu.available_resources()
    assert avail["CPU"] == 4.0


def test_runtime_env_py_modules(rt, tmp_path):
    """py_modules ships a local package through the GCS KV: workers import
    it without sharing the driver's filesystem layout (reference
    _private/runtime_env/py_modules.py)."""
    pkg = tmp_path / "mylib"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("MAGIC = 12345\n")
    (pkg / "calc.py").write_text("def triple(x):\n    return 3 * x\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(pkg)]})
    def use_pkg():
        import mylib
        from mylib.calc import triple

        return mylib.MAGIC, triple(7)

    assert ray_tpu.get(use_pkg.remote(), timeout=60) == (12345, 21)

    # the module must NOT leak into tasks without the runtime_env
    @ray_tpu.remote
    def no_pkg():
        try:
            import mylib  # noqa: F401

            return "leaked"
        except ImportError:
            return "clean"

    assert ray_tpu.get(no_pkg.remote(), timeout=60) == "clean"


def test_runtime_env_conda_rejected_pip_normalized(rt):
    """conda/container envs are rejected loudly (pointing at the pip
    plugin); a pip env normalizes at submit time (the r5 pip plugin —
    full behavior in test_core_robustness's venv isolation test)."""
    @ray_tpu.remote(runtime_env={"conda": ["requests"]})
    def f():
        return 1

    with pytest.raises(ValueError, match="pip"):
        f.remote()

    from ray_tpu.runtime_env import normalize_pip_env

    env = normalize_pip_env(["requests==2.0"])
    assert env["uri"].startswith("pipenv-")


def test_submit_spec_template_cache_and_invalidation(rt):
    """Submit fast-path (r13): the invariant spec parts are computed once
    per (function, option-set); a changed option set NEVER reuses a stale
    template (``options()`` returns a fresh instance, fresh template)."""
    from ray_tpu.core.runtime import _get_runtime

    runtime = _get_runtime()

    @ray_tpu.remote
    def f(x):
        return x

    t1 = f._template(runtime)
    assert f._template(runtime) is t1          # cached per instance
    assert t1["resources"] == {"CPU": 1.0}

    g = f.options(num_cpus=2, max_retries=5)
    t2 = g._template(runtime)
    assert t2 is not t1                         # new option set, new template
    assert t2["resources"] == {"CPU": 2.0}
    assert t2["max_retries"] == 5
    assert f._template(runtime) is t1           # original untouched

    # instantiated specs carry fresh ids and the template's options
    spec_a = _spec_of(g)
    spec_b = _spec_of(g)
    assert spec_a["task_id"] != spec_b["task_id"]
    assert spec_a["return_ids"] != spec_b["return_ids"]
    assert spec_a["resources"] == {"CPU": 2.0}
    assert spec_a["retries_left"] == 5

    # results still correct through the cached path
    assert ray_tpu.get([f.remote(i) for i in range(5)]) == list(range(5))
    assert ray_tpu.get(g.remote(7)) == 7

    # actor-method templates: cached on the handle, keyed by options
    @ray_tpu.remote
    class A:
        def m(self, x):
            return x

    a = A.remote()
    assert ray_tpu.get(a.m.remote(1)) == 1
    cache = a._tmpl_cache
    assert ("m", 1, None) in cache
    tmpl = cache[("m", 1, None)]
    assert ray_tpu.get(a.m.remote(2)) == 2
    assert cache[("m", 1, None)] is tmpl        # reused across calls
    # different num_returns -> different template key
    @ray_tpu.remote
    class B:
        def two(self):
            return 1, 2

    b = B.remote()
    assert ray_tpu.get(list(b.two.options(num_returns=2).remote())) == [1, 2]
    assert ("two", 2, None) in b._tmpl_cache


def _spec_of(remote_fn):
    from ray_tpu.core import task_spec as ts
    from ray_tpu.core.runtime import _get_runtime

    return ts.spec_from_template(
        remote_fn._template(_get_runtime()), [], {})


def test_pipe_casts_coalesce_into_batches(rt):
    """Control-message coalescing (r13): a worker-side client's submit
    burst reaches the driver as batched frames — the coalesced-batch
    histogram records multi-message frames."""
    from ray_tpu.util.metrics import registry_records

    def batch_hist():
        total_msgs, frames = 0, 0
        for rec in registry_records():
            if rec["name"] != "rtpu_pipe_batch_messages":
                continue
            for _key, (_counts, s, n) in rec["samples"]:
                total_msgs += s
                frames += n
        return total_msgs, frames

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Client:
        def burst(self, n):
            ray_tpu.get([noop.remote() for _ in range(n)])
            return n

    c = Client.remote()
    assert ray_tpu.get(c.burst.remote(5)) == 5   # warm
    msgs0, frames0 = batch_hist()
    assert ray_tpu.get(c.burst.remote(150)) == 150
    msgs, frames = batch_hist()
    d_msgs, d_frames = msgs - msgs0, frames - frames0
    assert d_frames > 0, "no coalesced frames observed"
    # batches actually coalesce: on average >= 2 messages per batch frame
    assert d_msgs / d_frames >= 2.0, (d_msgs, d_frames)
    ray_tpu.kill(c)
