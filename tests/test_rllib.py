"""RLlib: GAE/vtrace math, modules, PPO learning CartPole, IMPALA, replay."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_rl():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_compute_gae_simple():
    from ray_tpu.rllib import compute_gae

    # single env, no dones: GAE with lam=1 == discounted MC - values
    t_len = 5
    rewards = np.ones((t_len, 1), np.float32)
    values = np.zeros((t_len, 1), np.float32)
    dones = np.zeros((t_len, 1), bool)
    truncs = np.zeros((t_len, 1), bool)
    last_v = np.zeros((1,), np.float32)
    adv, ret = compute_gae(rewards, values, dones, truncs, last_v,
                           gamma=0.9, lam=1.0)
    expect_t0 = sum(0.9 ** i for i in range(t_len))
    assert adv[0, 0] == pytest.approx(expect_t0)
    assert ret[0, 0] == pytest.approx(expect_t0)


def test_compute_gae_respects_done():
    from ray_tpu.rllib import compute_gae

    rewards = np.array([[1.0], [1.0]], np.float32)
    values = np.zeros((2, 1), np.float32)
    dones = np.array([[True], [False]])
    truncs = np.zeros((2, 1), bool)
    last_v = np.ones((1,), np.float32) * 100
    adv, _ = compute_gae(rewards, values, dones, truncs, last_v,
                         gamma=0.9, lam=1.0)
    # step0 ends an episode: no bootstrap across it
    assert adv[0, 0] == pytest.approx(1.0)


def test_vtrace_on_policy_reduces_to_returns():
    from ray_tpu.rllib import compute_vtrace

    t_len = 4
    logp = np.zeros((t_len, 1), np.float32)
    rewards = np.ones((t_len, 1), np.float32)
    values = np.zeros((t_len, 1), np.float32)
    dones = np.zeros((t_len, 1), bool)
    last_v = np.zeros((1,), np.float32)
    vs, pg_adv = compute_vtrace(logp, logp, rewards, values, dones,
                                last_v, gamma=0.9)
    expect = sum(0.9 ** i for i in range(t_len))
    assert vs[0, 0] == pytest.approx(expect)


def test_rl_module_forward_shapes():
    import jax

    from ray_tpu.rllib import RLModuleSpec

    spec = RLModuleSpec(observation_dim=4, action_dim=2)
    mod = spec.build()
    params = mod.init(jax.random.PRNGKey(0))
    obs = np.zeros((7, 4), np.float32)
    out = mod.forward_train(params, obs)
    assert out["action_dist_inputs"].shape == (7, 2)
    assert out["vf_preds"].shape == (7,)
    exp = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert exp["actions"].shape == (7,)
    logp, ent = mod.logp_entropy(out, np.zeros((7,), np.int64))
    assert logp.shape == (7,) and ent.shape == (7,)
    assert np.all(np.asarray(ent) > 0)


def test_env_runner_samples(rt_rl):
    from ray_tpu.rllib import SingleAgentEnvRunner

    runner = SingleAgentEnvRunner("CartPole-v1", num_envs=2, seed=0)
    batch = runner.sample(num_steps=10)
    assert batch["obs"].shape == (10, 2, 4)
    assert batch["actions"].shape == (10, 2)
    assert batch["next_obs"].shape == (2, 4)
    runner.stop()


def test_replay_buffers():
    from ray_tpu.rllib import PrioritizedReplayBuffer, ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    buf.add({"x": np.arange(150, dtype=np.float32)})
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["x"].shape == (32,)

    pbuf = PrioritizedReplayBuffer(capacity=50, seed=0)
    pbuf.add({"x": np.arange(50, dtype=np.float32)})
    s = pbuf.sample(16)
    assert "weights" in s and "batch_indexes" in s
    pbuf.update_priorities(s["batch_indexes"], np.full(16, 10.0))
    s2 = pbuf.sample(1000)
    # heavily prioritized indexes dominate the resample
    frac = np.isin(s2["batch_indexes"], s["batch_indexes"]).mean()
    assert frac > 0.5


def test_ppo_learns_cartpole_local(rt_rl):
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=256)
              .training(lr=3e-4, minibatch_size=256, num_epochs=8,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for _ in range(10):
        result = algo.train()
        returns.append(result.get("episode_return_mean", 0.0))
    algo.cleanup()
    # CartPole starts ~20; PPO should clearly improve within ~20k steps
    assert max(returns[-4:]) > 60, f"PPO failed to learn: {returns}"


def test_ppo_remote_env_runners(rt_rl):
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=64)
              .training(minibatch_size=64, num_epochs=2)
              .debugging(seed=0))
    algo = config.build()
    result = algo.train()
    # autoreset reset-step rows are dropped, so <= T*N but close to it
    assert 64 * 2 * 2 * 0.8 < result["num_env_steps_sampled"] <= 64 * 2 * 2
    assert "policy_loss" in result
    algo.cleanup()


def test_impala_single_step(rt_rl):
    from ray_tpu.rllib import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .debugging(seed=0))
    algo = config.build()
    result = algo.train()
    assert "policy_loss" in result
    # autoreset reset-step rows are dropped, so <= T*N but close to it
    assert 64 * 0.8 < result["num_env_steps_sampled"] <= 64
    algo.cleanup()


def test_algorithm_checkpoint_roundtrip(rt_rl, tmp_path):
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(rollout_fragment_length=32)
              .training(minibatch_size=32, num_epochs=1))
    algo = config.build()
    algo.train()
    data = algo.save_checkpoint(str(tmp_path))
    w0 = algo.learner_group.get_weights()

    algo2 = config.copy().build()
    algo2.load_checkpoint(data, str(tmp_path))
    w1 = algo2.learner_group.get_weights()
    import jax

    for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(w1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo.cleanup()
    algo2.cleanup()


def test_env_runner_masks_autoreset_steps(rt_rl):
    """gymnasium NEXT_STEP autoreset: the step after term|trunc is a reset
    step (action ignored, reward 0) — it must be flagged invalid, including
    across sample() fragment boundaries (ADVICE r1)."""
    from ray_tpu.rllib import SingleAgentEnvRunner

    runner = SingleAgentEnvRunner("CartPole-v1", num_envs=2, seed=0)
    b1 = runner.sample(num_steps=60)
    b2 = runner.sample(num_steps=5)
    runner.stop()

    finished = np.logical_or(b1["terminateds"], b1["truncateds"])
    assert finished.any(), "CartPole should finish episodes within 60 steps"
    # within a fragment: valid[t+1] == ~finished[t]
    assert (b1["valid"][1:] == ~finished[:-1]).all()
    assert b1["valid"][0].all()  # first-ever steps are valid
    # across the boundary: first step of the next fragment
    assert (b2["valid"][0] == ~finished[-1]).all()
    # reset steps carry zero reward (what the env actually returned)
    assert (b1["rewards"][~b1["valid"]] == 0.0).all()


def test_ppo_postprocess_drops_invalid_rows(rt_rl):
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
            .training(minibatch_size=32)).build()
    batches = algo._sample(60)
    n_valid = int(sum(b["valid"].sum() for b in batches))
    n_total = int(sum(b["valid"].size for b in batches))
    train_batch = algo._postprocess(batches)
    assert len(train_batch["obs"]) == n_valid < n_total
    algo.cleanup()


def test_learner_mesh_sharded_matches_single_device(rt_rl):
    """A dp-mesh-sharded learner (8 virtual CPU devices) must produce
    numerically identical updates to a single-device learner on the same
    batch — XLA's in-jit grad psum IS the gradient sync (VERDICT r1 #4)."""
    import jax

    from ray_tpu.rllib.ppo import PPOLearner

    spec = {"observation_dim": 4, "action_dim": 2, "discrete": True}
    rng = np.random.default_rng(0)
    n = 64  # divisible by 8 devices
    batch = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, n),
        "action_logp": np.full(n, -0.69, np.float32),
        "vf_preds": rng.standard_normal(n).astype(np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "value_targets": rng.standard_normal(n).astype(np.float32),
    }
    multi = PPOLearner(spec, {"num_devices": jax.device_count()}, seed=0)
    single = PPOLearner(spec, {"num_devices": 1}, seed=0)
    assert multi.mesh.devices.size == 8
    m_multi = multi.update(batch, minibatch_size=32, num_epochs=2)
    m_single = single.update(batch, minibatch_size=32, num_epochs=2)
    w_multi, w_single = multi.get_weights(), single.get_weights()
    for a, b in zip(jax.tree.leaves(w_multi), jax.tree.leaves(w_single)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
    assert abs(m_multi["total_loss"] - m_single["total_loss"]) < 1e-4


def test_learner_padding_unbiased(rt_rl):
    """A ragged batch padded to the mesh size must yield the SAME loss and
    gradients as the unpadded batch on one device: padded rows carry zero
    loss weight via ``loss_mask`` (VERDICT r2 weak #7 — the old repeat
    padding biased minibatch statistics O(pad/batch))."""
    import jax

    from ray_tpu.rllib.ppo import PPOLearner

    spec = {"observation_dim": 4, "action_dim": 2, "discrete": True}
    rng = np.random.default_rng(1)
    n = 13  # ragged: pads to 16 on the 8-device mesh
    batch = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, n),
        "action_logp": np.full(n, -0.69, np.float32),
        "vf_preds": rng.standard_normal(n).astype(np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "value_targets": rng.standard_normal(n).astype(np.float32),
    }
    multi = PPOLearner(spec, {"num_devices": jax.device_count()}, seed=0)
    single = PPOLearner(spec, {"num_devices": 1}, seed=0)
    g_multi, m_multi = multi.compute_grads(batch)
    g_single, m_single = single.compute_grads(batch)
    assert abs(m_multi["total_loss"] - m_single["total_loss"]) < 1e-6
    for a, b in zip(jax.tree.leaves(g_multi), jax.tree.leaves(g_single)):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)


def test_learner_group_grad_sync_matches_local(rt_rl):
    """Two learner ACTORS with per-step gradient averaging must track a
    single local learner on the full batch (reference DDP semantics; the
    r1 weight-averaging scheme diverged)."""
    import jax

    from ray_tpu.rllib.learner import LearnerGroup
    from ray_tpu.rllib.ppo import PPOLearner

    spec = {"observation_dim": 4, "action_dim": 2, "discrete": True}
    cfg = {"num_devices": 1}
    rng = np.random.default_rng(1)
    n = 64
    batch = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, n),
        "action_logp": np.full(n, -0.69, np.float32),
        "vf_preds": rng.standard_normal(n).astype(np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "value_targets": rng.standard_normal(n).astype(np.float32),
    }
    group = LearnerGroup(PPOLearner, spec, cfg, num_learners=2, seed=0)
    local = PPOLearner(spec, cfg, seed=0)
    group.update(batch, minibatch_size=32, num_epochs=1)
    local.update(batch, minibatch_size=32, num_epochs=1)
    wg, wl = group.get_weights(), local.get_weights()
    for a, b in zip(jax.tree.leaves(wg), jax.tree.leaves(wl)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_impala_aggregation_tree(rt_rl):
    """num_aggregation_workers > 0: the v-trace postprocess runs on
    aggregator actors (reference impala.py:676-696 tree), same training
    result surface."""
    from ray_tpu.rllib import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .debugging(seed=0))
    config.num_aggregation_workers = 2
    algo = config.build()
    assert len(algo._aggregators) == 2
    r1 = algo.train()
    r2 = algo.train()
    assert "policy_loss" in r2 and np.isfinite(r2["policy_loss"])
    assert r2["num_env_steps_sampled"] > 0
    algo.cleanup()


# ---------------------------------------------------------------------------
# Model catalog (reference rllib/core/models/catalog.py role)
# ---------------------------------------------------------------------------

def test_catalog_picks_mlp_for_vector_obs():
    import gymnasium as gym

    from ray_tpu.rllib import Catalog, MLPEncoderConfig

    cat = Catalog.from_spaces(
        gym.spaces.Box(-1, 1, (7,), np.float32), gym.spaces.Discrete(3))
    assert isinstance(cat.encoder, MLPEncoderConfig)
    spec = cat.to_module_spec()
    assert spec.observation_dim == 7 and spec.action_dim == 3
    assert spec.conv_filters is None


def test_catalog_picks_cnn_for_image_obs_and_module_runs():
    import gymnasium as gym
    import jax

    from ray_tpu.rllib import ATARI_FILTERS, Catalog, CNNEncoderConfig

    cat = Catalog.from_spaces(
        gym.spaces.Box(0, 255, (32, 32, 3), np.uint8), gym.spaces.Discrete(4))
    assert isinstance(cat.encoder, CNNEncoderConfig)
    spec = cat.to_module_spec()
    assert spec.conv_filters == ATARI_FILTERS
    module = spec.build()
    params = module.init(jax.random.PRNGKey(0))
    assert "enc" in params
    obs = np.random.default_rng(0).random((2, 32 * 32 * 3), np.float32)
    out = module.forward_train(params, obs)
    assert out["action_dist_inputs"].shape == (2, 4)
    assert out["vf_preds"].shape == (2,)
    # spec survives the dict round-trip used across actor boundaries
    from dataclasses import asdict

    from ray_tpu.rllib import RLModuleSpec

    spec2 = RLModuleSpec(**{k: (tuple(tuple(x) if isinstance(x, (list, tuple))
                                      else x for x in v)
                                if isinstance(v, (list, tuple)) else v)
                            for k, v in asdict(spec).items()})
    out2 = spec2.build().forward_train(params, obs)
    assert np.allclose(np.asarray(out2["vf_preds"]),
                       np.asarray(out["vf_preds"]))


def test_lstm_encoder_scan_carry():
    import jax

    from ray_tpu.rllib import LSTMEncoderConfig

    enc = LSTMEncoderConfig(input_dim=5, cell_size=8)
    params = enc.init(jax.random.PRNGKey(1))
    x = np.random.default_rng(1).random((3, 6, 5), np.float32)
    feats, carry = jax.jit(enc.apply)(params, x)
    assert feats.shape == (3, 6, 8)
    # feeding the carry forward continues the sequence: running the two
    # halves with carry equals running the whole sequence at once
    f1, c1 = enc.apply(params, x[:, :3])
    f2, _ = enc.apply(params, x[:, 3:], c1)
    assert np.allclose(np.asarray(feats[:, 3:]), np.asarray(f2), atol=1e-5)


def test_learner_group_int8_grad_compression(rt_rl):
    """grad_compression="int8" ships quantized grads through the object
    store; training must still converge and match the uncompressed group's
    trajectory within quantization error."""
    import numpy as np

    from ray_tpu.rllib.learner import (dequantize_grads, quantize_grads)
    from ray_tpu.rllib.ppo import PPOLearner
    from ray_tpu.rllib.learner import LearnerGroup

    # round-trip: exact for representable values, bounded error otherwise
    tree = {"w": np.linspace(-1, 1, 300, dtype=np.float32).reshape(30, 10),
            "b": np.zeros(7, np.float32)}
    rt = dequantize_grads(quantize_grads(tree))
    assert rt["b"].shape == (7,)
    np.testing.assert_allclose(rt["w"], tree["w"], atol=1.0 / 127 + 1e-6)

    spec = {"observation_dim": 6, "action_dim": 3, "discrete": True,
            "hidden": (16,)}
    rng = np.random.default_rng(0)
    n = 64
    batch = {
        "obs": rng.standard_normal((n, 6)).astype(np.float32),
        "actions": rng.integers(0, 3, n),
        "action_logp": np.full(n, -1.1, np.float32),
        "vf_preds": np.zeros(n, np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "value_targets": np.zeros(n, np.float32),
    }
    group = LearnerGroup(PPOLearner, spec,
                         {"num_devices": 1, "grad_compression": "int8"},
                         num_learners=2, seed=0)
    m1 = group.update(batch, minibatch_size=32, num_epochs=1)
    m2 = group.update(batch, minibatch_size=32, num_epochs=1)
    assert np.isfinite(m1["policy_loss"]) and np.isfinite(m2["policy_loss"])
    # learners stayed in sync (same weights) despite the compressed hop
    import ray_tpu

    w0, w1 = ray_tpu.get([l.get_weights.remote() for l in group._learners])
    import jax

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), w0, w1)
