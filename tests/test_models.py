"""Model family: shapes, loss, decode==forward consistency, sharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu import models
from ray_tpu.models import (
    TransformerConfig, init_params, param_axes, forward, loss_and_metrics,
    init_cache, decode_step, generate,
)
from ray_tpu.parallel import MeshConfig, make_mesh, shard_params


CONFIGS = {
    "llama": models.llama_debug(),
    "gpt2": models.gpt2_debug(),
    "gemma": models.gemma_debug(),
    "qwen2": models.qwen2_debug(),
    "moe": models.moe_debug(),
}


@pytest.mark.parametrize("name", list(CONFIGS))
def test_forward_shapes(name):
    c = CONFIGS[name]
    params = init_params(jax.random.PRNGKey(0), c)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, c.vocab_size)
    logits, aux = forward(params, toks, c)
    assert logits.shape == (2, 16, c.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("name", list(CONFIGS))
def test_param_axes_match_params(name):
    c = CONFIGS[name]
    params = init_params(jax.random.PRNGKey(0), c)
    axes = param_axes(c)
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    # Every axes tuple must have one entry per array dim.
    def check(p, a):
        assert len(a) == p.ndim, f"{a} vs {p.shape}"
    jax.tree.map(check, params, axes,
                 is_leaf=lambda x: isinstance(x, tuple) and all(
                     e is None or isinstance(e, str) for e in x))


@pytest.mark.parametrize("name", list(CONFIGS))
def test_num_params_formula_matches(name):
    c = CONFIGS[name]
    params = init_params(jax.random.PRNGKey(0), c)
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == c.num_params()


def test_loss_decreases_under_sgd():
    c = models.llama_debug()
    params = init_params(jax.random.PRNGKey(0), c)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, c.vocab_size)
    batch = {"tokens": toks}

    @jax.jit
    def step(params):
        (loss, m), grads = jax.value_and_grad(
            lambda p: loss_and_metrics(p, batch, c), has_aux=True)(params)
        params = jax.tree.map(lambda p, g: p - 0.1 * g.astype(p.dtype),
                              params, grads)
        return params, loss

    params, l0 = step(params)
    for _ in range(5):
        params, loss = step(params)
    assert float(loss) < float(l0)


def test_decode_matches_forward():
    c = models.llama_debug()
    params = init_params(jax.random.PRNGKey(0), c)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, c.vocab_size)
    full, _ = forward(params, toks, c)

    # prefill 8, then decode 4 one at a time
    cache = init_cache(c, 2, 16)
    lp, cache = decode_step(params, cache, toks[:, :8], c)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(full[:, :8]),
                               atol=2e-2, rtol=2e-2)
    outs = [lp[:, -1:]]
    for i in range(8, 12):
        li, cache = decode_step(params, cache, toks[:, i:i + 1], c)
        outs.append(li)
    dec = jnp.concatenate(outs[1:], axis=1)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full[:, 8:12]),
                               atol=2e-2, rtol=2e-2)


def test_generate_greedy_deterministic():
    c = models.gpt2_debug()
    params = init_params(jax.random.PRNGKey(0), c)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, c.vocab_size)
    out1 = generate(params, prompt, c, max_new_tokens=6)
    out2 = generate(params, prompt, c, max_new_tokens=6)
    assert out1.shape == (1, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :4]), np.asarray(prompt))


def test_sharded_train_step_tp_fsdp():
    """Full train step jitted over a dp×fsdp×tp mesh with sharded params."""
    c = models.llama_debug()
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
    params = init_params(jax.random.PRNGKey(0), c)
    axes = param_axes(c)
    params = shard_params(params, axes, mesh)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, c.vocab_size)
    batch = {"tokens": toks}

    with jax.set_mesh(mesh):
        @jax.jit
        def step(params):
            (loss, m), grads = jax.value_and_grad(
                lambda p: loss_and_metrics(p, batch, c), has_aux=True)(params)
            return jax.tree.map(
                lambda p, g: p - 0.1 * g.astype(p.dtype), params, grads), loss

        new_params, loss = step(params)
    assert np.isfinite(float(loss))
    # Param shardings preserved through the step (trailing-None spec forms
    # compare unequal, so check equivalence).
    wq_new, wq_old = new_params["layers"]["wq"], params["layers"]["wq"]
    assert wq_new.sharding.is_equivalent_to(wq_old.sharding, wq_old.ndim)


def test_sharded_train_step_ring_attention_sp():
    """sp>1 routes attention through ring attention inside the jitted step."""
    c = models.llama_debug()
    mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
    params = init_params(jax.random.PRNGKey(0), c)
    params_sharded = shard_params(params, param_axes(c), mesh)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, c.vocab_size)
    # Explicit inputs/targets keep the model seq len at 64 (divisible by sp).
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    ref_loss, _ = loss_and_metrics(params, batch, c)  # no mesh: flash path

    with jax.set_mesh(mesh):
        @jax.jit
        def step(params):
            loss, m = loss_and_metrics(params, batch, c)
            return loss

        sp_loss = step(params_sharded)
    np.testing.assert_allclose(float(sp_loss), float(ref_loss), atol=2e-2, rtol=2e-2)


def test_remat_policies_grad_equivalent():
    """save_attn remat must produce the same loss AND grads as full remat
    (it only changes what backward recomputes); unknown policies fail loudly."""
    base = models.llama_debug()
    toks = np.asarray(
        np.random.default_rng(0).integers(0, base.vocab_size, (2, 33)),
        dtype=np.int32)
    batch = {"tokens": toks}

    def grads_for(policy):
        c = base.replace(remat=True, remat_policy=policy)
        params = init_params(jax.random.PRNGKey(0), c)
        return jax.jit(jax.value_and_grad(
            lambda p: loss_and_metrics(p, batch, c)[0]))(params)

    loss_full, g_full = grads_for("full")
    loss_attn, g_attn = grads_for("save_attn")
    np.testing.assert_allclose(float(loss_full), float(loss_attn), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
        g_full, g_attn)

    with pytest.raises(ValueError, match="remat_policy"):
        base.replace(remat_policy="save-attention")


def test_chunked_xent_matches_dense():
    """loss_chunk must not change the loss (exact) or grads (beyond bf16
    accumulation-order noise) — it only changes what backward keeps live."""
    base = models.llama_debug().replace(z_loss=1e-4, logits_softcap=30.0)
    toks = np.asarray(np.random.default_rng(0).integers(
        0, base.vocab_size, (2, 65)), dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    def loss_grads(cfg):
        params = init_params(jax.random.PRNGKey(0), cfg)
        return jax.jit(jax.value_and_grad(
            lambda p: loss_and_metrics(p, batch, cfg)[0]))(params)

    l_dense, g_dense = loss_grads(base)
    l_chunk, g_chunk = loss_grads(base.replace(loss_chunk=16))
    np.testing.assert_allclose(float(l_dense), float(l_chunk), rtol=1e-5)

    def close(a, b):
        a, b = np.asarray(a, "float32"), np.asarray(b, "float32")
        denom = max(1e-3, float(abs(b).max()))
        assert abs(a - b).max() / denom < 5e-3

    jax.tree.map(close, g_dense, g_chunk)


def test_chunked_xent_pads_non_divisible_seq():
    """L not divisible by loss_chunk pads with mask-0 — never a silent
    dense fallback."""
    base = models.llama_debug()
    toks = np.asarray(np.random.default_rng(1).integers(
        0, base.vocab_size, (2, 65)), dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
    params = init_params(jax.random.PRNGKey(0), base)
    l_dense = float(loss_and_metrics(params, batch, base)[0])
    l_pad = float(loss_and_metrics(
        params, batch, base.replace(loss_chunk=24))[0])
    np.testing.assert_allclose(l_dense, l_pad, rtol=1e-5)


def test_mistral_sliding_window_trains_and_decodes():
    """sliding_window threads through train (blockwise VJP path) and the
    KV-cache decode: decode logits must match the full-sequence forward."""
    c = models.mistral_debug()
    assert c.sliding_window == 24
    params = init_params(jax.random.PRNGKey(0), c)
    toks = np.asarray(np.random.default_rng(0).integers(
        0, c.vocab_size, (2, 65)), dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: loss_and_metrics(p, batch, c)[0]))(params)
    assert np.isfinite(float(loss))
    assert np.isfinite(float(optax_global_norm(grads)))

    # decode parity: windowed prefill+decode equals windowed full forward.
    # The cache is auto-RING (24 slots for window 24), so the 48-token
    # prompt prefills in two window-sized chunks.
    from ray_tpu.models.transformer import decode_step, forward, init_cache

    prompt = toks[:1, :48]
    logits_full, _ = forward(params, prompt, c)
    cache = init_cache(c, 1, 64)
    assert cache["k"].shape[2] == c.sliding_window
    logits_dec = None
    for i in range(0, 48, 24):
        logits_dec, cache = decode_step(params, cache, prompt[:, i:i + 24], c)
    np.testing.assert_allclose(
        np.asarray(logits_dec[:, -1], np.float32),
        np.asarray(logits_full[:, -1], np.float32), atol=2e-2, rtol=2e-2)


def optax_global_norm(tree):
    import optax

    return optax.global_norm(tree)


def test_rolling_kv_cache_matches_full_cache():
    """Sliding-window ring cache (O(window) HBM) must produce the same
    logits as the full-length cache at every decode step, including far
    past the window."""
    from ray_tpu.models.transformer import decode_step, init_cache

    c = models.mistral_debug()  # window 24
    params = init_params(jax.random.PRNGKey(0), c)
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        0, c.vocab_size, (2, 16)), jnp.int32)

    full = init_cache(c, 2, 64, rolling=False)
    ring = init_cache(c, 2, 64)
    assert full["k"].shape[2] == 64 and ring["k"].shape[2] == 24

    lf, full = decode_step(params, full, prompt, c)
    lr, ring = decode_step(params, ring, prompt, c)
    np.testing.assert_allclose(np.asarray(lf, np.float32),
                               np.asarray(lr, np.float32),
                               atol=1e-3, rtol=1e-2)
    step_full = jax.jit(lambda cc, t: decode_step(params, cc, t, c))
    step_ring = jax.jit(lambda cc, t: decode_step(params, cc, t, c))
    tok = jnp.argmax(lf[:, -1], -1).astype(jnp.int32)[:, None]
    for i in range(40):
        lf, full = step_full(full, tok)
        lr, ring = step_ring(ring, tok)
        np.testing.assert_allclose(np.asarray(lf, np.float32),
                                   np.asarray(lr, np.float32),
                                   atol=1e-3, rtol=1e-2, err_msg=f"step {i}")
        tok = jnp.argmax(lf[:, -1], -1).astype(jnp.int32)[:, None]

    # a prefill chunk larger than the ring is rejected loudly
    import pytest as _pytest

    big = jnp.zeros((2, 30), jnp.int32)
    with _pytest.raises(ValueError, match="ring cache"):
        decode_step(params, init_cache(c, 2, 64), big, c)


def test_generate_ring_prefill_long_prompt():
    """generate() keeps the O(window) ring even for prompts beyond the
    window (chunked prefill) and matches full-cache greedy decoding."""
    from ray_tpu.models.transformer import decode_step, generate, init_cache

    c = models.mistral_debug()  # window 24
    params = init_params(jax.random.PRNGKey(0), c)
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        0, c.vocab_size, (1, 40)), jnp.int32)
    out_ring = generate(params, prompt, c, max_new_tokens=6)

    cache = init_cache(c, 1, 64, rolling=False)
    logits, cache = decode_step(params, cache, prompt, c)
    toks = [int(jnp.argmax(logits[0, -1], -1))]
    for _ in range(5):
        nxt = jnp.asarray([[toks[-1]]], jnp.int32)
        logits, cache = decode_step(params, cache, nxt, c)
        toks.append(int(jnp.argmax(logits[0, -1], -1)))
    assert list(np.asarray(out_ring)[0, 40:]) == toks


def test_mistral_sp_halo_train_step():
    """Windowed model under an sp mesh routes through the halo-exchange
    path and matches the single-device loss."""
    c = models.mistral_debug()  # window 24
    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1, tp=2, sp=2))
    params = init_params(jax.random.PRNGKey(0), c)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0,
                              c.vocab_size)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}  # seq 64, Lloc 32
    ref_loss, _ = loss_and_metrics(params, batch, c)

    params_sharded = shard_params(params, param_axes(c), mesh)
    with jax.set_mesh(mesh):
        sp_loss = jax.jit(
            lambda p: loss_and_metrics(p, batch, c)[0])(params_sharded)
    np.testing.assert_allclose(float(sp_loss), float(ref_loss), atol=2e-2,
                               rtol=2e-2)

    # window > Lloc: the multi-hop halo (r5) handles it exactly
    big = c.replace(sliding_window=48)  # Lloc 32 < 48 -> 2 hops
    ref_big, _ = loss_and_metrics(params, batch, big)
    with jax.set_mesh(mesh):
        sp_big = jax.jit(
            lambda p: loss_and_metrics(p, batch, big)[0])(params_sharded)
    np.testing.assert_allclose(float(sp_big), float(ref_big), atol=2e-2,
                               rtol=2e-2)


def test_gemma2_alternating_windows_exact():
    """Per-layer alternating windows (Gemma-2 layer_types): the grouped
    layer scan must equal a hand-rolled per-layer naive-attention forward
    with each layer's own window AND the attention softcap."""
    import numpy as np

    from ray_tpu import models
    from ray_tpu.models import transformer as T
    from ray_tpu.ops.attention import naive_attention

    cfg = models.gemma_debug()
    assert cfg.window_pattern == (24, 0)
    assert cfg.uniform_window == 0      # mixed -> no ring cache
    assert cfg.layer_windows == (24, 0)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 64), np.int32))

    def ref_forward(params, tokens, c):
        dt = jnp.dtype(c.dtype)
        x = params["embed"].astype(dt)[tokens]
        cos, sin = T.rotary_embedding(jnp.arange(tokens.shape[1]), c.hdim,
                                      theta=c.rope_theta)
        for li in range(c.n_layers):
            lp = jax.tree.map(lambda a: a[li], params["layers"])
            h = T._norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c)
            q = jnp.einsum("bld,dhk->blhk", h, lp["wq"].astype(dt))
            k = jnp.einsum("bld,dhk->blhk", h, lp["wk"].astype(dt))
            v = jnp.einsum("bld,dhk->blhk", h, lp["wv"].astype(dt))
            q = T.apply_rotary(q, cos, sin)
            k = T.apply_rotary(k, cos, sin)
            o = naive_attention(q, k, v, causal=True,
                                window=c.layer_windows[li] or None,
                                softcap=c.attn_softcap)
            x = x + jnp.einsum("blhk,hkd->bld", o, lp["wo"].astype(dt))
            h = T._norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c)
            g = jax.nn.silu(jnp.einsum("bld,df->blf", h,
                                       lp["w_gate"].astype(dt)))
            u = jnp.einsum("bld,df->blf", h, lp["w_up"].astype(dt))
            x = x + jnp.einsum("blf,fd->bld", g * u,
                               lp["w_down"].astype(dt))
        x = T._norm(x, params["final_norm"], params.get("final_norm_b"),
                    c)
        logits = jnp.einsum("bld,dv->blv", x,
                            params["embed"].T.astype(dt)).astype(jnp.float32)
        return jnp.tanh(logits / c.logits_softcap) * c.logits_softcap

    got, _ = T.forward(params, toks, cfg)
    want = ref_forward(params, toks, cfg)
    assert float(jnp.abs(got - want).max()) < 2e-2  # bf16 activations

    # the alternation is load-bearing: a uniform-window twin differs
    uni, _ = T.forward(params, toks, cfg.replace(attn_windows=(24, 24)))
    assert float(jnp.abs(got - uni).max()) > 1e-3


def test_gemma2_decode_matches_forward():
    """Mixed-window decode (full cache + per-layer traced windows) must
    reproduce the training forward position by position."""
    import numpy as np

    from ray_tpu import models
    from ray_tpu.models import transformer as T

    cfg = models.gemma_debug()
    params = models.init_params(jax.random.PRNGKey(1), cfg)
    toks = jnp.asarray(np.random.default_rng(1).integers(
        0, cfg.vocab_size, (2, 56), np.int32))
    full, _ = T.forward(params, toks, cfg)
    cache = T.init_cache(cfg, 2, 56)
    assert cache["k"].shape[2] == 56  # mixed windows force full layout
    logits, cache = T.decode_step(params, cache, toks[:, :40], cfg)
    assert float(jnp.abs(logits - full[:, :40]).max()) < 2e-2
    for i in range(40, 44):
        lg, cache = T.decode_step(params, cache, toks[:, i:i + 1], cfg)
        assert float(jnp.abs(lg[:, 0] - full[:, i]).max()) < 2e-2


def test_attn_windows_config_validation():
    import pytest

    from ray_tpu import models

    with pytest.raises(ValueError, match="not divisible"):
        models.gemma_debug().replace(attn_windows=(24, 0, 0))
    with pytest.raises(ValueError, match="ints >= 0"):
        models.gemma_debug().replace(attn_windows=(24, -1))
    with pytest.raises(NotImplementedError, match="pipeline"):
        # per-layer windows + pp>1 is an explicit design limit
        import numpy as np

        from ray_tpu.models import transformer as T
        from ray_tpu.parallel import MeshConfig, make_mesh

        cfg = models.gemma_debug()
        params = models.init_params(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1, pp=8))
        toks = jnp.zeros((2, 32), jnp.int32)
        with jax.set_mesh(mesh):
            T.forward(params, toks, cfg)


def test_decode_step_multi_matches_scalar_decode():
    """Per-sample-position batched decode (the continuous-batching inner
    step) must be token-exact vs per-sequence scalar decode_step, incl.
    staggered prompt lengths, parked-slot masks, and the gemma-2
    alternating-window + softcap config."""
    import numpy as np

    from ray_tpu import models
    from ray_tpu.models import transformer as T

    for name in ("llama-debug", "gemma-debug"):
        cfg = models.get_config(name)
        params = models.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        n_seq, cache_len = 3, 48
        prompts = [rng.integers(0, cfg.vocab_size, (1, p)).astype(np.int32)
                   for p in (5, 9, 13)]
        refs = []
        for pr in prompts:
            c1 = T.init_cache(cfg, 1, cache_len, rolling=False)
            lg, c1 = T.decode_step(params, c1, jnp.asarray(pr), cfg)
            toks = [int(jnp.argmax(lg[0, -1]))]
            for _ in range(5):
                lg, c1 = T.decode_step(
                    params, c1, jnp.asarray([[toks[-1]]], dtype=jnp.int32),
                    cfg)
                toks.append(int(jnp.argmax(lg[0, -1])))
            refs.append(toks)

        dt = jnp.dtype(cfg.dtype)
        shape = (cfg.n_layers, n_seq, cache_len, cfg.kv_heads, cfg.hdim)
        cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
                 "pos": jnp.zeros((n_seq,), jnp.int32)}
        outs = [[] for _ in range(n_seq)]
        last_logits = [None] * n_seq
        maxp = max(p.shape[1] for p in prompts)
        for t in range(maxp):
            toks = np.zeros((n_seq, 1), np.int32)
            act = np.zeros(n_seq, bool)
            for i, pr in enumerate(prompts):
                if t < pr.shape[1]:
                    toks[i, 0] = pr[0, t]
                    act[i] = True
            lg, cache = T.decode_step_multi(params, cache,
                                            jnp.asarray(toks), cfg,
                                            jnp.asarray(act))
            for i, pr in enumerate(prompts):
                if t == pr.shape[1] - 1:
                    last_logits[i] = np.asarray(lg[i])
        cur = np.array([int(np.argmax(last_logits[i]))
                        for i in range(n_seq)], np.int32)
        for i in range(n_seq):
            outs[i].append(int(cur[i]))
        for _ in range(5):
            lg, cache = T.decode_step_multi(params, cache,
                                            jnp.asarray(cur[:, None]), cfg)
            cur = np.asarray(jnp.argmax(lg, axis=-1)).astype(np.int32)
            for i in range(n_seq):
                outs[i].append(int(cur[i]))
        assert outs == refs, (name, outs, refs)


def test_hf_llama_import_logits_parity():
    """import_hf_llama: logits must match transformers' LlamaForCausalLM
    exactly (same f32 math, same RoPE convention, same GQA mapping) on a
    randomly initialized tiny model."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from ray_tpu.models import forward
    from ray_tpu.models.import_hf import config_from_hf, import_hf_llama

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()

    cfg = config_from_hf(hf_cfg)
    params = import_hf_llama(hf.state_dict(), cfg)

    tokens = np.asarray([[3, 17, 99, 5, 64, 2, 120, 7]], np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens).long()).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), cfg)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4,
                               rtol=2e-3)


def test_hf_llama_import_generate_parity():
    """Greedy decode with imported weights must produce the same token
    ids as transformers' generate — proves the KV-cache decode path on
    real(istic) weights, not just the teacher-forced forward."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from ray_tpu.models import generate
    from ray_tpu.models.import_hf import config_from_hf, import_hf_llama

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=True)
    torch.manual_seed(1)
    hf = LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    params = import_hf_llama(hf.state_dict(), cfg)

    prompt = np.asarray([[5, 99, 23, 42]], np.int32)
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(prompt).long(),
                          max_new_tokens=8, do_sample=False,
                          eos_token_id=None).numpy()
    ours = np.asarray(generate(params, jnp.asarray(prompt), cfg,
                               max_new_tokens=8))
    np.testing.assert_array_equal(ours, ref)


def test_hf_import_rejects_unmapped_tensors_and_rope_scaling():
    """Strictness: unconsumed state-dict tensors (a bias the mapping
    does not model, standing in for Qwen3 q/k norms etc.) and
    rope_scaling configs must fail loudly, never import silently
    wrong."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from ray_tpu.models.import_hf import config_from_hf, import_hf_llama

    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2,
        num_key_value_heads=2, max_position_embeddings=32,
        rms_norm_eps=1e-5)
    hf = LlamaForCausalLM(hf_cfg)
    cfg = config_from_hf(hf_cfg)
    assert cfg.norm_eps == 1e-5

    sd = dict(hf.state_dict())
    sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(32)
    with pytest.raises(ValueError, match="does not consume"):
        import_hf_llama(sd, cfg)

    hf_cfg.rope_scaling = {"rope_type": "llama3", "factor": 8.0}
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_hf_qwen2_import_logits_parity():
    """Qwen2 (q/k/v biases) imports with exact logits parity — the
    attn_qkv_bias path end to end."""
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    from ray_tpu.models import forward
    from ray_tpu.models.import_hf import config_from_hf, import_hf_llama

    hf_cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=False,
        use_sliding_window=False)
    torch.manual_seed(2)
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    # random biases (zeros would not exercise the path)
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0, 0.5)

    cfg = config_from_hf(hf_cfg)
    assert cfg.attn_qkv_bias
    params = import_hf_llama(hf.state_dict(), cfg)

    tokens = np.asarray([[3, 17, 99, 5, 64, 2, 120, 7]], np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens).long()).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), cfg)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4,
                               rtol=2e-3)


def test_hf_qwen2_swa_layer_mapping():
    """Qwen2 use_sliding_window: HF runs FULL attention on the first
    max_window_layers layers and SWA after — config_from_hf must map
    that to an explicit per-layer attn_windows tuple, and ignore
    sliding_window entirely when use_sliding_window is off."""
    from transformers import Qwen2Config

    from ray_tpu.models.import_hf import config_from_hf

    cfg = config_from_hf(Qwen2Config(
        num_hidden_layers=4, sliding_window=1024,
        use_sliding_window=True, max_window_layers=2))
    assert cfg.attn_windows == (0, 0, 1024, 1024)
    assert cfg.sliding_window == 0

    cfg = config_from_hf(Qwen2Config(
        num_hidden_layers=4, sliding_window=1024,
        use_sliding_window=False))
    assert cfg.attn_windows is None and cfg.sliding_window == 0

    # explicit layer_types wins over the max_window_layers prefix rule,
    # and periodic patterns reduce to their minimal repeat
    hf = Qwen2Config(num_hidden_layers=4, sliding_window=1024,
                     use_sliding_window=True, max_window_layers=0)
    hf.layer_types = ["sliding_attention", "full_attention"] * 2
    cfg = config_from_hf(hf)
    assert cfg.attn_windows == (1024, 0)
    assert cfg.layer_windows == (1024, 0, 1024, 0)

    # all-sliding uniform pattern reduces to one entry
    hf.layer_types = ["sliding_attention"] * 4
    cfg = config_from_hf(hf)
    assert cfg.attn_windows == (1024,)
    assert cfg.uniform_window == 1024

    # unknown attention kinds and mis-sized lists refuse loudly
    hf.layer_types = ["chunked_attention"] * 4
    with pytest.raises(ValueError, match="layer_types"):
        config_from_hf(hf)
    hf.layer_types = ["sliding_attention", "full_attention"]  # 2 != 4
    with pytest.raises(ValueError, match="layer_types"):
        config_from_hf(hf)
