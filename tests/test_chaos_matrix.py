"""Chaos matrix (ISSUE 5 tentpole): real workloads driven through
deterministic failpoint injection, asserting correct results + recovery.

Every recovery mechanism the repo claims (task retries, actor restart,
lineage, node-death re-placement, GCS snapshot FT, Serve re-route, Data
exchange re-execution, Train checkpoint resume) keeps a failpoint armed
here as its regression test. Sites live in ``ray_tpu/util/failpoints.py``;
``RTPU_FAILPOINTS=0`` disables the whole plane.

Quick subset (tier-1, unmarked): worker kill mid-exec, store seal failure,
Serve replica death, compiled-DAG actor death. Everything else — including every multi-node case —
is ``slow``. Deadlines are generous (2-vCPU CI box, CLAUDE.md deflake
rules: retried transient-connection polls, no tight wall-clock asserts).
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import failpoints

from conftest import poll_until


@pytest.fixture
def chaos_rt(tmp_path):
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield tmp_path
    failpoints.disarm()
    ray_tpu.shutdown()


def _token(tmp_path, name):
    """Path for a cross-process at-most-once kill election (``once=``) —
    per-process ``times=`` would re-arm in every respawned worker."""
    return str(tmp_path / f"fp-{name}.tok")


def _events_named(name, **field_filters):
    """Head-visible lifecycle events matching ``name`` + field values
    (ISSUE 18: every chaos death case leaves exactly one death event
    with a correct cause class and a postmortem)."""
    from ray_tpu.util import state

    return [e for e in state.list_events(limit=100000)
            if e["name"] == name
            and all(e.get(k) == v for k, v in field_filters.items())]


# ---------------------------------------------------------------------------
# quick subset (tier-1): worker kill, seal failure, serve replica death,
# compiled-DAG actor death
# ---------------------------------------------------------------------------

def test_worker_kill_mid_exec_task_graph(chaos_rt):
    """SIGKILL a worker mid-task inside a lineage chain: the task re-runs
    on another worker (max_retries) and the dependent graph completes with
    the correct result."""
    failpoints.arm(
        f"worker.exec=kill@arg=square@once={_token(chaos_rt, 'kill1')}")

    @ray_tpu.remote(max_retries=2)
    def square(x):
        return x * x

    @ray_tpu.remote
    def total(*xs):
        return sum(xs)

    refs = [square.remote(i) for i in range(8)]
    assert ray_tpu.get(total.remote(*refs), timeout=120) == sum(
        i * i for i in range(8))

    # the kill left exactly ONE worker_death event (once= election),
    # with the right cause class and a postmortem from the reaping site
    deaths = poll_until(
        lambda: _events_named("worker_death", task="square"),
        timeout=30, desc="worker_death event for the killed square")
    assert len(deaths) == 1, deaths
    assert deaths[0]["cause"] == "signal:SIGKILL"
    assert deaths[0]["postmortem"]["cause"] == "signal:SIGKILL"


def test_store_seal_failure_retries_task(chaos_rt):
    """A failed object-store seal surfaces as the producing task's error;
    ``retry_exceptions`` resubmits it and the retry succeeds."""
    # once= (not times=1): the retry may land on a DIFFERENT worker whose
    # own per-process times budget would fire again and exhaust max_retries
    failpoints.arm(f"store.seal=raise@once={_token(chaos_rt, 'seal')}")

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def big():
        return np.arange(300_000, dtype=np.int64)  # too big to inline

    out = ray_tpu.get(big.remote(), timeout=120)
    assert out.shape == (300_000,) and int(out[-1]) == 299_999


def test_serve_replica_death_rerouted_and_replaced(chaos_rt):
    """Kill a Serve replica's worker mid-request under load: the handle
    re-routes the failed request to a live replica (no caller-visible
    error) and the controller reconciles a replacement replica."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x * 2

    try:
        handle = serve.run(Echo.bind())
        assert handle.remote(1).result() == 2
        failpoints.arm("worker.exec=kill@arg=handle_request"
                       f"@once={_token(chaos_rt, 'serve')}")
        results = [handle.remote(i).result() for i in range(20)]
        assert results == [2 * i for i in range(20)]

        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        deps = poll_until(
            lambda: ray_tpu.get(ctrl.list_deployments.remote()),
            timeout=30, desc="controller view")
        assert deps["Echo"]["num_replicas"] == 2  # dead one was replaced

        # the controller (an actor: events ride its worker pipe) emitted
        # the replica death + the re-route fanout as lifecycle events
        dead = poll_until(
            lambda: _events_named("serve_replica_death", deployment="Echo"),
            timeout=30, desc="serve_replica_death event")
        assert len(dead) == 1, dead
        assert poll_until(
            lambda: _events_named("serve_reroute", deployment="Echo"),
            timeout=30, desc="serve_reroute event")
    finally:
        serve.shutdown()


def test_compiled_dag_actor_death_mid_loop(chaos_rt):
    """Kill an actor participating in a compiled DAG mid-loop: the next
    get() surfaces DAGExecutionError promptly (loop-ref death detection,
    not a channel-read timeout), the broken DAG refuses new admissions,
    and teardown unlinks every shm channel."""
    import os as _os

    from ray_tpu.dag import DAGExecutionError, InputNode

    @ray_tpu.remote
    class St:
        def bump(self, x):
            return x + 1

    a, b = St.remote(), St.remote()
    with InputNode() as inp:
        dag = b.bump.bind(a.bump.bind(inp))
    compiled = dag.experimental_compile(max_in_flight=4)
    paths = [ch.path for ch in compiled._channels]
    try:
        assert compiled.execute(1).get(timeout=60) == 3
        ray_tpu.kill(a)
        # wait for the death to land in the directory (the loop ref
        # resolves to ActorDiedError) so the race where stage `a` still
        # processes the next input can't make the test flake
        poll_until(
            lambda: len(ray_tpu.wait(
                compiled._loop_refs,
                num_returns=len(compiled._loop_refs), timeout=0.1)[0]) >= 1,
            timeout=30, desc="dead actor's exec-loop ref resolved")
        fut = compiled.execute(2)
        t0 = time.monotonic()
        with pytest.raises(DAGExecutionError):
            fut.get(timeout=60)
        assert time.monotonic() - t0 < 30, "death surfaced via timeout, " \
            "not detection"
        with pytest.raises(DAGExecutionError):
            compiled.execute(3)   # broken pipeline refuses new work

        # ray_tpu.kill() exhausts restarts: exactly one terminal
        # actor_death event for `a`, cause = the kill signal
        deaths = poll_until(
            lambda: _events_named("actor_death",
                                  actor_id=a._actor_id.hex()),
            timeout=30, desc="actor_death event for the killed stage")
        assert len(deaths) == 1, deaths
        assert deaths[0]["cause"].startswith("signal:")
        assert deaths[0]["postmortem"]["cause"] == deaths[0]["cause"]
    finally:
        compiled.teardown()
    assert not any(_os.path.exists(p) for p in paths), \
        "teardown left shm channels linked"


# ---------------------------------------------------------------------------
# single-node slow cases
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_retry_exceptions_resubmission_guard(chaos_rt):
    """An application error on the Nth execution resubmits (bounded); the
    result is published exactly once — consumers never observe the error
    of a retried attempt, and exhausted retries DO surface."""
    # once=+times=2 makes the failure budget GLOBAL (exactly 2 failed
    # executions, wherever the resubmitted attempts land) — a per-process
    # times=2 would re-fire on every fresh worker the retry lands on
    failpoints.arm("worker.exec.before_result=raise@times=2@arg=flaky"
                   f"@once={_token(chaos_rt, 'flaky')}")

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        return "ok"

    assert ray_tpu.get(flaky.remote(), timeout=120) == "ok"

    failpoints.arm("worker.exec.before_result=raise@times=10@arg=doomed")

    @ray_tpu.remote(max_retries=1, retry_exceptions=True)
    def doomed():
        return "never"

    with pytest.raises(Exception):
        ray_tpu.get(doomed.remote(), timeout=120)

    # opting in WITHOUT max_retries must not be silently inert: the
    # reference default budget (3) applies
    failpoints.arm("worker.exec.before_result=raise@arg=bare"
                   f"@times=1@once={_token(chaos_rt, 'bare')}")

    @ray_tpu.remote(retry_exceptions=True)
    def bare():
        return "ok"

    assert ray_tpu.get(bare.remote(), timeout=120) == "ok"

    # reference list form: only the NAMED exception types retry
    failpoints.arm("worker.exec.before_result=raise:ValueError@arg=picky"
                   f"@times=1@once={_token(chaos_rt, 'picky')}")

    @ray_tpu.remote(max_retries=3, retry_exceptions=[ValueError])
    def picky():
        return "ok"

    assert ray_tpu.get(picky.remote(), timeout=120) == "ok"

    failpoints.arm("worker.exec.before_result=raise:ValueError@arg=strict"
                   f"@times=1@once={_token(chaos_rt, 'strict')}")

    @ray_tpu.remote(max_retries=3, retry_exceptions=[KeyError])
    def strict():
        return "never"

    from ray_tpu.core.exceptions import TaskError

    with pytest.raises(TaskError):  # ValueError not in the list: surfaces
        ray_tpu.get(strict.remote(), timeout=120)


@pytest.mark.slow
def test_actor_herd_survives_worker_kill(chaos_rt):
    """An actor herd keeps serving through one member's SIGKILL: the dead
    actor restarts (max_restarts) and every herd member answers after."""
    failpoints.arm(
        f"worker.exec=kill@arg=bump@once={_token(chaos_rt, 'herd')}")

    @ray_tpu.remote(max_restarts=-1)
    class Member:
        def bump(self, x):
            return x + 1

    herd = [Member.remote() for _ in range(4)]

    def herd_answers():
        try:
            return ray_tpu.get([m.bump.remote(41) for m in herd],
                               timeout=30) == [42] * 4
        except Exception:
            return False  # the killed member is mid-restart: retry

    assert poll_until(herd_answers, timeout=120, desc="herd answers")

    # the restart left an actor_restart lifecycle event (warning, not a
    # terminal actor_death — the member came back)
    restarts = poll_until(
        lambda: _events_named("actor_restart", cause="signal:SIGKILL"),
        timeout=30, desc="actor_restart event for the killed member")
    assert restarts[0]["severity"] == "warning"
    herd_ids = {m._actor_id.hex() for m in herd}
    assert not [e for e in _events_named("actor_death")
                if e.get("actor_id") in herd_ids]


@pytest.mark.slow
def test_delayed_and_dropped_control_pipe_messages(chaos_rt):
    """Delayed driver->worker control messages and dropped worker->driver
    telemetry pushes never affect correctness — results stay exact."""
    failpoints.arm("pipe.send=delay:0.02@times=10")
    failpoints.arm("worker.pipe.send=drop@arg=metrics@times=5")

    @ray_tpu.remote
    def mul(x):
        return x * 3

    assert ray_tpu.get([mul.remote(i) for i in range(30)],
                       timeout=120) == [3 * i for i in range(30)]


def test_pipe_send_failpoint_fires_on_native_path(chaos_rt):
    """r14 satellite: the driver->worker chaos filter sits BEFORE the
    native engine, so `pipe.send` keeps firing (and the workload keeps
    its exactness) with the GIL-free pipe armed. Asserts the engine is
    actually attached AND the failpoint actually fired — a silently
    skipped filter would pass the correctness check alone."""
    from ray_tpu.core.runtime import _get_runtime
    from ray_tpu.util.metrics import registry_records

    rt = _get_runtime()
    failpoints.arm("pipe.send=delay:0.01@times=8")

    @ray_tpu.remote
    def mul(x):
        return x * 7

    assert ray_tpu.get([mul.remote(i) for i in range(24)],
                       timeout=120) == [7 * i for i in range(24)]
    # checked AFTER the workload: prestarted workers attach their engine
    # on dial-back, so an at-init check would race the accept loop
    native = [ws for ws in rt.workers.values()
              if ws.status != "dead" and ws.npipe is not None]
    if not native:
        pytest.skip("native pipe engine not active (no .so / killed)")
    fired = 0.0
    for rec in registry_records():
        if rec["name"] == "rtpu_failpoints_fired_total":
            for key, v in rec["samples"]:
                if dict(key).get("site") == "pipe.send":
                    fired += v
    assert fired >= 8, f"pipe.send fired {fired} times on the native path"


@pytest.mark.slow
def test_data_shuffle_reducer_death_recovers(chaos_rt):
    """Kill a streaming-exchange reducer actor mid-ingest: the plan
    re-executes from lineage and the result is exact (sort order + row
    count), for both the sort and the combinable-groupby engines."""
    from ray_tpu import data as rdata

    failpoints.arm(
        f"worker.exec=kill@arg=add_block@once={_token(chaos_rt, 'red1')}")
    rows = rdata.range(2000).sort("id", descending=True).take_all()
    vals = [int(r["id"]) for r in rows]
    assert vals == sorted(range(2000), reverse=True)

    failpoints.arm(
        f"worker.exec=kill@arg=add_block@once={_token(chaos_rt, 'red2')}")
    out = (rdata.range(1000)
           .map(lambda r: {"k": r["id"] % 7, "v": r["id"]})
           .groupby("k").sum("v").take_all())
    expect = {}
    for i in range(1000):
        expect[i % 7] = expect.get(i % 7, 0) + i
    got = {int(r["k"]): int(r["sum(v)"]) for r in out}
    assert got == expect

    # both engine kills left death events, each dead reducer worker
    # exactly ONCE (no dupes, no losses), classified with forensics.
    # Count is >= 2, not == 2: aborting a half-done stage can tear down
    # sibling reducers that were still mid-add_block.
    deaths = poll_until(
        lambda: d if len(d := _events_named(
            "worker_death", task="add_block")) >= 2 else None,
        timeout=60, desc="reducer death events")
    assert len({ev["worker_id"] for ev in deaths}) == len(deaths), deaths
    assert [ev for ev in deaths if ev["cause"] == "signal:SIGKILL"]
    for ev in deaths:
        assert ev["cause"].startswith("signal:"), ev
        assert ev["postmortem"]["cause"] == ev["cause"]


@pytest.mark.slow
def test_trainer_worker_kill_resumes_from_checkpoint(chaos_rt):
    """SIGKILL a train worker mid-run (process death, not a user
    exception): the trainer restarts the gang and resumes from the latest
    checkpoint instead of step 0."""
    from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                               RunConfig, ScalingConfig)

    failpoints.arm("worker.exec=kill@arg=next_result@after=4"
                   f"@once={_token(chaos_rt, 'train')}")

    def loop(config):
        import pickle
        import tempfile

        import ray_tpu.train as train

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            rank_dir = os.path.join(ckpt.path, "rank_0")
            with open(os.path.join(rank_dir, "state.pkl"), "rb") as f:
                start = pickle.load(f)["step"] + 1
        for step in range(start, 6):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"step": step}, f)
            train.report({"step": step, "resumed_from": start},
                         checkpoint=Checkpoint(d))

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(chaos_rt / "train"),
            failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 5
    assert result.metrics["resumed_from"] > 0  # did NOT restart from 0

    # the resume left a checkpoint_resume lifecycle event (emitted by
    # the driver-side retry loop, so no pipe hop to wait for)
    resumes = _events_named("checkpoint_resume")
    assert resumes and resumes[0]["attempt"] >= 1
    assert resumes[0]["checkpoint"]


# ---------------------------------------------------------------------------
# multi-node slow cases
# ---------------------------------------------------------------------------

@pytest.fixture
def chaos_cluster(tmp_path):
    from ray_tpu.cluster import Cluster

    # deflaked default node_timeout (8s): under 2-vCPU contention a
    # healthy node routinely misses several 0.5s beats, and a false
    # node-death mid-test breaks placement asserts (CLAUDE.md)
    c = Cluster(gcs_snapshot=str(tmp_path / "gcs.snap"))
    yield c
    failpoints.disarm()
    ray_tpu.shutdown()
    c.shutdown()


def _cluster_init(c):
    return ray_tpu.init(address=c.address, cluster_authkey=c.authkey,
                        num_cpus=2)


def _alive_nodes() -> int:
    return sum(1 for n in ray_tpu.nodes() if n["Alive"])


@pytest.mark.slow
def test_daemon_kill_mid_lease_grant_replaces_work(chaos_cluster):
    """A node daemon dies the moment it accepts forwarded work (lease
    grant): the node is declared dead and the task re-places on a
    surviving node within the retry budget."""
    c = chaos_cluster
    c.add_node(num_cpus=2, resources={"pool": 4})
    c.add_node(num_cpus=2, resources={"pool": 4},
               env={"RTPU_FAILPOINTS":
                    "daemon.lease_grant=exit:137@arg=submit_spec"})
    _cluster_init(c)
    poll_until(lambda: _alive_nodes() >= 3, timeout=60, desc="nodes up")

    @ray_tpu.remote(max_retries=3, resources={"pool": 1})
    def work(i):
        return i * 10

    # SPREAD lands work on the doomed daemon; its death re-places
    refs = [work.options(scheduling_strategy="SPREAD").remote(i)
            for i in range(8)]
    assert ray_tpu.get(refs, timeout=180) == [i * 10 for i in range(8)]

    # the dead daemon is exactly ONE node_death at the head (acked
    # heartbeat cursor dedups re-delivery), classified and with the
    # GCS's blast-radius postmortem
    deaths = poll_until(lambda: _events_named("node_death"),
                        timeout=60, desc="node_death event")
    time.sleep(2)  # dedup settle: a re-shipped batch must not dupe it
    deaths = _events_named("node_death")
    assert len(deaths) == 1
    assert deaths[0]["cause"] in ("connection lost", "heartbeat timeout")
    assert deaths[0]["postmortem"]["cause"] == deaths[0]["cause"]


@pytest.mark.slow
def test_gcs_kill_mid_submit_snapshot_recovery(chaos_cluster):
    """kill -9 the GCS while a task stream is in flight: daemons keep
    computing, the restarted GCS reloads the snapshot, nodes re-register,
    and every submitted task completes correctly."""
    c = chaos_cluster
    c.add_node(num_cpus=2, resources={"worker": 4})
    rt = _cluster_init(c)
    rt.kv_op("put", "chaos-key", b"durable")
    time.sleep(1.5)  # let the snapshot loop persist

    @ray_tpu.remote(max_retries=3, resources={"worker": 1})
    def job(i):
        time.sleep(0.05)
        return i + 1000

    results = {}
    errors = []

    def submit_stream():
        for i in range(30):
            try:
                results[i] = ray_tpu.get(job.remote(i), timeout=60)
            except Exception as e:  # noqa: BLE001 — recorded for assert
                errors.append((i, e))

    t = threading.Thread(target=submit_stream)
    t.start()
    time.sleep(0.6)  # land the kill mid-stream
    c.restart_gcs()
    t.join(timeout=240)
    assert not t.is_alive(), "submit stream wedged after GCS restart"
    assert not errors, f"tasks failed across GCS restart: {errors[:3]}"
    assert results == {i: i + 1000 for i in range(30)}
    assert poll_until(lambda: rt.kv_op("get", "chaos-key") == b"durable",
                      timeout=60, desc="KV after restart")

    # the restart itself is a lifecycle event (recorded by the new GCS
    # on snapshot reload, so it survives the process that died)
    restarts = poll_until(lambda: _events_named("gcs_restart"),
                          timeout=60, desc="gcs_restart event")
    assert restarts[0]["severity"] == "warning"


@pytest.mark.slow
def test_heartbeat_blackout_node_reregisters(chaos_cluster):
    """A heartbeat blackout (~ network partition) gets the node declared
    dead; when beats resume, the heartbeat NACK re-registers it and the
    node serves work again."""
    c = chaos_cluster
    # beats at 0.5s, node_timeout 8s: 34 dropped beats (~17s blackout)
    # comfortably crosses the declared-dead line even under contention;
    # the after= prefix lets the node register + settle first
    c.add_node(num_cpus=2, resources={"flaky": 4},
               env={"RTPU_FAILPOINTS":
                    "gcs.heartbeat=drop@after=6@times=34"})
    _cluster_init(c)
    poll_until(lambda: _alive_nodes() >= 2, timeout=60,
               desc="node registered")
    # partition: the node drops out...
    poll_until(lambda: _alive_nodes() < 2, timeout=60, desc="node dead")
    # ...and heals: beats resume, NACK re-registers
    poll_until(lambda: _alive_nodes() >= 2, timeout=120,
               desc="node re-registered")

    @ray_tpu.remote(max_retries=3, resources={"flaky": 1})
    def ping():
        return "pong"

    assert ray_tpu.get(ping.remote(), timeout=120) == "pong"


@pytest.mark.slow
def test_rpc_dispatch_drop_hits_default_deadline(chaos_cluster,
                                                 monkeypatch):
    """The GCS swallowing a request (dropped dispatch) surfaces as a
    TimeoutError on the caller's DEFAULT deadline — no un-deadlined park —
    and the retried poll succeeds; the timeout counter records it."""
    from ray_tpu.core.runtime import _get_runtime
    from ray_tpu.util import metric_defs as md

    monkeypatch.setenv("RTPU_RPC_DEFAULT_TIMEOUT_S", "3")
    c = chaos_cluster
    c.add_node(num_cpus=1)
    rt = _cluster_init(c)
    assert rt is _get_runtime()
    gcs = rt.cluster.gcs
    rt.kv_op("put", "drop-me", b"v")

    def timeouts():
        return sum(v for _, v in
                   md.get("rtpu_rpc_client_timeouts_total")._samples())

    before = timeouts()
    # arg=kv_get: only this test calls kv_get here, so the drop cannot be
    # consumed by a background scheduler/heartbeat RPC
    gcs.call("fp_arm", "rpc.server.dispatch=drop@arg=kv_get@times=1",
             timeout=10)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        gcs.call("kv_get", "drop-me", "default")  # default deadline
    elapsed = time.monotonic() - t0
    assert 2.0 <= elapsed < 30.0, f"default deadline off: {elapsed}"
    assert timeouts() == before + 1
    # the retried poll (the CLAUDE.md deflake idiom) recovers
    assert poll_until(lambda: gcs.call("kv_get", "drop-me", "default") == b"v",
                      timeout=30, desc="kv_get after drop")


@pytest.mark.slow
def test_gcs_kill_between_pg_reserve_and_commit(chaos_cluster):
    """Satellite: kill -9 the GCS INSIDE the 2-phase window (resources
    staged on every node, commit not yet run). The creator's commit is
    node-local, registration retries through the restart, and the group
    converges to READY + schedulable."""
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    c = chaos_cluster
    c.add_node(num_cpus=2, resources={"slot": 2})
    c.add_node(num_cpus=2, resources={"slot": 2})
    _cluster_init(c)
    poll_until(lambda: _alive_nodes() >= 3, timeout=60, desc="nodes up")

    # the driver is the creator: stall ITS commit phase only (local arm,
    # no broadcast), leaving the window open long enough to land the kill
    failpoints.apply_spec("adapter.pg.before_commit=delay:4")
    box = {}

    def create():
        try:
            box["pg"] = placement_group(
                [{"CPU": 1, "slot": 1}] * 2, strategy="STRICT_SPREAD")
        except Exception as e:  # noqa: BLE001 — asserted below
            box["err"] = e

    t = threading.Thread(target=create)
    t.start()
    time.sleep(1.5)  # prepare done on both nodes; creator is in delay:4
    c.restart_gcs()
    t.join(timeout=120)
    failpoints.clear()
    assert not t.is_alive(), "pg creation wedged across GCS restart"
    assert "err" not in box, f"pg creation failed: {box.get('err')}"
    pg = box["pg"]
    assert pg.wait(timeout_seconds=120)

    @ray_tpu.remote(max_retries=2)
    def where():
        return os.getpid()

    refs = [
        where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(2)
    ]
    assert len(set(ray_tpu.get(refs, timeout=180))) == 2


@pytest.mark.slow
def test_elastic_trainer_node_loss_shrinks_then_reexpands(chaos_cluster,
                                                         tmp_path):
    """The elasticity drill (r20 acceptance): kill a node mid-epoch —
    training fences, re-forms at N-1, and resumes from the last
    all-ranks-ok checkpoint WITHOUT burning a max_failures attempt
    (max_failures=0: any group restart would fail the run); when a
    replacement node registers, the executor re-expands to N at a
    checkpoint boundary. Both membership transitions are asserted via
    train_world_epoch events, and progress records prove actual steps
    ran at the shrunken world size."""
    import glob
    import json

    from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                               RunConfig, ScalingConfig)

    c = chaos_cluster
    c.add_node(num_cpus=2, resources={"trainslot": 1})
    victim = c.add_node(num_cpus=2, resources={"trainslot": 1})
    _cluster_init(c)
    poll_until(lambda: _alive_nodes() >= 3, timeout=60, desc="nodes up")

    total_steps = 80

    def loop(config):
        import pickle
        import tempfile
        import time as _t

        import ray_tpu.train as train

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "rank_0", "state.pkl"),
                      "rb") as f:
                start = pickle.load(f)["step"] + 1
        for step in range(start, config["steps"]):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"step": step}, f)
            train.report({"step": step, "ws": ctx.world_size,
                          "epoch": ctx.world_epoch},
                         checkpoint=Checkpoint(d))
            _t.sleep(0.3)

    storage = str(tmp_path / "train")
    trainer = JaxTrainer(
        loop,
        train_loop_config={"steps": total_steps},
        # trainslot pins one worker per non-head node (the head carries
        # none), so killing the victim daemon kills exactly one rank
        scaling_config=ScalingConfig(
            num_workers=2, min_workers=1,
            resources_per_worker={"trainslot": 1.0}),
        run_config=RunConfig(
            name="elastic", storage_path=storage,
            failure_config=FailureConfig(max_failures=0)),
    )
    box = {}

    def run():
        try:
            box["result"] = trainer.fit()
        except BaseException as e:  # noqa: BLE001 - reported by asserts
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()

    # a complete (both-ranks-ok) checkpoint must exist before the kill,
    # or the shrink proves nothing about resume
    def complete_ckpt():
        for p in glob.glob(os.path.join(storage, "elastic", "trial_*",
                                        "checkpoint_*")):
            if (os.path.exists(os.path.join(p, ".rank_0.ok"))
                    and os.path.exists(os.path.join(p, ".rank_1.ok"))):
                return p
        return None

    poll_until(complete_ckpt, timeout=90, desc="first complete checkpoint")
    c.kill_node(victim)

    # node declared dead -> WorkerDeathError -> elastic shrink to 1
    shrink = poll_until(
        lambda: _events_named("train_world_epoch", reason="shrink") or None,
        timeout=120, desc="shrink membership epoch")
    assert int(shrink[-1]["world_size"]) == 1, shrink
    assert int(shrink[-1]["prev_world_size"]) == 2, shrink
    assert shrink[-1]["checkpoint"], "shrink must resume from a checkpoint"

    # capacity returns: a replacement node -> re-expansion to N at a
    # checkpoint boundary
    c.add_node(num_cpus=2, resources={"trainslot": 1})
    expand = poll_until(
        lambda: _events_named("train_world_epoch", reason="expand") or None,
        timeout=180, desc="expand membership epoch")
    assert int(expand[-1]["world_size"]) == 2, expand
    assert int(expand[-1]["prev_world_size"]) == 1, expand

    t.join(timeout=300)
    assert not t.is_alive(), "fit() wedged after membership churn"
    assert "err" not in box, f"elastic fit failed: {box.get('err')!r}"
    result = box["result"]
    assert result.metrics["step"] == total_steps - 1
    assert result.metrics["ws"] == 2          # finished re-expanded
    assert result.metrics["epoch"] >= 2       # shrink + expand epochs

    # actual training steps ran at the shrunken world size (not just a
    # transition event): the progress stream has ws=1 records between
    # the two membership epochs
    (progress_path,) = glob.glob(os.path.join(
        storage, "elastic", "trial_*", "progress.jsonl"))
    ws_seen = [json.loads(line)["ws"]
               for line in open(progress_path) if line.strip()]
    assert 1 in ws_seen and ws_seen[-1] == 2, ws_seen
    # max_failures=0 budget intact: the elastic path never fell back to
    # a group restart (which would have emitted checkpoint_resume)
    assert not _events_named("checkpoint_resume")
