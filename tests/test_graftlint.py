"""graftlint self-tests: fixture contract, rule coverage, catalog drift,
CLI exit codes (ISSUE 6).

The per-family "tree is clean" assertions live in test_invariants.py —
graftlint is the enforcement engine for those invariants; this file
proves the engine itself works.
"""

from pathlib import Path

import pytest

from ray_tpu.devtools import graftlint
from ray_tpu.devtools.graftlint import catalog
from ray_tpu.devtools.graftlint.__main__ import main as graftlint_main

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "graftlint_fixtures"

_FIXTURE_FILES = sorted(
    (p.parent.name, p) for p in FIXTURES.rglob("*.py"))


def _hits(rule: str, path: Path):
    return [f for f in graftlint.lint([path], rules=[rule])
            if f.rule == rule]


# ---------------------------------------------------------------------------
# fixture contract: every bad_* fires its rule, every ok_* stays silent
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "rule,path", _FIXTURE_FILES,
    ids=[f"{r}/{p.name}" for r, p in _FIXTURE_FILES])
def test_fixture(rule, path):
    hits = _hits(rule, path)
    rendered = "\n  ".join(f.render() for f in hits)
    if path.name.startswith("bad_"):
        assert hits, (
            f"positive fixture {rule}/{path.name} produced no "
            f"{rule} finding — the rule regressed")
    else:
        assert not hits, (
            f"negative fixture {rule}/{path.name} should be clean but "
            f"got:\n  {rendered}")


def test_every_rule_has_positive_and_negative_fixtures():
    """ISSUE 6 satellite: a rule without fixtures is an unproven rule."""
    missing = []
    for rule in graftlint.all_rules():
        d = FIXTURES / rule.name
        bad = list(d.glob("bad_*.py")) if d.is_dir() else []
        ok = list(d.glob("ok_*.py")) if d.is_dir() else []
        if not bad or not ok:
            missing.append(f"{rule.name} (bad={len(bad)}, ok={len(ok)})")
    assert not missing, (
        "rules without >=1 positive AND >=1 negative fixture under "
        f"tests/graftlint_fixtures/: {missing}")


def test_fixture_dirs_match_rules():
    """No orphan fixture dirs for rules that no longer exist."""
    known = set(graftlint.rule_names())
    dirs = {d.name for d in FIXTURES.iterdir() if d.is_dir()}
    assert dirs <= known, f"fixture dirs for unknown rules: {dirs - known}"


# ---------------------------------------------------------------------------
# findings format + suppressions
# ---------------------------------------------------------------------------

def test_finding_render_format():
    """Findings print as ``path:line RULE message`` (acceptance
    criterion)."""
    bad = FIXTURES / "layering-seam" / "bad_core_internal_import.py"
    (f,) = _hits("layering-seam", bad)
    rendered = f.render()
    assert rendered.startswith(f"{f.path}:{f.line} layering-seam ")
    assert rendered.split(" ", 2)[2] == f.message


def test_inline_suppression_silences_finding(tmp_path):
    src = (FIXTURES / "layering-seam" /
           "bad_core_internal_import.py").read_text()
    patched = src.replace(
        "    from ray_tpu.core.runtime import _get_runtime",
        "    # graftlint: disable=layering-seam -- test: judged intentional\n"
        "    from ray_tpu.core.runtime import _get_runtime")
    p = tmp_path / "suppressed.py"
    p.write_text(patched)
    assert not _hits("layering-seam", p)
    # but a reasonless suppression is itself flagged
    bare = tmp_path / "bare.py"
    bare.write_text(patched.replace(" -- test: judged intentional", ""))
    assert not _hits("layering-seam", bare)
    assert _hits("bare-suppression", bare)


def test_suppression_in_docstring_is_inert(tmp_path):
    p = tmp_path / "doc.py"
    p.write_text('"""Example: # graftlint: disable=layering-seam"""\n')
    assert not graftlint.lint([p])


def test_bare_disable_all_cannot_silence_itself(tmp_path):
    """'disable=all' with no reason must still produce the
    bare-suppression finding — the rule is unsuppressible (review fix)."""
    p = tmp_path / "a.py"
    p.write_text("import os\nx = os.sep  # graftlint: disable=all\n")
    assert any(f.rule == "bare-suppression" for f in graftlint.lint([p]))


def test_suppression_before_def_covers_header_only(tmp_path):
    """An own-line suppression before a compound statement covers its
    header, never the whole body (review fix)."""
    p = tmp_path / "b.py"
    p.write_text(
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self, conn):\n"
        "        self.lock = threading.Lock()\n"
        "        self.conn = conn\n\n"
        "    # graftlint: disable=blocking-under-lock -- header only\n"
        "    def run(self):\n"
        "        with self.lock:\n"
        "            time.sleep(1)\n")
    hits = graftlint.lint([p], rules=["blocking-under-lock"])
    assert hits, "suppression leaked into the function body"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_exit_codes(capsys):
    bad = FIXTURES / "blocking-under-lock" / "bad_sleep_and_recv.py"
    assert graftlint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "blocking-under-lock" in out and ":" in out.split(" ")[0]
    ok = FIXTURES / "blocking-under-lock" / "ok_cv_wait_and_io_outside.py"
    assert graftlint_main([str(ok)]) == 0
    assert graftlint_main(["--list-rules"]) == 0
    assert graftlint_main(["--rule", "no-such-rule", str(ok)]) == 2
    assert graftlint_main([str(FIXTURES / "does-not-exist.py")]) == 2


def test_tree_is_clean():
    """Acceptance criterion: the shipped tree lints clean (all rules) —
    the CLI exits 0 exactly when this shared finding list is empty. Real
    violations get fixed; judged-intentional sites carry inline reasons
    — never a silent baseline. (Shared lint pass: the suite runs the
    full-tree analysis once, not once per test module.)"""
    from _graftlint_tree import tree_findings

    findings = tree_findings()
    assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# failpoint doc-sync (the documented-list half needs the real catalog)
# ---------------------------------------------------------------------------

def test_failpoint_documented_sites_parse():
    from ray_tpu.devtools.graftlint.rules_failpoints import documented_sites

    sites = documented_sites(
        (ROOT / "ray_tpu" / "util" / "failpoints.py").read_text())
    assert {"worker.exec", "pipe.send", "store.seal",
            "gcs.heartbeat"} <= sites


def test_partial_path_lint_no_stale_failpoint_noise():
    """Linting a file subset that contains hit() sites must not claim
    every documented site outside the subset vanished (review fix)."""
    findings = graftlint.lint(
        [ROOT / "ray_tpu" / "util", ROOT / "ray_tpu" / "core" / "worker.py"],
        rules=["failpoint-sites"])
    assert not findings, [f.render() for f in findings]


def test_overlapping_paths_dedupe():
    """A file passed alongside its containing dir must not be analyzed
    twice — double analysis fabricated duplicate-failpoint findings
    (review fix)."""
    findings = graftlint.lint(
        [ROOT / "ray_tpu" / "core" / "worker.py", ROOT / "ray_tpu" / "core"],
        rules=["failpoint-sites"])
    assert not findings, [f.render() for f in findings]


def test_undocumented_failpoint_site_is_flagged(tmp_path):
    extra = tmp_path / "extra.py"
    extra.write_text(
        "from ray_tpu.util import failpoints\n\n\n"
        "def op():\n"
        "    failpoints.hit('never.documented.site')\n")
    findings = graftlint.lint(
        [ROOT / "ray_tpu" / "util" / "failpoints.py", extra],
        rules=["failpoint-sites"])
    assert any("never.documented.site" in f.message for f in findings), (
        [f.render() for f in findings])


# ---------------------------------------------------------------------------
# catalog (same drift contract as metric_defs' README table)
# ---------------------------------------------------------------------------

def test_readme_rule_catalog_not_stale():
    readme = ROOT / "README.md"
    text = readme.read_text()
    assert catalog.MD_BEGIN in text and catalog.MD_END in text, (
        "README.md lost the graftlint rule-catalog markers")
    start = text.find(catalog.MD_BEGIN)
    end = text.find(catalog.MD_END) + len(catalog.MD_END)
    assert text[start:end] == catalog.markdown_table(), (
        "README rule catalog is stale — run "
        "python -m ray_tpu.devtools.graftlint --update README.md")


def test_catalog_lists_every_rule():
    table = catalog.markdown_table()
    for rule in graftlint.all_rules():
        assert f"`{rule.name}`" in table


# ---------------------------------------------------------------------------
# model cache (ISSUE 15): cold/warm/no-cache parity, stat-keyed invalidation
# ---------------------------------------------------------------------------

def _span_leak_src():
    return (
        "from ray_tpu.util import tracing\n\n\n"
        "def handler():\n"
        "    sp = tracing.manual_span('serve::probe')\n"
        "    return 1\n")


def test_cache_parity_and_invalidation(tmp_path):
    """With ``root`` given, findings must be byte-identical across a
    cold run (populates .graftlint_cache/), a warm run (served from it),
    and a ``cache=False`` run — and editing a file must invalidate its
    entry (the key is (path, mtime_ns, size))."""
    import shutil

    core = tmp_path / "ray_tpu" / "core"
    core.mkdir(parents=True)
    for name in ("worker.py", "protocol.py"):
        shutil.copy(ROOT / "ray_tpu" / "core" / name, core / name)
    serve = tmp_path / "ray_tpu" / "serve"
    serve.mkdir()
    leaky = serve / "probe.py"
    leaky.write_text(_span_leak_src())

    paths = [tmp_path / "ray_tpu"]
    cold = [f.render() for f in graftlint.lint(paths, root=tmp_path)]
    cache_dir = tmp_path / ".graftlint_cache"
    assert cache_dir.is_dir() and list(cache_dir.glob("*.pkl")), (
        "cold lint with root= did not populate the model cache")
    warm = [f.render() for f in graftlint.lint(paths, root=tmp_path)]
    raw = [f.render() for f in
           graftlint.lint(paths, root=tmp_path, cache=False)]
    assert cold == warm == raw, (cold, warm, raw)
    assert any("manual-span-finish" in line for line in cold), (
        "parity test lost its known finding — the fixture file no "
        "longer trips manual-span-finish", cold)

    # editing the file must bust its cache entry, not serve stale model
    leaky.write_text(_span_leak_src().replace(
        "    return 1", "    sp.finish()\n    return 1"))
    fixed = [f.render() for f in graftlint.lint(paths, root=tmp_path)]
    assert not any("manual-span-finish" in line for line in fixed), fixed


def test_cache_never_engages_without_root(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("x = 1\n")
    graftlint.lint([p])
    assert not (tmp_path / ".graftlint_cache").exists()


# ---------------------------------------------------------------------------
# protocol catalog drift (ISSUE 15): removing a cataloged op must fail
# ---------------------------------------------------------------------------

def test_protocol_catalog_drift_is_flagged(tmp_path):
    """Dropping 'put' from PIPE_CASTS in core/protocol.py while
    worker.py still casts it must produce pipe-protocol-sync findings on
    both sides of the wire (sender drift + now-uncataloged arm)."""
    import shutil

    core = tmp_path / "ray_tpu" / "core"
    core.mkdir(parents=True)
    for name in ("worker.py", "runtime.py", "protocol.py"):
        shutil.copy(ROOT / "ray_tpu" / "core" / name, core / name)
    cat = core / "protocol.py"
    src = cat.read_text()
    assert '"put",' in src
    cat.write_text(src.replace('"put",', "", 1))

    findings = graftlint.lint([tmp_path / "ray_tpu"],
                              rules=["pipe-protocol-sync"])
    msgs = [f.render() for f in findings
            if f.rule == "pipe-protocol-sync"]
    assert any("'put'" in m for m in msgs), msgs
