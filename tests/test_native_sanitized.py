"""Sanitizer lane for the native plane (ISSUE 15).

``make -C native sanitize`` builds ASan+UBSan and TSan variants of
librtpu_store.so and runs the two C stress harnesses. This lane closes
the remaining gap: the *Python-facing* surface — ctypes marshaling,
buffer lifetimes, the id padding contract, drain-buffer reuse — runs
against the instrumented .so, so an out-of-bounds read the plain build
silently tolerates aborts the child here.

Mechanics: the ASan runtime must be in the process before the .so loads,
so the exercise runs in a child interpreter with LD_PRELOADed libasan
and ``RTPU_NATIVE_SO`` pointed at the instrumented artifact (the loader
override added for exactly this lane). Leak checking stays off: the
CopyPool and its detached workers are intentionally leaked (pipe.cc).

Slow-marked: the default `make test` lane skips it; `pytest -m slow
tests/test_native_sanitized.py` (or plain pytest on the file) runs it.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]
NATIVE = ROOT / "native"
ASAN_SO = NATIVE / "build" / "librtpu_store_asan.so"

pytestmark = pytest.mark.slow

# What the child runs: every Python wrapper over the native API, against
# real shm + a real socketpair. Assertions are correctness checks; the
# point is that ASan/UBSan watch every native byte they touch.
_CHILD = r"""
import os, socket, sys

from ray_tpu import _native

st = _native.native_status()
assert st["override"] and st["so_path"].endswith("librtpu_store_asan.so"), st
assert st["loaded"] and st["pipe"] and st["lz4"] and not st["stale"], st

# -- arena: create/seal/get/release/delete + eviction + frag stats ----------
_native.NativeArena.destroy("san-lane")
arena = _native.NativeArena("san-lane", capacity=8 << 20)
try:
    for i in range(16):
        oid = b"obj-%03d" % i
        mv = arena.create(oid, 32 * 1024)
        assert mv is not None
        mv[:] = bytes([i]) * len(mv)
        arena.seal(oid)
        got = arena.get(oid)
        assert got is not None and bytes(got[:8]) == bytes([i]) * 8
        del got
        arena.release(oid)
        arena.release(oid)  # drop the create ref too: evictable
    stats = arena.stats()
    assert stats["num_objects"] == 16, stats
    arena.delete(b"obj-000")
    assert not arena.contains(b"obj-000")
    assert arena.contains(b"obj-001")
    arena.evict(1 << 20)
    arena.frag_stats()
finally:
    arena.close()
    _native.NativeArena.destroy("san-lane")

# -- pipe engine: send/drain/refpins/drain_pins/stats/close -----------------
a, b = socket.socketpair()
tx = _native.NativePipe(a.fileno())
rx = _native.NativePipe(b.fileno())
try:
    msgs = [b"\x80" + bytes([i]) * (100 + 37 * i) for i in range(64)]
    for m in msgs:
        assert tx.send(m)
    got = []
    while len(got) < len(msgs):
        recs = rx.drain(timeout=2.0)
        assert recs is not None, "unexpected EOF"
        for typ, payload in recs:
            assert typ == _native.REC_MSG
            got.append(payload)
    assert got == msgs

    # oversized record: exercises the grow-and-retry drain path
    big = b"\x80" + os.urandom(3 << 20)
    assert tx.send(big)
    recs = []
    while not recs:
        recs = rx.drain(timeout=2.0)
    assert recs == [(0, big)]

    # refpin frame -> native borrow table -> transitions + drain_pins
    oid = b"p" * 16
    assert tx.send(b"RTP1" + oid + b"\x01")
    recs = []
    while not recs:
        recs = rx.drain(timeout=2.0)
    assert recs == [(_native.REC_REFPINS, oid + b"\x01")], recs
    assert rx.drain_pins() == [(oid, 1)]
    assert rx.drain_pins() == []

    st_tx, st_rx = tx.stats(), rx.stats()
    assert st_tx["sent_msgs"] == len(msgs) + 2, st_tx
    assert st_rx["recv_msgs"] == len(msgs) + 1, st_rx
    assert st_rx["refpin_deltas"] == 1, st_rx
finally:
    tx.close()
    rx.close()
    a.close()
    b.close()

# -- data plane: parallel_copy + lz4 wrappers -------------------------------
src = bytearray(os.urandom(2 << 20))
dst = bytearray(len(src))
assert _native.parallel_copy(dst, src, threads=2) == len(src)
assert dst == src

for raw in (b"", b"abc", bytes(range(256)) * 64, os.urandom(1 << 16)):
    comp = _native.lz4_compress(raw)
    assert comp is not None
    assert _native.lz4_decompress(comp, len(raw)) == raw
    out = bytearray(len(raw) or 1)
    if raw:
        assert _native.lz4_decompress_into(comp, out) == len(raw)
        assert bytes(out) == raw
try:
    _native.lz4_decompress(b"\x1fAAA\xff\xff", 64)
except ValueError:
    pass
else:
    raise AssertionError("malformed lz4 block must raise")

print("SANITIZED-LANE-OK")
"""


def _libasan_path():
    try:
        out = subprocess.run(
            ["gcc", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
    except Exception:
        return None
    return out if out and os.path.sep in out else None


def test_python_surface_under_asan():
    libasan = _libasan_path()
    if libasan is None:
        pytest.skip("libasan not resolvable via gcc")
    if not ASAN_SO.exists():
        build = subprocess.run(
            ["make", "-C", str(NATIVE), "-s",
             f"build/{ASAN_SO.name}"],
            capture_output=True, text=True, timeout=300)
        assert build.returncode == 0, build.stderr
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": libasan,
        "RTPU_NATIVE_SO": str(ASAN_SO),
        # halt_on_error is the default; leaks are designed (CopyPool)
        "ASAN_OPTIONS": "detect_leaks=0",
        # skip the background arena prefault: the lane times child exit,
        # and the prefault thread adds nothing the harness doesn't cover
        "RTPU_WORKER": "1",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD], env=env, cwd=str(ROOT),
        capture_output=True, text=True, timeout=300)
    tail = (proc.stdout + "\n" + proc.stderr)[-4000:]
    assert proc.returncode == 0, f"sanitized child failed:\n{tail}"
    assert "SANITIZED-LANE-OK" in proc.stdout, tail
    assert "ERROR: AddressSanitizer" not in proc.stderr, tail
    assert "runtime error:" not in proc.stderr, tail


def test_sanitize_artifacts_fresh_enough():
    """`make -C native sanitize` must keep building both .so variants.
    Fresh checkouts have neither artifact (build/ is untracked), and the
    ASan lane above only builds its own .so — so build the TSan variant
    here if missing: the assertion is that the TARGET still works, not
    that a previous run left its output behind."""
    if not ASAN_SO.exists():
        pytest.skip("sanitize artifacts not built in this checkout")
    tsan_so = NATIVE / "build" / "librtpu_store_tsan.so"
    if not tsan_so.exists():
        build = subprocess.run(
            ["make", "-C", str(NATIVE), "-s", f"build/{tsan_so.name}"],
            capture_output=True, text=True, timeout=300)
        assert build.returncode == 0, (
            "TSan .so failed to build — `make -C native sanitize` "
            f"builds BOTH; the target or its deps regressed:\n"
            f"{build.stderr}")
    assert tsan_so.exists()
