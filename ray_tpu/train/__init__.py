"""ray_tpu.train — distributed training over host actors + pjit.

Role analog: ``python/ray/train`` (SURVEY §2.5, §3.4). Public surface
mirrors the reference — ``JaxTrainer`` stands where ``TorchTrainer`` does,
``report``/``get_context``/``get_checkpoint`` match ``ray.train.*`` — but the
data plane is pjit over a device mesh: gradient sync is XLA collectives over
ICI (no process groups), parallelism is declared as a MeshConfig, and
checkpoints save sharded param pytrees host-side.
"""

from ray_tpu.train.checkpoint import (AsyncSave, Checkpoint,
                                      load_pytree, save_pytree,
                                      save_pytree_async)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    ElasticWorldSizeError,
    TrainingProtocolError,
    TrainingWorkerError,
    WorkerDeathError,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    TrainingFailedError,
)
from ray_tpu.train.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
)
from ray_tpu.train.train_state import (
    TrainLoopHelper,
    create_train_state,
    make_train_step,
    state_shardings,
)
from ray_tpu.train.telemetry import StepTelemetry, get_step_telemetry

__all__ = [
    "Checkpoint",
    "save_pytree",
    "save_pytree_async",
    "AsyncSave",
    "load_pytree",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "Backend",
    "BackendConfig",
    "JaxConfig",
    "TrainingWorkerError",
    "TrainingProtocolError",
    "WorkerDeathError",
    "ElasticWorldSizeError",
    "TrainContext",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
    "BaseTrainer",
    "DataParallelTrainer",
    "JaxTrainer",
    "TrainingFailedError",
    "StepTelemetry",
    "get_step_telemetry",
]
