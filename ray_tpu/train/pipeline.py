"""Pipeline parallelism: microbatched GPipe schedule over the ``pp`` axis.

Absent from the reference (SURVEY §2.4: pipeline parallel = "absent").
TPU-native formulation: stages are the ``pp`` mesh axis; the layer stack is
sharded over it so each device group holds L/pp layers; activations rotate
stage-to-stage with ``lax.ppermute`` (one ICI hop); the whole schedule is a
``lax.scan`` inside ``shard_map``, so XLA overlaps the permute of tick t+1
with stage compute of tick t. Autodiff through the scan replays the
schedule in reverse, which IS the backward pipeline (collective-permute
transposes to the opposite rotation) — no hand-written 1F1B needed for
correctness; the bubble is the standard GPipe (S-1)/(M+S-1) fraction.

Use: stack per-layer params on a leading dim, map that dim's logical axis
to ``pp`` (``ShardingRules({"layers": "pp", ...})``), and call
:func:`pipeline_apply` inside ``shard_map`` over the mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    layer_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis: str = "pp",
) -> jax.Array:
    """Run microbatches through all pipeline stages.

    Called inside ``shard_map`` over ``axis``:

    - ``stage_params``: THIS stage's layer stack, leading dim = layers
      owned by the stage (global stack sharded over ``axis``).
    - ``microbatches``: [M, mb, ...] — every stage receives the same
      value; only stage 0 actually consumes it.

    Returns [M, mb, ...] of final-stage outputs, valid on every stage
    (broadcast at the end so downstream loss code is SPMD-uniform).
    """
    num_stages = lax.axis_size(axis)
    stage_id = lax.axis_index(axis)
    num_micro = microbatches.shape[0]
    ticks = num_micro + num_stages - 1

    def stage_compute(x):
        # apply this stage's layers sequentially (scan over local stack)
        def body(h, lp):
            return layer_fn(lp, h), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    mb_shape = microbatches.shape[1:]
    state = jnp.zeros(mb_shape, microbatches.dtype)      # in-flight act
    outputs = jnp.zeros((num_micro,) + mb_shape, microbatches.dtype)
    # the carry is per-stage data from the first rotation on: mark it
    # varying over the pipeline axis up front or the scan's VMA check
    # rejects the unvarying->varying promotion (partial-auto shard_map)
    try:
        state = lax.pcast(state, (axis,), to="varying")
        outputs = lax.pcast(outputs, (axis,), to="varying")
    except (AttributeError, TypeError):  # older jax: no pcast / check_rep
        pass

    fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others keep the
        # activation that just arrived from the previous stage
        ingest = microbatches[jnp.minimum(t, num_micro - 1)]
        x = jnp.where(stage_id == 0,
                      jnp.where(t < num_micro, ingest, state), state)
        y = stage_compute(x)
        # last stage emits microbatch (t - (S-1)) when it's valid. A
        # where-gated unconditional update, not lax.cond: both are
        # correct, but cond+dynamic_update in a partial-auto shard_map
        # scan tripped an XLA CPU lowering CHECK ("invalid binary
        # instruction opcode copy"); the select formulation lowers clean
        # and costs one masked write per tick.
        emit_idx = t - (num_stages - 1)
        valid = jnp.logical_and(stage_id == num_stages - 1, emit_idx >= 0)
        updated = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.maximum(emit_idx, 0), 0)
        outputs = jnp.where(valid, updated, outputs)
        # rotate activations to the next stage
        state = lax.ppermute(y, axis, fwd_perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(tick, (state, outputs),
                                   jnp.arange(ticks))
    # broadcast final-stage outputs to all stages (loss is SPMD-uniform)
    outputs = _select_from_stage(outputs, num_stages - 1, axis)
    return outputs


def _select_from_stage(x: jax.Array, src: int, axis: str) -> jax.Array:
    """All stages receive stage ``src``'s value (masked psum broadcast)."""
    stage_id = lax.axis_index(axis)
    masked = jnp.where(stage_id == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def split_microbatches(batch: jax.Array, num_micro: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    b = batch.shape[0]
    if b % num_micro:
        raise ValueError(f"batch {b} not divisible by {num_micro} microbatches")
    return batch.reshape((num_micro, b // num_micro) + batch.shape[1:])


def merge_microbatches(micro: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [B, ...]."""
    return micro.reshape((-1,) + micro.shape[2:])
