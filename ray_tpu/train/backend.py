"""Backend plugins: per-framework worker-group setup hooks.

Role analog: ``Backend``/``BackendConfig`` (``python/ray/train/backend.py``)
with the Neuron-XLA backend (``train/torch/xla/config.py:20,120``) as the
shape blueprint: on_start does rendezvous env vars, on_training_start does
framework init, on_shutdown cleans up. The TPU-native backend wires the JAX
coordination service instead of ``dist.init_process_group``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshConfig


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by the BackendExecutor around the worker group."""

    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass


@dataclass
class JaxConfig(BackendConfig):
    """JAX/TPU backend config.

    ``distributed=None`` (auto): initialize ``jax.distributed`` only when the
    group has more than one worker — single-host groups (one v5e-8 host, CPU
    tests) just use the local runtime. The coordinator is worker 0's IP
    (reference rendezvous analog: ``_setup_torch_process_group``'s
    MASTER_ADDR, ``train/torch/config.py:65``).
    """

    distributed: Optional[bool] = None
    coordinator_port: int = 8476
    mesh: MeshConfig = field(default_factory=MeshConfig)
    extra_env: Dict[str, str] = field(default_factory=dict)

    @property
    def backend_cls(self):
        return JaxBackend


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int) -> Dict[str, Any]:
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return {
        "process_index": jax.process_index(),
        "device_count": jax.device_count(),
    }


def _shutdown_jax_distributed() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        n = len(worker_group)
        distributed = backend_config.distributed
        if distributed is None:
            distributed = n > 1
        env = dict(backend_config.extra_env)
        if env:
            worker_group.execute(lambda e=env: __import__("os").environ.update(e))
        if distributed:
            meta = worker_group.execute_single(0, lambda: __import__(
                "socket").gethostbyname(__import__("socket").gethostname()))
            coordinator = f"{meta}:{backend_config.coordinator_port}"
            import ray_tpu

            refs = [
                w.execute.remote(_init_jax_distributed, coordinator, n, i)
                for i, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs)

    def on_shutdown(self, worker_group, backend_config: JaxConfig):
        try:
            worker_group.execute(_shutdown_jax_distributed)
        except Exception:
            pass
