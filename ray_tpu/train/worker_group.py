"""WorkerGroup: the gang of train-worker actors.

Role analog: ``python/ray/train/_internal/worker_group.py`` (``WorkerGroup``
:102, ``RayTrainWorker`` :19). Each worker is one host process owning that
host's accelerator devices through a single jax runtime.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _Session, _init_session, \
    _shutdown_session, get_session


class RayTrainWorker:
    """Actor running on each host of the worker group."""

    def __init__(self):
        self._session: Optional[_Session] = None

    # -- environment / metadata ------------------------------------------

    def set_env_vars(self, env: Dict[str, str]) -> None:
        os.environ.update({k: str(v) for k, v in env.items()})

    def get_metadata(self) -> Dict[str, Any]:
        return {
            "hostname": socket.gethostname(),
            "ip": socket.gethostbyname(socket.gethostname()),
            "pid": os.getpid(),
        }

    def get_device_info(self) -> Dict[str, Any]:
        import jax

        devs = jax.local_devices()
        return {
            "backend": jax.default_backend(),
            "local_device_count": len(devs),
            "global_device_count": jax.device_count(),
            "process_index": jax.process_index(),
        }

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process."""
        return fn(*args, **kwargs)

    # -- training session -------------------------------------------------

    def start_session(
        self,
        train_fn: Callable,
        context: TrainContext,
        starting_checkpoint_path: Optional[str] = None,
    ) -> None:
        ckpt = (Checkpoint(starting_checkpoint_path)
                if starting_checkpoint_path else None)
        os.makedirs(context.trial_dir, exist_ok=True)
        session = _Session(lambda: train_fn(context.loop_config)
                           if _fn_wants_config(train_fn) else train_fn(),
                           context, ckpt)
        self._session = session
        _init_session(session)
        session.start()

    def next_result(self, timeout: Optional[float] = 60.0):
        assert self._session is not None, "no session running"
        kind, payload, ckpt = self._session.next_result(timeout=timeout)
        if kind == "error":
            raise payload
        return (kind, payload, ckpt)

    def shutdown_session(self) -> None:
        self._session = None
        _shutdown_session()


def _fn_wants_config(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


class WorkerGroup:
    """Spawns and addresses N RayTrainWorker actors."""

    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_group=None,
    ):
        cls = ray_tpu.remote(RayTrainWorker)
        self.workers: List[Any] = []
        for i in range(num_workers):
            opts: Dict[str, Any] = {
                "num_cpus": resources_per_worker.get("CPU", 1.0),
                "resources": {k: v for k, v in resources_per_worker.items()
                              if k != "CPU"},
            }
            if placement_group is not None:
                opts["placement_group"] = placement_group
                opts["placement_group_bundle_index"] = i
            self.workers.append(cls.options(**opts).remote())

    def __len__(self):
        return len(self.workers)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results (ordered by rank)."""
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
