"""Worker-side training session: ``report``/``get_context`` API.

Role analog: ``_TrainSession`` (``python/ray/train/_internal/session.py:110``)
— the user's ``train_loop_per_worker`` runs on a daemon thread inside the
worker actor; ``ray_tpu.train.report(metrics, checkpoint=)`` enqueues results
that the driver drains via actor calls. ``report`` is also a **barrier** in
spirit: training on a slice is SPMD, so every worker reports the same step
count in lockstep.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class TrainContext:
    """What the user can ask about their worker (reference
    ``ray.train.get_context()``)."""

    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = "default"
    trial_name: str = "trial"
    trial_dir: str = "."
    trial_id: str = "0"
    loop_config: Dict[str, Any] = field(default_factory=dict)
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    # elastic membership (r20): the epoch bumps every time the gang is
    # re-formed at a new world size (preemption shrink / capacity-restore
    # expand); ``resumed_from`` names the checkpoint this session resumed
    # from, or None on a cold start. The LR/batch rescale contract: the
    # user loop reads get_world_size() EVERY session (never caches it
    # across restarts — graftlint ``stale-world-size``) and rescales its
    # per-host batch / learning rate from it, so global batch semantics
    # survive world-size changes.
    world_epoch: int = 0
    resumed_from: Optional[str] = None

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_epoch(self) -> int:
        return self.world_epoch

    def get_resumed_from(self) -> Optional[str]:
        return self.resumed_from

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id


class _Session:
    """Per-process singleton holding the running train thread."""

    def __init__(
        self,
        train_fn: Callable[[], Any],
        context: TrainContext,
        starting_checkpoint: Optional[Checkpoint] = None,
    ):
        self.context = context
        self.starting_checkpoint = starting_checkpoint
        self.result_queue: "queue.Queue" = queue.Queue()
        self.continue_event = threading.Event()
        self.error: Optional[BaseException] = None
        self.finished = False
        # Seed past any checkpoint_* already in the trial dir: after a group
        # restart a fresh session starting at 0 would write checkpoints that
        # name-sort BELOW the pre-crash ones, so every later resume would
        # pick the stale pre-crash checkpoint and repeat work.
        self._checkpoint_seq = self._next_checkpoint_seq(context.trial_dir)

        def runner():
            try:
                train_fn()
                self.result_queue.put(("done", None, None))
            except BaseException as e:  # noqa: BLE001 — propagated to driver
                self.error = e
                self.result_queue.put(("error", e, None))

        self.thread = threading.Thread(target=runner, daemon=True,
                                       name="train_loop")

    @staticmethod
    def _next_checkpoint_seq(trial_dir: str) -> int:
        try:
            seqs = [int(name[len("checkpoint_"):])
                    for name in os.listdir(trial_dir)
                    if name.startswith("checkpoint_")
                    and name[len("checkpoint_"):].isdigit()]
        except OSError:
            return 0
        return max(seqs, default=-1) + 1

    def start(self):
        self.thread.start()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        ckpt_path = None
        if checkpoint is not None:
            # Persist the worker's checkpoint into the trial dir so it
            # outlives the worker process (StorageContext analog,
            # reference train/_internal/storage.py:349).
            seq = self._checkpoint_seq
            self._checkpoint_seq += 1
            dest = os.path.join(
                self.context.trial_dir,
                f"checkpoint_{seq:06d}",
                f"rank_{self.context.world_rank}",
            )
            if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
                checkpoint.to_directory(dest)
            ckpt_path = os.path.dirname(dest)
            # completion marker, written AFTER the rank dir landed: a gang
            # killed mid-persist leaves a torn checkpoint_N, and resume
            # (trainer._latest_checkpoint) must skip it — only checkpoints
            # marked by every rank are resumable
            marker = os.path.join(
                ckpt_path, f".rank_{self.context.world_rank}.ok")
            with open(marker, "w"):
                pass
            # world-size stamp: elastic resume must know how many rank
            # markers make this checkpoint complete — the CURRENT gang's
            # size is no longer a valid guess once world size can change
            # between checkpoints. Every rank writes the same value
            # (idempotent); written after the rank dir like the marker.
            ws_path = os.path.join(ckpt_path, ".world_size")
            if not os.path.exists(ws_path):
                tmp = ws_path + f".tmp.{self.context.world_rank}"
                with open(tmp, "w") as f:
                    f.write(str(self.context.world_size))
                os.replace(tmp, ws_path)
        # step telemetry: each report is one user-loop step — inter-report
        # wall time + well-known keys land in the metrics registry (and
        # federate to the head /metrics); never fails the report
        try:
            from ray_tpu.train import telemetry

            telemetry.on_report(metrics)
        except Exception:
            pass
        self.result_queue.put(("result", dict(metrics), ckpt_path))
        # Block until the driver consumed the result — keeps workers in
        # lockstep at report granularity and bounds queue memory.
        self.continue_event.wait()
        self.continue_event.clear()

    def next_result(self, timeout: Optional[float] = None):
        try:
            kind, payload, ckpt = self.result_queue.get(timeout=timeout)
        except queue.Empty:
            return ("pending", None, None)
        if kind == "result":
            self.continue_event.set()
        return (kind, payload, ckpt)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.starting_checkpoint


_session: Optional[_Session] = None


def _init_session(session: _Session) -> None:
    global _session
    _session = session


def _shutdown_session() -> None:
    global _session
    _session = None


def get_session() -> Optional[_Session]:
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from the train loop."""
    if _session is None:
        raise RuntimeError(
            "ray_tpu.train.report() called outside a training session")
    _session.report(metrics, checkpoint)


def get_context() -> TrainContext:
    if _session is None:
        return TrainContext()
    return _session.context


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from, if the run was restored."""
    if _session is None:
        return None
    return _session.get_checkpoint()


def get_dataset_shard(name: str = "train"):
    """This worker's DataIterator for the named dataset (reference
    ``ray.train.get_dataset_shard``; sharding via ``streaming_split``)."""
    if _session is None:
        raise RuntimeError(
            "get_dataset_shard() called outside a training session")
    shard = _session.context.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset {name!r}; pass datasets={{{name!r}: ds}} to the "
            f"trainer (have {sorted(_session.context.dataset_shards)})")
    return shard
