"""BackendExecutor: placement, spawn, rank assignment, elastic membership.

Role analog: ``python/ray/train/_internal/backend_executor.py:66`` — create
a placement group (:206), spawn the WorkerGroup (:124), share accelerator
visibility (:286), assign ranks (:356), run training (:436), and restart the
whole group on worker failure (:708). TPU twist: a slice is all-or-nothing
(one dead host breaks ICI), so fixed-topology failure handling is always
group-restart from the last checkpoint.

Elastic membership (r20, past the reference): with
``ScalingConfig.min_workers`` set, the executor subscribes to the cluster
adapter's node-death fan-out and treats preemption as a MEMBERSHIP EPOCH
change instead of a failure — :meth:`reform` fences the survivors (kills
the old gang: a half-dead SPMD group must never keep stepping), re-probes
the largest placeable world size, re-forms the worker group there,
renumbers ranks 0..n-1, re-splits dataset shards, and resumes every rank
from the last all-ranks-ok checkpoint; :meth:`maybe_expand` runs the same
machine upward at checkpoint boundaries when capacity returns. Each
re-form bumps ``world_epoch`` (surfaced to the user loop via
``TrainContext.world_epoch``/``resumed_from`` — the LR/batch rescale
hooks). Double preemption DURING a re-form converges because every retry
re-probes capacity before placing; the attempt bound turns pathological
churn into the group-restart fallback instead of a livelock.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ray_tpu import config
import ray_tpu
from ray_tpu.core.exceptions import (ActorDiedError, ActorUnavailableError,
                                     WorkerCrashedError)
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import placement_group as create_pg, \
    remove_placement_group
from ray_tpu.util.retry import retry_transient

logger = logging.getLogger(__name__)

#: exception classes that mean "the rank's PROCESS is gone" (node loss,
#: OOM-kill, preemption) — distinct from a user exception raised inside
#: the training loop, which must keep its original group-restart
#: semantics (elastically re-forming around a deterministic bug would
#: resume-crash-resume forever)
_DEATH_ERRORS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError,
                 ConnectionError)


class TrainingWorkerError(RuntimeError):
    pass


class TrainingProtocolError(TrainingWorkerError):
    """Ranks desynchronized: some finished while others still report().
    This is a training-loop bug (per-rank ``report()`` counts must match
    — the lockstep contract), not a death; retrying cannot fix it."""


class WorkerDeathError(TrainingWorkerError):
    """One or more ranks' processes died mid-training.

    Carries which ranks died (``dead_ranks``: rank -> exception), any
    node up/down payloads the executor's death subscription recorded
    since the last drain (``node_events``), and the event plane's death
    postmortems (``postmortems``: worker/actor/node death events, exit
    forensics attached) so the error names the blast radius instead of
    a bare "inconsistent worker states".
    """

    def __init__(self, message: str, dead_ranks: Dict[int, BaseException],
                 node_events: Optional[List[dict]] = None,
                 postmortems: Optional[List[dict]] = None):
        super().__init__(message)
        self.dead_ranks = dict(dead_ranks)
        self.node_events = list(node_events or [])
        self.postmortems = list(postmortems or [])


class ElasticWorldSizeError(TrainingWorkerError):
    """Surviving placeable capacity fell below ``min_workers`` — the
    elastic path cannot hold the floor; the trainer falls back to a
    group restart attempt (which waits out the capacity loss through
    ``FailureConfig.max_failures``)."""


def _death_postmortems(limit: int = 200) -> List[dict]:
    """Recent death events (worker/actor/node) from the event plane —
    best-effort: the plane may be disabled or the GCS unreachable, and
    error enrichment must never mask the error it enriches."""
    try:
        from ray_tpu.util import state

        evs = retry_transient(
            lambda: state.list_events(limit=limit),
            attempts=3, delay=0.1, desc="death postmortem fetch")
    except Exception:
        return []
    return [e for e in evs
            if e.get("name") in ("worker_death", "actor_death",
                                 "node_death")]


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None
        # elastic membership state
        self._world_size = scaling_config.num_workers
        self._world_epoch = 0
        self._spec: Optional[Dict[str, Any]] = None   # start_training args
        self._start_ckpt: Optional[str] = None
        self._node_events: List[dict] = []
        self._node_events_lock = threading.Lock()
        self._node_sub_cb: Optional[Callable[[dict], None]] = None

    # -- elastic state -----------------------------------------------------

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def world_epoch(self) -> int:
        return self._world_epoch

    def _subscribe_node_events(self) -> None:
        if self._node_sub_cb is None:
            def _on_node_event(payload: dict) -> None:
                with self._node_events_lock:
                    self._node_events.append(dict(payload))
            self._node_sub_cb = _on_node_event
        try:
            from ray_tpu.util import state

            state.subscribe_node_events(self._node_sub_cb)
        except Exception:
            pass  # single-node / uninitialized: nothing to watch

    def _unsubscribe_node_events(self) -> None:
        if self._node_sub_cb is None:
            return
        try:
            from ray_tpu.util import state

            state.unsubscribe_node_events(self._node_sub_cb)
        except Exception:
            pass

    def drain_node_events(self) -> List[dict]:
        """Node up/down payloads recorded since the last drain."""
        with self._node_events_lock:
            out, self._node_events = self._node_events, []
        return out

    def _placeable_world_size(self) -> int:
        """Largest world size placeable RIGHT NOW in [0, num_workers]:
        sum over alive nodes of how many per-worker resource bundles fit
        in the node's total capacity. Capacity, not availability, is the
        right basis — reform fences (kills) the old gang before placing
        the new one, so the old workers' holdings are about to free. The
        node-view probe rides the GCS, so it absorbs the under-load
        transient-ConnectionError class via the shared retry helper."""
        res = self._scaling.worker_resources()
        requested = self._scaling.num_workers
        try:
            nodes = retry_transient(ray_tpu.nodes, attempts=5,
                                    desc="elastic membership probe")
        except Exception:
            # probe dead: claim the current size so the caller's retry
            # loop (which re-probes) decides, rather than failing here
            return min(self._world_size, requested)
        total = 0
        for n in nodes:
            if not n.get("Alive", True):
                continue
            caps = n.get("Resources") or {}
            fit: Optional[int] = None
            for key, need in res.items():
                if need <= 0:
                    continue
                have = float(caps.get(key, 0.0))
                k = int(have // need)
                fit = k if fit is None else min(fit, k)
            total += fit if fit is not None else 0
        return max(0, min(total, requested))

    # -- lifecycle --------------------------------------------------------

    def start(self, num_workers: Optional[int] = None) -> None:
        n = int(num_workers if num_workers is not None
                else self._scaling.num_workers)
        res = self._scaling.worker_resources()
        strategy = self._scaling.effective_placement_strategy()
        try:
            self._pg = create_pg(
                bundles=[dict(res) for _ in range(n)],
                strategy=strategy,
            )
        except Exception:
            if strategy in ("STRICT_SPREAD", "STRICT_PACK", "SLICE_PACK"):
                # gang semantics were REQUESTED: an infeasible reservation
                # must fail loudly, not silently degrade placement
                raise
            # Resource pool too small for a PACK/SPREAD group (tests with
            # tiny clusters): fall back to unconstrained placement.
            self._pg = None
        self.worker_group = WorkerGroup(n, res, placement_group=self._pg)
        self._world_size = n
        # Readiness barrier with a deadline: an infeasible resource demand
        # (e.g. slice-mode bundles on a host that can't fit them) must fail
        # loudly, not hang the driver forever.
        timeout = float(config.get("worker_start_timeout"))
        # graftlint: disable=jax-platforms-leak -- train workers are the
        # designated chip owners (the driver only coordinates): forwarding
        # the platform/XLA env to the gang IS the per-actor opt-in CLAUDE.md
        # prescribes; pool workers still get the hard "cpu" default
        env = {k: v for k, v in os.environ.items()
               if k in ("JAX_PLATFORMS", "XLA_FLAGS", "TPU_VISIBLE_CHIPS")}
        try:
            ray_tpu.get([w.set_env_vars.remote(env)
                         for w in self.worker_group.workers],
                        timeout=timeout)
        except Exception as e:
            self.shutdown()
            raise RuntimeError(
                f"train workers failed to start within {timeout}s — the "
                f"resource demand {res} x{n} is likely infeasible on this "
                f"cluster (set RTPU_WORKER_START_TIMEOUT to adjust)") from e
        self._backend.on_start(self.worker_group, self._backend_config)
        self._subscribe_node_events()

    def shutdown(self) -> None:
        self._unsubscribe_node_events()
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def restart(self) -> None:
        self.shutdown()
        self.start()

    # -- elastic membership epochs ----------------------------------------

    def reform(self, checkpoint_path: Optional[str] = None, *,
               reason: str = "shrink", target: Optional[int] = None,
               attempts: int = 8) -> int:
        """Fence -> re-form -> resume: the membership-epoch transition.

        Kills whatever survives of the current gang (a half-dead SPMD
        group must not keep stepping), re-forms the worker group at
        ``target`` (or the largest placeable world size), renumbers
        ranks, re-splits dataset shards, and restarts every rank's
        session from ``checkpoint_path`` with a bumped ``world_epoch``.
        Returns the new world size.

        A failure inside one attempt (double preemption: a node dies
        while the NEW group is placing or starting) falls through to the
        next attempt, which RE-PROBES capacity — the target can only
        ratchet down toward ``min_workers``, so the loop converges
        instead of livelocking; the bound converts pathological churn
        into the caller's group-restart fallback.
        """
        if self._spec is None:
            raise TrainingWorkerError(
                "reform() called before start_training()")
        min_workers = self._scaling.resolved_min_workers()
        requested = self._scaling.num_workers
        prev_size = self._world_size
        last_err: Optional[BaseException] = None
        for attempt in range(max(int(attempts), 1)):
            self.shutdown()   # the fence
            n = target if target is not None else self._placeable_world_size()
            n = max(0, min(int(n), requested))
            target = None     # later attempts re-probe (double preemption)
            if n < min_workers:
                raise ElasticWorldSizeError(
                    f"placeable world size {n} fell below min_workers="
                    f"{min_workers} (requested {requested}) — elastic "
                    f"re-form cannot hold the floor") from last_err
            self._world_epoch += 1
            try:
                self.start(num_workers=n)
                self._launch_sessions(checkpoint_path)
            except Exception as e:  # noqa: BLE001 — re-probe and retry
                last_err = e
                logger.warning(
                    "elastic re-form attempt %d at world size %d failed: "
                    "%r; re-probing", attempt + 1, n, e)
                continue
            try:
                from ray_tpu.util import events

                events.emit("train_world_epoch", epoch=self._world_epoch,
                            world_size=n, prev_world_size=prev_size,
                            reason=reason,
                            checkpoint=checkpoint_path or "")
            except Exception:
                pass
            logger.info("mesh re-formed: world size %d -> %d (epoch %d, "
                        "%s)", prev_size, n, self._world_epoch, reason)
            return n
        raise TrainingWorkerError(
            f"elastic re-form failed after {attempts} attempt(s)"
        ) from last_err

    def maybe_expand(self, checkpoint_path: Optional[str], *,
                     attempts: int = 8) -> Optional[int]:
        """Scale-back-up check, run at checkpoint boundaries: if the
        cluster can place more workers than the current (shrunken) world
        size, re-form upward toward the requested size from the
        just-written all-ranks-ok checkpoint. Returns the new world size
        or None when no expansion happened."""
        requested = self._scaling.num_workers
        if self._world_size >= requested:
            return None
        n = self._placeable_world_size()
        if n <= self._world_size:
            return None
        return self.reform(checkpoint_path, reason="expand", target=n,
                           attempts=attempts)

    # -- training ---------------------------------------------------------

    def start_training(
        self,
        train_fn: Callable,
        loop_config: Dict[str, Any],
        trial_dir: str,
        experiment_name: str,
        checkpoint_path: Optional[str] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ) -> None:
        assert self.worker_group is not None
        # keep the spec: reform() re-launches these sessions at a new
        # world size without the trainer re-plumbing its arguments
        self._spec = {
            "train_fn": train_fn,
            "loop_config": loop_config,
            "trial_dir": trial_dir,
            "experiment_name": experiment_name,
            "datasets": datasets or {},
        }
        self._launch_sessions(checkpoint_path)

    def _launch_sessions(self, checkpoint_path: Optional[str]) -> None:
        assert self.worker_group is not None
        assert self._spec is not None
        spec = self._spec
        self._start_ckpt = checkpoint_path
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        n = len(self.worker_group)
        # dataset ingest (reference DataConfig): each named dataset is
        # streaming_split across ranks; workers pull their shard's blocks.
        # Re-split on every membership epoch: shard count tracks the
        # CURRENT world size, never the requested one.
        shard_lists: Dict[str, Any] = {}
        for name, ds in spec["datasets"].items():
            shard_lists[name] = ds.streaming_split(n)
        trial_dir = spec["trial_dir"]
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_rank=rank,
                world_size=n,
                local_rank=0,
                local_world_size=1,
                node_rank=rank,
                experiment_name=spec["experiment_name"],
                trial_name=os.path.basename(trial_dir),
                trial_dir=trial_dir,
                loop_config=dict(spec["loop_config"]),
                dataset_shards={name: shards[rank]
                                for name, shards in shard_lists.items()},
                world_epoch=self._world_epoch,
                resumed_from=checkpoint_path,
            )
            refs.append(w.start_session.remote(spec["train_fn"], ctx,
                                               checkpoint_path))
        ray_tpu.get(refs)

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[Any]]:
        """Drain one ``report`` from every worker (they move in lockstep).

        Returns a list of (metrics, checkpoint_dir) per rank, or None when
        all workers finished. Raises :class:`WorkerDeathError` (which
        ranks died + node events + event-plane postmortems) when rank
        processes are gone, :class:`TrainingProtocolError` when ranks
        desynchronized (a loop bug, not a death), and re-raises a user
        training exception unchanged.
        """
        assert self.worker_group is not None
        refs = [w.next_result.remote(timeout)
                for w in self.worker_group.workers]
        outs: List[Any] = []
        dead: Dict[int, BaseException] = {}
        for rank, ref in enumerate(refs):
            try:
                outs.append(ray_tpu.get(ref))
            except _DEATH_ERRORS as e:
                dead[rank] = e
                outs.append(None)
            # user training errors propagate unchanged (previous
            # semantics: first raising rank wins; the trainer's restart
            # budget owns those)
        if dead:
            node_events = self.drain_node_events()
            downs = [p for p in node_events if p.get("event") == "down"]
            msg = (f"rank(s) {sorted(dead)} of {len(refs)} died: "
                   + "; ".join(f"rank {r}: {type(e).__name__}: {e}"
                               for r, e in sorted(dead.items())))
            if downs:
                msg += ("; node events: "
                        + ", ".join(
                            f"{(p.get('node_id') or b'').hex()[:8]} "
                            f"down ({p.get('cause', '?')})"
                            if isinstance(p.get("node_id"), bytes)
                            else f"{p.get('node_id', '?')} down "
                                 f"({p.get('cause', '?')})"
                            for p in downs))
            raise WorkerDeathError(msg, dead, node_events=node_events,
                                   postmortems=_death_postmortems())
        kinds = {k for k, _, _ in outs}
        if kinds == {"done"}:
            return None
        if "pending" in kinds:
            raise TimeoutError(
                f"workers did not report within {timeout}s (kinds={kinds})")
        if kinds == {"result"}:
            return [(m, c) for _, m, c in outs]
        if "done" in kinds and "result" in kinds:
            done_ranks = [r for r, (k, _, _) in enumerate(outs)
                          if k == "done"]
            raise TrainingProtocolError(
                f"ranks desynchronized: rank(s) {done_ranks} finished "
                f"while others still report() — per-rank report() counts "
                f"must match (the lockstep contract); this is a "
                f"training-loop bug, not a worker death")
        raise TrainingWorkerError(f"inconsistent worker states: {kinds}")

    def finish_training(self) -> None:
        if self.worker_group is None:
            return
        for w in self.worker_group.workers:
            try:
                ray_tpu.get(w.shutdown_session.remote())
            except Exception:
                pass
