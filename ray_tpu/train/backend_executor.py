"""BackendExecutor: placement, spawn, rank assignment, restart-on-failure.

Role analog: ``python/ray/train/_internal/backend_executor.py:66`` — create
a placement group (:206), spawn the WorkerGroup (:124), share accelerator
visibility (:286), assign ranks (:356), run training (:436), and restart the
whole group on worker failure (:708). TPU twist: a slice is all-or-nothing
(one dead host breaks ICI), so failure handling is always group-restart from
the last checkpoint.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, List, Optional

from ray_tpu import config
import ray_tpu
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import placement_group as create_pg, \
    remove_placement_group

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        n = self._scaling.num_workers
        res = self._scaling.worker_resources()
        strategy = self._scaling.effective_placement_strategy()
        try:
            self._pg = create_pg(
                bundles=[dict(res) for _ in range(n)],
                strategy=strategy,
            )
        except Exception:
            if strategy in ("STRICT_SPREAD", "STRICT_PACK", "SLICE_PACK"):
                # gang semantics were REQUESTED: an infeasible reservation
                # must fail loudly, not silently degrade placement
                raise
            # Resource pool too small for a PACK/SPREAD group (tests with
            # tiny clusters): fall back to unconstrained placement.
            self._pg = None
        self.worker_group = WorkerGroup(n, res, placement_group=self._pg)
        # Readiness barrier with a deadline: an infeasible resource demand
        # (e.g. slice-mode bundles on a host that can't fit them) must fail
        # loudly, not hang the driver forever.
        timeout = float(config.get("worker_start_timeout"))
        # graftlint: disable=jax-platforms-leak -- train workers are the
        # designated chip owners (the driver only coordinates): forwarding
        # the platform/XLA env to the gang IS the per-actor opt-in CLAUDE.md
        # prescribes; pool workers still get the hard "cpu" default
        env = {k: v for k, v in os.environ.items()
               if k in ("JAX_PLATFORMS", "XLA_FLAGS", "TPU_VISIBLE_CHIPS")}
        try:
            ray_tpu.get([w.set_env_vars.remote(env)
                         for w in self.worker_group.workers],
                        timeout=timeout)
        except Exception as e:
            self.shutdown()
            raise RuntimeError(
                f"train workers failed to start within {timeout}s — the "
                f"resource demand {res} x{n} is likely infeasible on this "
                f"cluster (set RTPU_WORKER_START_TIMEOUT to adjust)") from e
        self._backend.on_start(self.worker_group, self._backend_config)

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def restart(self) -> None:
        self.shutdown()
        self.start()

    # -- training ---------------------------------------------------------

    def start_training(
        self,
        train_fn: Callable,
        loop_config: Dict[str, Any],
        trial_dir: str,
        experiment_name: str,
        checkpoint_path: Optional[str] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ) -> None:
        assert self.worker_group is not None
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        n = len(self.worker_group)
        # dataset ingest (reference DataConfig): each named dataset is
        # streaming_split across ranks; workers pull their shard's blocks.
        shard_lists: Dict[str, Any] = {}
        for name, ds in (datasets or {}).items():
            shard_lists[name] = ds.streaming_split(n)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_rank=rank,
                world_size=n,
                local_rank=0,
                local_world_size=1,
                node_rank=rank,
                experiment_name=experiment_name,
                trial_name=os.path.basename(trial_dir),
                trial_dir=trial_dir,
                loop_config=dict(loop_config),
                dataset_shards={name: shards[rank]
                                for name, shards in shard_lists.items()},
            )
            refs.append(w.start_session.remote(train_fn, ctx, checkpoint_path))
        ray_tpu.get(refs)

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[Any]]:
        """Drain one ``report`` from every worker (they move in lockstep).

        Returns a list of (metrics, checkpoint_dir) per rank, or None when
        all workers finished. Raises on worker training error.
        """
        assert self.worker_group is not None
        refs = [w.next_result.remote(timeout)
                for w in self.worker_group.workers]
        outs = ray_tpu.get(refs)
        kinds = {k for k, _, _ in outs}
        if kinds == {"done"}:
            return None
        if "pending" in kinds:
            raise TimeoutError(
                f"workers did not report within {timeout}s (kinds={kinds})")
        if kinds != {"result"}:
            raise TrainingWorkerError(f"inconsistent worker states: {kinds}")
        return [(m, c) for _, m, c in outs]

    def finish_training(self) -> None:
        if self.worker_group is None:
            return
        for w in self.worker_group.workers:
            try:
                ray_tpu.get(w.shutdown_session.remote())
            except Exception:
                pass
