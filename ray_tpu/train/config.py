"""Train/AIR-style run configuration dataclasses.

Role analogs in the reference: ``ScalingConfig``/``RunConfig``/
``FailureConfig``/``CheckpointConfig`` in ``python/ray/air/config.py`` and
``Result`` in ``python/ray/air/result.py``. TPU-native addition: a
:class:`ray_tpu.parallel.mesh.MeshConfig` rides inside ``ScalingConfig`` so
the *parallelism layout* (dp/fsdp/tp/sp/ep/pp) is declared where the
reference only declares a worker count — the mesh is the TPU equivalent of
"how many DDP ranks".
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshConfig


@dataclass
class ScalingConfig:
    """How many workers (host processes) and what each one owns.

    One worker = one host actor owning all that host's TPU chips through a
    single jax runtime (SURVEY §7 design stance: process per host, not per
    chip). ``num_workers=1`` covers single-host slices (v5e-8 and below) and
    every CPU test; multi-host slices get one worker per host plus a
    jax.distributed rendezvous run by the backend.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    topology: Optional[str] = None        # e.g. "v5e-256" (informational)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    # Elastic membership (r20): ``min_workers`` turns preemption tolerance
    # on — on worker/node loss the BackendExecutor re-forms the gang at
    # the largest placeable world size in [min_workers, num_workers]
    # instead of failing the run, and re-expands toward ``num_workers``
    # at checkpoint boundaries when capacity returns. None (default)
    # keeps the fixed-size gang: any loss is a group restart that burns a
    # FailureConfig.max_failures attempt. Elastic mode requires the user
    # loop to honor the rescale contract (read get_world_size() fresh
    # every session; see TrainContext.world_epoch).
    min_workers: Optional[int] = None

    @property
    def elastic(self) -> bool:
        return self.min_workers is not None

    def resolved_min_workers(self) -> int:
        if self.min_workers is None:
            return self.num_workers
        return max(1, min(int(self.min_workers), self.num_workers))

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            if self.topology:
                # slice mode: one worker per HOST owning all its chips
                from ray_tpu.util.accelerators import \
                    get_num_tpu_chips_on_node

                res["TPU"] = float(max(get_num_tpu_chips_on_node(), 1))
            else:
                res["TPU"] = 1.0
        if self.topology and self.use_tpu:
            # pin each worker to a distinct slice host via the pod-name
            # resource every host carries (SURVEY §2.6 pattern); resources
            # registered by the runtime at init on TPU hosts
            from ray_tpu.util.accelerators import get_current_pod_name

            pod_name = get_current_pod_name()
            if pod_name:
                res[pod_name] = 1.0
        return res

    def effective_placement_strategy(self) -> str:
        """Multi-host slices gang-schedule one bundle per DISTINCT host
        (SLICE_PACK); everything else keeps the configured strategy."""
        if (self.use_tpu and self.topology and self.num_workers > 1
                and self.placement_strategy == "PACK"):
            return "SLICE_PACK"
        return self.placement_strategy


@dataclass
class FailureConfig:
    """Failure-recovery budget for a training run.

    Group restart (reference ``backend_executor.py:708 _restart``) is now
    the FALLBACK, not the only recovery: with
    ``ScalingConfig.min_workers`` set, a worker/node loss first goes
    through the elastic path — fence the survivors, re-form the gang at
    the largest placeable world size, and resume from the last
    all-ranks-ok checkpoint — WITHOUT consuming a ``max_failures``
    attempt (preemption is weather, not a failure of the job).
    ``max_failures`` attempts are spent only when recovery has to fall
    back to a same-size group restart: elasticity disabled
    (``min_workers=None`` — on a TPU slice one lost host kills the ICI
    collective, so fixed-topology runs must restart the whole gang), the
    surviving capacity below ``min_workers``, or the re-form loop itself
    failing ``elastic_reform_attempts`` times (double preemption burning
    every candidate world size).
    """

    max_failures: int = 0
    # bound on consecutive fence->re-form->resume attempts per membership
    # change: each attempt re-probes placeable capacity, so a second
    # preemption DURING re-form just shrinks the next attempt's target
    # (convergence), and the bound turns a pathological churn loop into
    # an ordinary group-restart fallback instead of a livelock.
    elastic_reform_attempts: int = 8


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    callbacks: list = field(default_factory=list)   # tune logger callbacks
    stop: Optional[Any] = None                      # Stopper | callable

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")


@dataclass
class Result:
    """What ``Trainer.fit`` returns (reference ``air/result.py``)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Any]          # ray_tpu.train.Checkpoint
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_dataframe: Optional[Any] = None
    config: Optional[Dict[str, Any]] = None

    @property
    def best_checkpoints(self):
        return getattr(self, "_best_checkpoints", [])
