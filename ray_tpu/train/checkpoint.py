"""Checkpoint: a directory handle, plus pytree (de)serialization helpers.

Role analog: ``ray.train.Checkpoint`` (``python/ray/train/_checkpoint.py:56``)
— a checkpoint IS a directory on a filesystem; frameworks decide what's
inside. The pytree helpers save/restore JAX param/opt-state trees; sharded
``jax.Array`` leaves are fetched host-side per shard so each host writes
only what it owns (orbax-style process-local saving) and restore re-places
shards onto the target mesh sharding.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Iterator, Optional

import numpy as np

_METADATA_FILE = ".metadata.json"
_TREE_FILE = "pytree.npz"
_STRUCT_FILE = "pytree_struct.pkl"


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "dict_checkpoint.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "dict_checkpoint.pkl"), "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        if os.path.abspath(path) != self.path:
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_metadata(self, meta: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(meta, f)

    def update_metadata(self, meta: Dict[str, Any]) -> None:
        m = self.get_metadata()
        m.update(meta)
        self.set_metadata(m)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


# ---------------------------------------------------------------------------
# Pytree save/restore
# ---------------------------------------------------------------------------

def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_pytree(tree: Any, path: str, *, name: str = "state") -> None:
    """Save a pytree of arrays under ``path``. Device arrays are pulled to
    host as numpy; structure goes to a pickle next to the flat arrays.

    Crash-atomic: payloads are written as ``.tmp-*`` siblings in the
    target dir (same filesystem, so ``os.replace`` is atomic), fsynced,
    renamed into place, and only then is the ``.metadata.json``
    completeness marker landed (merge-updating any user-set metadata) and
    the directory fsynced. A worker killed mid-save leaves either temp
    litter or the previous files — never a readable-but-torn save that
    ``trainer._latest_checkpoint`` could resume from.
    """
    import jax

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    host = []
    for leaf in leaves:
        if hasattr(leaf, "addressable_data"):   # jax.Array (maybe sharded)
            leaf = jax.device_get(leaf)
        host.append(np.asarray(leaf))
    final_tree = os.path.join(path, f"{name}_{_TREE_FILE}")
    final_struct = os.path.join(path, f"{name}_{_STRUCT_FILE}")
    tmp_tree = os.path.join(path, f".tmp-{name}_{_TREE_FILE}")
    tmp_struct = os.path.join(path, f".tmp-{name}_{_STRUCT_FILE}")
    with open(tmp_tree, "wb") as f:
        np.savez(f, **{str(i): a for i, a in enumerate(host)})
        f.flush()
        os.fsync(f.fileno())
    with open(tmp_struct, "wb") as f:
        pickle.dump(treedef, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_tree, final_tree)
    os.replace(tmp_struct, final_struct)
    meta_path = os.path.join(path, _METADATA_FILE)
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
    meta.setdefault("pytrees", {})[name] = {"leaves": len(host)}
    tmp_meta = os.path.join(path, f".tmp-{_METADATA_FILE.lstrip('.')}")
    with open(tmp_meta, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_meta, meta_path)
    _fsync_dir(path)


class AsyncSave:
    """Handle for an in-flight background checkpoint write."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._errbox = errbox

    def wait(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in flight")
        if self._errbox:
            raise self._errbox[0]

    def done(self) -> bool:
        return not self._thread.is_alive()


def save_pytree_async(tree: Any, path: str, *, name: str = "state") -> AsyncSave:
    """Non-blocking :func:`save_pytree` (orbax async-checkpoint role): the
    device->host pull happens NOW (a consistent snapshot — the train loop
    may donate/overwrite the buffers immediately after this returns), and
    the disk write runs on a background thread. The caller owns the
    handle: call ``.wait()`` before relying on the files (e.g.
    ``TrainLoopHelper.save_checkpoint_async`` returns it so a train loop
    waits before reporting the checkpoint)."""
    import threading

    import jax

    leaves, treedef = jax.tree.flatten(tree)
    # The snapshot must not ALIAS caller buffers (the caller is licensed
    # to donate/overwrite immediately). On a real device backend,
    # device_get already materializes a fresh host buffer — forcing a
    # second copy there would double host RAM for a multi-GB state. On
    # the CPU backend device_get/np.asarray can be zero-copy views of the
    # (donatable) buffer, so there the copy is forced.
    from ray_tpu.util.tpu_info import is_tpu_backend

    _pull = ((lambda x: np.asarray(jax.device_get(x))) if is_tpu_backend()
             else (lambda x: np.array(jax.device_get(x), copy=True)))
    host = [_pull(leaf) if hasattr(leaf, "addressable_data")
            else np.array(leaf, copy=True)
            for leaf in leaves]
    snapshot = jax.tree.unflatten(treedef, host)
    errbox: list = []

    def write():
        try:
            save_pytree(snapshot, path, name=name)
        except BaseException as e:  # surfaced at wait()
            errbox.append(e)

    t = threading.Thread(target=write, daemon=True,
                         name="ckpt-async-write")
    t.start()
    return AsyncSave(t, errbox)


def load_pytree(path: str, *, name: str = "state", shardings: Any = None) -> Any:
    """Load a pytree saved by :func:`save_pytree`; optionally re-place leaves
    onto ``shardings`` (a matching pytree of ``NamedSharding``)."""
    import jax

    with open(os.path.join(path, f"{name}_{_STRUCT_FILE}"), "rb") as f:
        treedef = pickle.load(f)
    z = np.load(os.path.join(path, f"{name}_{_TREE_FILE}.npz")
                if not os.path.exists(os.path.join(path, f"{name}_{_TREE_FILE}"))
                else os.path.join(path, f"{name}_{_TREE_FILE}"))
    leaves = [z[str(i)] for i in range(len(z.files))]
    tree = jax.tree.unflatten(treedef, leaves)
    if shardings is not None:
        tree = jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)
    return tree
