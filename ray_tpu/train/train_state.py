"""TrainState + pjit train-step factory: the TPU training inner loop.

Green-field relative to the reference (its inner loop is the user's torch
code; Ray only sees epoch-granularity reports, SURVEY §3.4). Here the
framework owns a canonical pjit training step because the sharding layout
(params on fsdp/tp, batch on dp×fsdp, sequence on sp) is framework policy:

- params/opt-state are placed by logical-axis rules (ZeRO-3 ≡ fsdp axis);
- the step is jitted once with donated state (buffers reused in HBM);
- gradients come out of ``jax.grad`` already averaged across the data axes
  by XLA (the loss is a global mean — no explicit allreduce anywhere).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import MeshConfig, make_mesh
from ray_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules

TrainState = Dict[str, Any]   # {"step", "params", "opt_state"}


def create_train_state(
    params: Any,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    return {
        "step": jnp.zeros((), jnp.int32),
        "params": params,
        "opt_state": optimizer.init(params),
    }


def state_shardings(
    state: TrainState,
    param_axes: Any,
    mesh: Mesh,
    rules: Optional[ShardingRules] = None,
) -> TrainState:
    """NamedSharding pytree for a TrainState: opt-state moments inherit the
    param sharding they correspond to (ZeRO: optimizer state sharded like
    params); scalars replicate."""
    rules = rules or DEFAULT_RULES
    param_shardings = jax.tree.map(
        lambda axes: NamedSharding(mesh, rules.spec(axes)),
        param_axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )
    replicated = NamedSharding(mesh, P())

    params_struct = jax.tree.structure(state["params"])

    def opt_leaf_sharding(leaf):
        # optax states are pytrees whose array leaves either mirror the param
        # tree (moments) or are scalars (counts).
        if jax.tree.structure(leaf) == params_struct:
            return param_shardings
        return jax.tree.map(lambda _: replicated, leaf)

    opt_shardings = jax.tree.map(
        opt_leaf_sharding, state["opt_state"],
        is_leaf=lambda x: jax.tree.structure(x) == params_struct or not isinstance(x, (tuple, list, dict)),
    )
    return {
        "step": replicated,
        "params": param_shardings,
        "opt_state": opt_shardings,
    }


def make_train_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Dict]],
    optimizer: optax.GradientTransformation,
    *,
    donate: bool = True,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict]]:
    """Build a jittable ``(state, batch) -> (state, metrics)`` step.

    Call it under ``jax.set_mesh(mesh)`` with sharded state — XLA inserts
    all collectives (grad psum over dp/fsdp, all-gathers for fsdp params,
    ring permutes for sp attention).
    """

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"], batch)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = {
            "step": state["step"] + 1,
            "params": params,
            "opt_state": opt_state,
        }
        return new_state, metrics

    from ray_tpu.util.device_plane import registered_jit

    return registered_jit(step, name="train::step", component="train",
                          donate_argnums=(0,) if donate else (),
                          compiler_options=_compiler_options())


def _compiler_options() -> Optional[Dict[str, str]]:
    """Per-jit XLA compile options from the ``xla_compiler_options`` knob
    (``RTPU_XLA_COMPILER_OPTIONS="k=v k2=v2"``). Per-jit because TPU
    flags in ``XLA_FLAGS`` abort the HOST XLA flag parser on the
    tunneled axon backend — compile options ride to the remote compiler
    instead."""
    from ray_tpu import config as _knobs

    raw = str(_knobs.get("xla_compiler_options") or "").strip()
    if not raw:
        return None
    out: Dict[str, Any] = {}
    for tok in raw.replace(",", " ").split():
        key, _, val = tok.partition("=")
        if not key or not val:
            raise ValueError(
                f"xla_compiler_options entry {tok!r} is not k=v")
        # Quoted values opt OUT of type coercion: string-typed XLA options
        # whose value LOOKS numeric/bool (k='123') stay strings — the
        # coercion below would otherwise make them unexpressible
        if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
            out[key] = val[1:-1]
            continue
        # XLA's option setter wants typed values (a literal "true" is
        # rejected as "not a valid bool value"; same for int/float
        # fields fed strings)
        if val.lower() in ("true", "false"):
            out[key] = val.lower() == "true"
        elif val.lstrip("-").isdigit():
            out[key] = int(val)
        else:
            try:
                out[key] = float(val)
            except ValueError:
                out[key] = val
    return out


@dataclass
class TrainLoopHelper:
    """Convenience bundle most train loops need: mesh + sharded state + step.

    Used by the built-in LLM workloads (bench.py, examples) and by users who
    don't want to hand-roll the pjit plumbing. One call builds the mesh from
    the ScalingConfig's MeshConfig, places params, and compiles the step.
    """

    mesh: Mesh
    state: TrainState
    step_fn: Callable
    rules: ShardingRules
    _multi_step_cache: Dict[int, Callable] = field(default_factory=dict)

    @classmethod
    def create(
        cls,
        init_params_fn: Callable[[], Any],
        param_axes: Any,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        *,
        mesh_config: Optional[MeshConfig] = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[ShardingRules] = None,
        donate: bool = True,
    ) -> "TrainLoopHelper":
        rules = rules or DEFAULT_RULES
        if mesh is None:
            mesh = make_mesh(mesh_config or MeshConfig())
        with jax.set_mesh(mesh):
            # Init params already sharded: jit the initializer with sharded
            # outputs so big models never materialize replicated.
            abstract = jax.eval_shape(init_params_fn)
            p_sh = jax.tree.map(
                lambda axes: NamedSharding(mesh, rules.spec(axes)),
                param_axes,
                is_leaf=lambda x: isinstance(x, tuple) and all(
                    a is None or isinstance(a, str) for a in x),
            )
            from ray_tpu.util.device_plane import registered_jit

            params = registered_jit(init_params_fn,
                                    name="train::init_params",
                                    component="train",
                                    out_shardings=p_sh)()
            state = create_train_state(params, optimizer)
            st_sh = state_shardings(state, param_axes, mesh, rules)
            state = jax.tree.map(
                lambda x, s: jax.device_put(x, s) if hasattr(x, "shape") else x,
                state, st_sh)
            step_fn = make_train_step(loss_fn, optimizer, donate=donate)
        return cls(mesh=mesh, state=state, step_fn=step_fn, rules=rules)

    def batch_sharding(self) -> NamedSharding:
        batch_axes = tuple(a for a in ("dcn", "dp", "fsdp")
                           if a in self.mesh.axis_names)
        return NamedSharding(self.mesh, P(batch_axes or None))

    def _check_batch(self, batch: Dict[str, jax.Array]) -> None:
        shape = dict(self.mesh.shape)
        ways = 1
        for a in ("dcn", "dp", "fsdp"):
            ways *= shape.get(a, 1)
        for k, v in batch.items():
            if hasattr(v, "shape") and v.shape and v.shape[0] % ways:
                raise ValueError(
                    f"batch[{k!r}] leading dim {v.shape[0]} does not divide "
                    f"by the data-parallel ways dcn*dp*fsdp={ways} of mesh "
                    f"{shape}; pad the batch or change the mesh")

    def run_step(self, batch: Dict[str, jax.Array]):
        self._check_batch(batch)
        bs = self.batch_sharding()
        batch = jax.tree.map(lambda x: jax.device_put(x, bs), batch)
        with jax.set_mesh(self.mesh):
            self.state, metrics = self.step_fn(self.state, batch)
        return metrics

    def save_checkpoint_async(self, path: str, *, name: str = "state"):
        """Snapshot the CURRENT train state and write it in the background
        (orbax async-checkpoint role). The device→host pull — with forced
        copies — completes before this returns, so the next ``run_steps``
        may donate/overwrite the state buffers immediately; only the disk
        write overlaps training. Call ``.wait()`` on the returned handle
        before relying on the files."""
        from ray_tpu.train.checkpoint import save_pytree_async

        return save_pytree_async(self.state, path, name=name)

    def profile_steps(self, batch: Dict[str, jax.Array], n: int,
                      logdir: str):
        """Capture an XLA device trace of ``n`` scanned steps to
        ``logdir`` (view with TensorBoard's profile plugin / xprof).

        The scaling-book loop is "annotate shardings, let XLA insert
        collectives, PROFILE, iterate" — this is the profile step, one
        call. Returns the last step's metrics; trace capture failures
        (some backends don't support profiling) surface as a warning,
        never break the step."""
        import warnings

        metrics = None
        try:
            with jax.profiler.trace(logdir):
                metrics = self.run_steps(batch, n)
                # completion barrier INSIDE the trace: a dependent
                # device_get, not block_until_ready (which acks early on
                # the tunneled axon backend — see CLAUDE.md)
                jax.device_get(jax.tree.leaves(metrics)[0])
        except Exception as e:
            warnings.warn(f"profiler trace failed ({e})"
                          + ("; ran unprofiled" if metrics is None else
                             "; steps DID run, capture incomplete"))
            if metrics is None:  # never double-apply optimizer steps
                metrics = self.run_steps(batch, n)
        return metrics

    def run_steps(self, batch: Dict[str, jax.Array], n: int):
        """Run ``n`` optimizer steps on the same batch as ONE compiled
        program (``lax.scan`` over the step body) and return the last
        step's metrics.

        One dispatch + one host read per n steps instead of per step —
        the idiomatic TPU inner loop (host round-trips never pace the
        chip). The returned loss depends on every step's params (the
        carry chains them), so a ``device_get`` of it provably spans all
        n steps — sound timing even on backends where
        ``block_until_ready`` acks early."""
        fresh = n not in self._multi_step_cache
        if fresh:
            step_fn = self.step_fn

            def multi(state, batch):
                def body(s, _):
                    s2, m = step_fn(s, batch)
                    return s2, m

                state, ms = jax.lax.scan(body, state, None, length=n)
                return state, jax.tree.map(lambda a: a[-1], ms)

            from ray_tpu.util.device_plane import registered_jit

            self._multi_step_cache[n] = registered_jit(
                multi, name="train::run_steps", component="train",
                steps=n, donate_argnums=(0,),
                compiler_options=_compiler_options())
        self._check_batch(batch)
        bs = self.batch_sharding()
        batch = jax.tree.map(lambda x: jax.device_put(x, bs), batch)
        import time as _time

        t0 = _time.perf_counter()
        with jax.set_mesh(self.mesh):
            self.state, metrics = self._multi_step_cache[n](self.state, batch)
        if fresh:
            # a fresh scanned program's first call is a compile event
            # (timing includes its first execution — dispatch is async so
            # compile dominates); telemetry must never break the step
            try:
                from ray_tpu.train import telemetry

                telemetry.record_compile(_time.perf_counter() - t0)
            except Exception:
                pass
        return metrics
