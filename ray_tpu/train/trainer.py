"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer.

Role analog: ``python/ray/train/base_trainer.py:111`` (``fit :567``) and
``data_parallel_trainer.py:25``. The reference routes every ``fit`` through
a 1-trial Tune run; here the training loop drives the BackendExecutor
directly and the Tune integration wraps the same ``_run`` body via
``as_trainable`` (so ``Tuner(JaxTrainer(...))`` works identically).

TPU-native difference: ``JaxTrainer`` is the flagship (the reference's
``TorchTrainer`` analog) — workers are host actors; inside the loop the user
builds a mesh (``scaling_config.mesh``) and runs a pjit-compiled step;
gradient sync is XLA collectives over ICI, invisible to the framework, while
the reference wires torch DDP explicitly (``train/torch/config.py:150``).
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    WorkerDeathError,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)


class TrainingFailedError(RuntimeError):
    pass


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap as a Tune trainable class (reference
        ``base_trainer.py:693 _generate_trainable_cls``)."""
        from ray_tpu.tune.trainable import wrap_function

        trainer = self

        def _trainable(config: Dict[str, Any]):
            import ray_tpu.tune as tune

            merged = trainer._merged_loop_config()
            merged.update(config.get("train_loop_config", config))
            for metrics, ckpt in trainer._iter_results(merged):
                tune.report(metrics, checkpoint=ckpt)

        return wrap_function(_trainable)

    def _merged_loop_config(self) -> Dict[str, Any]:
        return {}


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker`` on every worker of the group."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(scaling_config=scaling_config, run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets or {}

    def _merged_loop_config(self) -> Dict[str, Any]:
        return dict(self.train_loop_config)

    # -- experiment dirs --------------------------------------------------

    def _trial_dir(self) -> Tuple[str, str]:
        name = self.run_config.name or f"JaxTrainer_{uuid.uuid4().hex[:8]}"
        exp_dir = os.path.join(self.run_config.resolved_storage_path(), name)
        trial_dir = os.path.join(exp_dir, f"trial_{uuid.uuid4().hex[:8]}")
        os.makedirs(trial_dir, exist_ok=True)
        return name, trial_dir

    # -- fit --------------------------------------------------------------

    def fit(self) -> Result:
        name, trial_dir = self._trial_dir()
        failure_cfg = self.run_config.failure_config
        attempts = failure_cfg.max_failures + 1
        last_error: Optional[BaseException] = None
        start_ckpt = (self.resume_from_checkpoint.path
                      if self.resume_from_checkpoint else None)

        for attempt in range(max(attempts, 1)):
            executor = BackendExecutor(self.backend_config, self.scaling_config)
            try:
                executor.start()
                result = self._training_run(executor, name, trial_dir,
                                            start_ckpt)
                executor.shutdown()
                return result
            except BaseException as e:  # noqa: BLE001
                last_error = e
                executor.shutdown()
                # resume the retry from the latest persisted checkpoint
                latest = _latest_checkpoint(
                    trial_dir, self.scaling_config.num_workers)
                if latest:
                    start_ckpt = latest
                    try:
                        from ray_tpu.util import events

                        events.emit("checkpoint_resume", trial=name,
                                    checkpoint=latest, attempt=attempt + 1,
                                    error=type(e).__name__)
                    except Exception:
                        pass
        raise TrainingFailedError(
            f"training failed after {attempts} attempt(s)") from last_error

    def _training_run(self, executor: BackendExecutor, name: str,
                      trial_dir: str,
                      start_ckpt: Optional[str]) -> Result:
        executor.start_training(
            self.train_loop_per_worker,
            loop_config=self._merged_loop_config(),
            trial_dir=trial_dir,
            experiment_name=name,
            checkpoint_path=start_ckpt,
            datasets=self.datasets,
        )
        progress_path = os.path.join(trial_dir, "progress.jsonl")
        last_metrics: Dict[str, Any] = {}
        checkpoints: List[Tuple[Dict[str, Any], str]] = []
        elastic = self.scaling_config.elastic
        reform_attempts = \
            self.run_config.failure_config.elastic_reform_attempts
        with open(progress_path, "a") as progress:
            while True:
                try:
                    results = executor.get_next_results()
                except WorkerDeathError as e:
                    if not elastic:
                        raise
                    # elastic shrink: preemption is weather, not a
                    # failure — fence, re-form at the largest placeable
                    # size, resume from the last all-ranks-ok checkpoint
                    # WITHOUT burning a max_failures attempt. If the
                    # floor can't hold (ElasticWorldSizeError) this
                    # raises into fit()'s group-restart fallback.
                    latest = _latest_checkpoint(
                        trial_dir, self.scaling_config.num_workers) \
                        or start_ckpt
                    executor.reform(latest, reason="shrink",
                                    attempts=reform_attempts)
                    continue
                if results is None:
                    break
                rank0_metrics, _ = results[0]
                ckpt_dir = next((c for _, c in results if c), None)
                last_metrics = dict(rank0_metrics)
                last_metrics.setdefault("_timestamp", time.time())
                progress.write(json.dumps(last_metrics, default=str) + "\n")
                progress.flush()
                if ckpt_dir:
                    checkpoints.append((last_metrics, ckpt_dir))
                    self._prune_checkpoints(checkpoints)
                    if elastic:
                        # scale-back-up at the epoch boundary: an
                        # all-ranks-ok checkpoint just landed, so this is
                        # the exact point a bigger gang can resume from
                        executor.maybe_expand(ckpt_dir,
                                              attempts=reform_attempts)
        executor.finish_training()
        best = checkpoints[-1][1] if checkpoints else None
        return Result(
            metrics=last_metrics,
            checkpoint=Checkpoint(best) if best else None,
            path=trial_dir,
        )

    def _iter_results(self, loop_config: Dict[str, Any]):
        """Generator used by the Tune trainable wrapper."""
        name, trial_dir = self._trial_dir()
        executor = BackendExecutor(self.backend_config, self.scaling_config)
        executor.start()
        try:
            executor.start_training(
                self.train_loop_per_worker, loop_config=loop_config,
                trial_dir=trial_dir, experiment_name=name,
                checkpoint_path=(self.resume_from_checkpoint.path
                                 if self.resume_from_checkpoint else None),
                datasets=self.datasets,
            )
            while True:
                results = executor.get_next_results()
                if results is None:
                    break
                metrics, _ = results[0]
                ckpt_dir = next((c for _, c in results if c), None)
                yield metrics, (Checkpoint(ckpt_dir) if ckpt_dir else None)
            executor.finish_training()
        finally:
            executor.shutdown()

    def _prune_checkpoints(
            self, checkpoints: List[Tuple[Dict[str, Any], str]]) -> None:
        cfg: CheckpointConfig = self.run_config.checkpoint_config
        if not cfg.num_to_keep or len(checkpoints) <= cfg.num_to_keep:
            return
        if cfg.checkpoint_score_attribute:
            sign = 1 if cfg.checkpoint_score_order == "max" else -1
            checkpoints.sort(
                key=lambda mc: sign * float(
                    mc[0].get(cfg.checkpoint_score_attribute, float("-inf"))))
            doomed = checkpoints[:-cfg.num_to_keep]
            keep = checkpoints[-cfg.num_to_keep:]
        else:
            doomed = checkpoints[:-cfg.num_to_keep]
            keep = checkpoints[-cfg.num_to_keep:]
        for _, path in doomed:
            shutil.rmtree(path, ignore_errors=True)
        checkpoints[:] = keep


class JaxTrainer(DataParallelTrainer):
    """The flagship TPU trainer (TorchTrainer analog)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional[JaxConfig] = None, **kwargs):
        scaling = kwargs.get("scaling_config") or ScalingConfig()
        backend = jax_config or JaxConfig(mesh=scaling.mesh)
        super().__init__(train_loop_per_worker,
                         backend_config=backend, **kwargs)


def _is_torn_save_dir(path: str) -> bool:
    """A rank dir holding pytree payload files without their
    ``.metadata.json`` completeness marker (or crash-atomic ``.tmp-``
    litter) was killed mid-save — resuming from it would load a torn
    state. Non-pytree checkpoints (user-managed files) carry no marker
    contract and are accepted as-is."""
    try:
        entries = os.listdir(path)
    except OSError:
        return True
    if any(e.startswith(".tmp-") for e in entries):
        return True
    has_pytree = any(e.endswith("_pytree.npz")
                     or e.endswith("_pytree_struct.pkl") for e in entries)
    return has_pytree and ".metadata.json" not in entries


def _latest_checkpoint(trial_dir: str,
                       world_size: int = 1) -> Optional[str]:
    """Newest checkpoint that every rank finished persisting. A gang that
    died mid-persist (chaos: worker SIGKILL during report) leaves a torn
    checkpoint_N — rank dirs missing, partial, or (worst) fully copied
    but unverifiable — so resume accepts ONLY checkpoints carrying every
    rank's ``.rank_R.ok`` marker (written by session.report after the
    copy). Rank-dir presence alone proves nothing: the kill can land
    after the copies and before the first marker.

    Elastic runs change world size between checkpoints, so completeness
    is judged against the ``.world_size`` stamp each checkpoint carries
    (falling back to the caller's ``world_size`` for pre-elastic dirs).
    Rank dirs that LOOK complete but were killed mid ``save_pytree``
    (payload files without the ``.metadata.json`` marker, or temp
    litter) are skipped too — see :func:`_is_torn_save_dir`."""
    if not os.path.isdir(trial_dir):
        return None
    for name in sorted((d for d in os.listdir(trial_dir)
                        if d.startswith("checkpoint_")), reverse=True):
        path = os.path.join(trial_dir, name)
        try:
            entries = os.listdir(path)
        except OSError:
            continue
        ws = max(world_size, 1)
        if ".world_size" in entries:
            try:
                with open(os.path.join(path, ".world_size")) as f:
                    ws = max(int(f.read().strip()), 1)
            except (OSError, ValueError):
                continue  # unreadable stamp: do not trust the dir
        if not all(f".rank_{r}.ok" in entries for r in range(ws)):
            continue
        if any(_is_torn_save_dir(os.path.join(path, d))
               for d in entries if d.startswith("rank_")
               and os.path.isdir(os.path.join(path, d))):
            continue
        return path
    return None
