"""Train-step telemetry: step time / tokens-per-s / MFU / compile events
/ HBM gauges as first-class metrics.

Green-field relative to the reference (Ray sees only user-reported dicts;
SURVEY §3.4): Podracer-style TPU stacks (arXiv:2104.06272) live and die by
step-time/MFU telemetry, so ray_tpu owns a canonical step-metrics hook.
Everything lands in the process-local metrics registry
(:mod:`ray_tpu.util.metrics`), which federates to the head ``/metrics``
endpoint like any other process's samples — a training run is
Prometheus-observable with zero user wiring.

Wired in three places:
- ``ray_tpu.train.report(...)`` (the user loop's once-per-step barrier)
  feeds :func:`on_report` — inter-report wall time becomes the step time,
  and well-known keys (``tokens_per_s``/``tokens``/``mfu``/``loss``) are
  forwarded when present;
- ``TrainLoopHelper.run_steps`` records compile events (a fresh scanned
  program's first call);
- ``bench.py`` records its measured step time / tokens/s / MFU, so the
  perf trajectory is self-reporting.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional


class StepTelemetry:
    """Records per-step training telemetry into the metrics registry.

    Thread-safe; metrics are created lazily on first record so importing
    this module costs nothing. ``snapshot()`` returns the last recorded
    values (bench embeds it in its JSON output)."""

    _HBM_SAMPLE_EVERY = 10  # device memory_stats() is a backend query

    def __init__(self, component: str = "train"):
        self.component = component
        self._lock = threading.Lock()
        self._m: Optional[Dict[str, Any]] = None
        self._last: Dict[str, Any] = {}
        self._steps = 0
        self._last_report_t: Optional[float] = None
        #: spec-sheet peak override (FLOP/s across attached devices) —
        #: lets MFU attribution run off-TPU (parity tests, CPU rehearsal)
        self.peak_flops: Optional[float] = None

    def _metrics(self) -> Dict[str, Any]:
        if self._m is None:
            from ray_tpu.util import metric_defs as md

            self._m = {
                "step_time": md.get("rtpu_train_step_seconds"),
                "steps": md.get("rtpu_train_steps_total"),
                "tokens_per_s": md.get("rtpu_train_tokens_per_s"),
                "mfu": md.get("rtpu_train_mfu"),
                "loss": md.get("rtpu_train_loss"),
                "compiles": md.get("rtpu_train_compile_total"),
                "compile_time": md.get("rtpu_train_compile_seconds"),
                "hbm_used": md.get("rtpu_tpu_hbm_used_bytes"),
                "hbm_limit": md.get("rtpu_tpu_hbm_limit_bytes"),
            }
        return self._m

    # -- recording -------------------------------------------------------

    def record_step(self, step_time_s: float, *, tokens: Optional[float] = None,
                    flops: Optional[float] = None,
                    mfu: Optional[float] = None,
                    loss: Optional[float] = None, steps: int = 1,
                    program: Optional[str] = None) -> None:
        """Record ``steps`` optimizer steps that took ``step_time_s`` each.

        ``tokens``: tokens consumed per step (tokens/s is derived).
        ``mfu``: measured utilization; when absent but ``flops`` (FLOPs
        per step) is given and a TPU is attached (or ``peak_flops`` is
        set), it is computed against the chip's spec-sheet peak.
        ``program``: a device-plane registry name — when given and
        ``flops`` is absent, per-step FLOPs come from the registered
        program's static cost analysis (cost-model-driven attribution;
        util/device_plane.py) instead of a hand-maintained formula."""
        try:
            if flops is None and program is not None:
                from ray_tpu.util import device_plane

                flops = device_plane.program_flops_per_step(program)
            m = self._metrics()
            with self._lock:
                for _ in range(max(1, int(steps))):
                    m["step_time"].observe(step_time_s)
                m["steps"].inc(max(1, int(steps)))
                self._steps += max(1, int(steps))
                self._last["step_time_s"] = step_time_s
                if tokens is not None and step_time_s > 0:
                    tps = tokens / step_time_s
                    m["tokens_per_s"].set(tps)
                    self._last["tokens_per_s"] = round(tps, 1)
                if flops is not None and step_time_s > 0:
                    self._set_achieved_flops(flops / step_time_s, program)
                if mfu is None and flops is not None and step_time_s > 0:
                    mfu = self._mfu_from_flops(flops, step_time_s)
                if mfu is not None:
                    m["mfu"].set(float(mfu))
                    self._last["mfu"] = round(float(mfu), 4)
                if loss is not None:
                    m["loss"].set(float(loss))
                    self._last["loss"] = float(loss)
                sample_hbm = self._steps % self._HBM_SAMPLE_EVERY in (0, 1)
            if sample_hbm:
                self.sample_hbm()
            # trace plane: the step also lands as a span, so TPU step
            # telemetry joins the driver's unified Perfetto timeline
            from ray_tpu.util import tracing

            if tracing.tracing_enabled():
                end = time.time_ns()
                attrs: Dict[str, Any] = {"steps": max(1, int(steps))}
                if tokens is not None:
                    attrs["tokens"] = float(tokens)
                if mfu is not None:
                    attrs["mfu"] = float(mfu)
                tracing.record_span(
                    "train::step",
                    end - int(step_time_s * max(1, int(steps)) * 1e9),
                    end, attrs)
        except Exception:
            pass  # telemetry must never fail a train step

    def _mfu_from_flops(self, flops: float,
                        step_time_s: float) -> Optional[float]:
        try:
            peak = self.peak_flops
            if peak is None:
                import jax

                from ray_tpu.util.tpu_info import (is_tpu_backend,
                                                   peak_flops_per_chip)

                if not is_tpu_backend():
                    return None
                peak = peak_flops_per_chip() * jax.device_count()
            return flops / (step_time_s * peak) if peak else None
        except Exception:
            return None

    def _set_achieved_flops(self, flops_per_s: float,
                            program: Optional[str]) -> None:
        try:
            from ray_tpu.util import metric_defs as md

            md.get("rtpu_device_achieved_flops_per_s").set(
                flops_per_s,
                tags={"program": program or self.component})
            self._last["flops_per_s"] = round(flops_per_s, 1)
        except Exception:
            pass

    def record_compile(self, seconds: float) -> None:
        try:
            m = self._metrics()
            m["compiles"].inc()
            m["compile_time"].observe(seconds)
            with self._lock:
                self._last["compiles"] = (self._last.get("compiles", 0) + 1)
                self._last["last_compile_s"] = round(seconds, 3)
            from ray_tpu.util import tracing

            if tracing.tracing_enabled():
                end = time.time_ns()
                tracing.record_span("train::compile",
                                    end - int(seconds * 1e9), end)
        except Exception:
            pass

    def sample_hbm(self) -> Optional[Dict[str, int]]:
        """Refresh the HBM gauges from the attached devices (no-op off
        TPU). Returns the sample when available."""
        try:
            from ray_tpu.util.tpu_info import hbm_usage

            usage = hbm_usage()
            if usage is None:
                return None
            m = self._metrics()
            m["hbm_used"].set(usage["bytes_in_use"])
            m["hbm_limit"].set(usage["bytes_limit"])
            with self._lock:
                self._last["hbm"] = dict(usage)
            return usage
        except Exception:
            return None

    def on_report(self, metrics: Dict[str, Any]) -> None:
        """Hook for ``ray_tpu.train.report``: each report is one user-loop
        step; inter-report wall time is the step time. Known metric keys
        are forwarded; everything else stays the user's business."""
        now = time.monotonic()
        with self._lock:
            last = self._last_report_t
            self._last_report_t = now
        if last is None:
            return  # first report: no interval yet
        kw: Dict[str, Any] = {}
        for key in ("tokens_per_s", "tokens", "mfu", "loss"):
            v = metrics.get(key)
            if isinstance(v, (int, float)):
                kw[key] = float(v)
        tps = kw.pop("tokens_per_s", None)
        dt = max(1e-9, now - last)
        if tps is not None and "tokens" not in kw:
            kw["tokens"] = tps * dt
        self.record_step(dt, **kw)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"steps": self._steps, **self._last}


_default = StepTelemetry()


def get_step_telemetry() -> StepTelemetry:
    return _default


def record_step(step_time_s: float, **kwargs) -> None:
    _default.record_step(step_time_s, **kwargs)


def record_compile(seconds: float) -> None:
    _default.record_compile(seconds)


def sample_hbm():
    return _default.sample_hbm()


def on_report(metrics: Dict[str, Any]) -> None:
    _default.on_report(metrics)


def snapshot() -> Dict[str, Any]:
    return _default.snapshot()
