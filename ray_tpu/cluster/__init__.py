"""Multi-node runtime: GCS server process, node daemons, object transfer.

Role analog: the reference's distributed core — GCS server
(``src/ray/gcs/gcs_server/gcs_server.cc:307-692``), per-node raylet
(``src/ray/raylet/node_manager.h:119``), node-to-node object transfer
(``src/ray/object_manager/object_manager.h``) and resource gossip
(``src/ray/common/ray_syncer/ray_syncer.h:88``). The design here keeps the
single-node runtime (``ray_tpu/core/runtime.py``) as the per-node execution
engine: every node daemon embeds one, and a thin cluster adapter routes
cross-node concerns (object directory, KV, named actors, task spillback,
object pulls) through the GCS process.

Processes:

- ``python -m ray_tpu.cluster.gcs_server`` — control plane: node table +
  heartbeat health checks, global object directory (status + locations),
  KV, function table, named actors, pubsub-lite.
- ``python -m ray_tpu.cluster.node_daemon`` — per-node daemon: embeds a
  ``DriverRuntime`` (worker pool + local scheduler + local store) wired to
  the GCS, serves task submissions and object pulls from peers.
- the user driver — also a node (the head pattern): its runtime registers
  with the GCS and schedules locally first, spilling tasks to peer nodes
  by resource feasibility.

Tests boot extra node daemons as local subprocesses
(:class:`ray_tpu.cluster.cluster_utils.Cluster`), the reference's
``python/ray/cluster_utils.py:135`` pattern.
"""

from ray_tpu.cluster.cluster_utils import Cluster

__all__ = ["Cluster"]
