"""Pluggable storage for the GCS's durable tables.

Role analog: ``src/ray/gcs/store_client/`` — the reference backs every
GCS table by a StoreClient (in-memory, or Redis for fault tolerance,
``redis_store_client.h``). Here the seam is the same, sized to our
snapshot model: the GCS persists its DURABLE tables (kv, functions,
actors, named_actors, pgs) through a ``StoreClient``; runtime state
(nodes, objects) deliberately re-populates from heartbeats and owner
publishes after a restart.

Backends:

- :class:`FileStoreClient` — one pickle file, atomic rename (the
  original behavior; head-node disk only).
- :class:`SqliteStoreClient` — per-table rows in a sqlite database in
  WAL mode, one transaction per save. Point it at storage that survives
  head-node disk loss (a persistent/attached block volume — NOT an NFS/
  SMB mount: WAL's shm-based locking is incoherent over network
  filesystems) and a fresh GCS recovers the control plane; this is the
  redis-store role without requiring a redis server in the image.

URIs (``make_store_client``): a bare path is the file backend;
``sqlite://<path>`` is the sqlite backend.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

DURABLE_TABLES = ("kv", "functions", "actors", "named_actors", "pgs")


class StoreClient:
    """Load/save the durable-table snapshot dict."""

    def load(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def save(self, snap: Dict[str, Any]) -> bool:
        """Persist; returns False on a (transient) failure so the caller
        can re-mark its dirty flag — a swallowed error would silently
        lose the final snapshot forever."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileStoreClient(StoreClient):
    """Atomic-rename pickle file (original snapshot behavior)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except (FileNotFoundError, EOFError, pickle.PickleError):
            return None

    def save(self, snap: Dict[str, Any]) -> bool:
        tmp = f"{self.path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(snap, f)
            os.rename(tmp, self.path)
            return True
        except OSError:
            return False


class SqliteStoreClient(StoreClient):
    """Durable tables as rows in a sqlite DB (external-store GCS FT).

    One row per table, written in one transaction per save; WAL mode so
    a reader (a restarted GCS) never blocks on a writer killed
    mid-transaction. Unchanged tables are skipped via a content hash, so
    steady-state saves touch only what moved.
    """

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self.path = path
        try:
            self._conn = self._open(path)
        except Exception:
            # A corrupt/truncated db must not keep the GCS from booting
            # (the file backend boots empty on a bad snapshot). Preserve
            # the evidence and start fresh.
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            self._conn = self._open(path)
        self._hashes: Dict[str, bytes] = {}

    @staticmethod
    def _open(path: str):
        import sqlite3

        conn = sqlite3.connect(path, timeout=5.0, check_same_thread=False)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute(
            "CREATE TABLE IF NOT EXISTS gcs_tables ("
            "name TEXT PRIMARY KEY, payload BLOB)")
        conn.commit()
        return conn

    def load(self) -> Optional[Dict[str, Any]]:
        import hashlib

        try:
            rows = self._conn.execute(
                "SELECT name, payload FROM gcs_tables").fetchall()
        except Exception:
            return None
        if not rows:
            return None
        snap: Dict[str, Any] = {}
        for name, payload in rows:
            try:
                snap[name] = pickle.loads(payload)
                self._hashes[name] = hashlib.sha1(payload).digest()
            except Exception:
                continue  # one corrupt table must not lose the rest
        return snap or None

    def save(self, snap: Dict[str, Any]) -> bool:
        import hashlib

        writes = []
        for name in DURABLE_TABLES:
            if name not in snap:
                continue
            payload = pickle.dumps(snap[name])
            h = hashlib.sha1(payload).digest()
            if self._hashes.get(name) == h:
                continue
            writes.append((name, payload, h))
        if not writes:
            return True
        try:
            with self._conn:  # one transaction: all-or-nothing
                self._conn.executemany(
                    "INSERT INTO gcs_tables(name, payload) VALUES(?, ?) "
                    "ON CONFLICT(name) DO UPDATE SET payload=excluded.payload",
                    [(n, p) for n, p, _ in writes])
        except Exception:
            return False  # caller re-marks dirty and retries next tick
        for name, _, h in writes:
            self._hashes[name] = h
        return True

    def close(self) -> None:
        try:
            self._conn.close()
        except Exception:
            pass


def make_store_client(uri: Optional[str]) -> Optional[StoreClient]:
    """``None`` -> no persistence; ``sqlite://<path>`` -> sqlite backend;
    anything else -> file backend at that path."""
    if not uri:
        return None
    if uri.startswith("sqlite://"):
        return SqliteStoreClient(uri[len("sqlite://"):])
    return FileStoreClient(uri)
