"""GCS server process: cluster control plane.

Role analog: ``src/ray/gcs/gcs_server/gcs_server.cc:307-692`` — node table
with heartbeat health checks (``GcsHealthCheckManager``), global object
directory with locations (``ownership_based_object_directory.h`` role),
InternalKV (``gcs_kv_manager.h``), function table
(``gcs_function_manager.h``), named-actor registry
(``gcs_actor_manager.h``), and pubsub (``src/ray/pubsub``) collapsed into
one threaded process over the message-RPC layer.

State is deliberately coarse: per-node execution detail (worker pools,
actor call queues) lives in the node daemons; the GCS holds only what must
be globally consistent.
"""

from __future__ import annotations

import argparse
import threading
import time
from typing import Any, Dict, Optional, Set

from ray_tpu import config
from ray_tpu.cluster.rpc import RpcServer, ServerConn

DEFAULT_HEARTBEAT_S = 1.0
DEFAULT_NODE_TIMEOUT_S = 5.0

PENDING, READY, ERROR = "PENDING", "READY", "ERROR"


class _GlobalObject:
    __slots__ = ("status", "inline", "error", "size", "locations",
                 "pins", "was_pinned", "t_terminal")

    def __init__(self):
        self.status = PENDING
        self.inline: Optional[bytes] = None
        self.error: Optional[bytes] = None
        self.size = 0
        self.locations: Set[bytes] = set()  # node ids holding the segment
        # distributed refcount (reference reference_count.h:61 role):
        # nodes with >=1 live reference. Pinned entries are never evicted;
        # when the LAST pin drops on a terminal object that was ever
        # pinned, holders are told to free their segments.
        self.pins: Set[bytes] = set()
        self.was_pinned = False
        self.t_terminal = 0.0


class _NodeEntry:
    __slots__ = ("node_id", "addr", "resources", "avail", "last_seen",
                 "alive", "is_head", "labels", "stats")

    def __init__(self, node_id: bytes, addr: str, resources: Dict[str, float],
                 is_head: bool, labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.addr = addr  # node daemon RPC address ("" for the driver/head)
        self.resources = dict(resources)
        self.avail = dict(resources)
        self.last_seen = time.monotonic()
        self.alive = True
        self.is_head = is_head
        # static key=value node labels (reference NodeLabels): TPU
        # generation / slice type / user labels, set at node start
        self.labels = dict(labels or {})
        # latest host utilization sample from the heartbeat (reporter role)
        self.stats: Dict = {}


class GcsService:
    def __init__(self, node_timeout_s: float = DEFAULT_NODE_TIMEOUT_S,
                 snapshot_path: Optional[str] = None):
        import os

        from ray_tpu.util.contention import timed_rlock

        # one coarse state lock — instrumented, because every RPC handler
        # serializes on it (the "is the GCS the bottleneck?" question is
        # answered by this lock's wait histogram)
        self.lock = timed_rlock("gcs.state")
        # built-in GCS metrics (defs in util/metric_defs.py; exported to
        # the head /metrics by rpc_metrics_get with component=gcs labels)
        from ray_tpu.util import metric_defs as _md

        self._m_rpc = _md.get("rtpu_gcs_rpc_total")
        self._m_rpc_lat = _md.get("rtpu_gcs_rpc_seconds")
        self._m_pubsub = _md.get("rtpu_gcs_pubsub_messages_total")
        self._m_tables = _md.get("rtpu_gcs_table_size")
        self._m_alive = _md.get("rtpu_gcs_nodes_alive")
        self._m_hb_gap = _md.get("rtpu_gcs_heartbeat_gap_seconds")
        self._method_keys: Dict[str, tuple] = {}
        self._channel_keys: Dict[str, tuple] = {}
        self.nodes: Dict[bytes, _NodeEntry] = {}
        self.objects: Dict[bytes, _GlobalObject] = {}
        self.max_objects = int(config.get("gcs_max_objects"))
        self.evict_min_age_s = float(config.get("gcs_evict_min_age_s"))
        # refcount-zero objects are freed after a GRACE, not inline: a
        # consumer's pin cast rides a different connection than the
        # producer's obj_ready, so "no pins right now" can be an in-flight
        # pin (freeing inline deleted entries a consumer was about to
        # watch, hanging its get forever)
        self.free_grace_s = float(config.get("gcs_free_grace_s"))
        self._free_candidates: Dict[bytes, float] = {}
        # oids swept by the free path: a late pin on one of these gets a
        # terminal ObjectLostError entry instead of a silent empty PENDING
        self._freed_tombstones: Dict[bytes, float] = {}
        # cluster-wide task events (reference GcsTaskManager store)
        from collections import deque

        self.task_events = deque(maxlen=int(config.get("gcs_max_task_events")))
        # per-node high-water mark of received task-event sequence numbers
        # (dedup for cursor rewinds after node re-registration)
        self._task_ev_seq: Dict[bytes, int] = {}
        # trace plane: collected spans shipped on node heartbeats (same
        # cursor+dedup contract as task_events); head /api/traces and
        # state.list_spans pull via rpc_trace_events_get
        self.trace_events = deque(
            maxlen=int(config.get("gcs_max_trace_events")))
        self._trace_ev_seq: Dict[bytes, int] = {}
        # profiling plane: profile batches shipped on node heartbeats
        # (same cursor+dedup contract); head state.profile() pulls via
        # rpc_profile_events_get. Stack-dump request/reply rendezvous for
        # the cluster-wide `ray_tpu stack` (py-spy role).
        self.profile_events = deque(
            maxlen=int(config.get("gcs_max_profile_events")))
        self._profile_ev_seq: Dict[bytes, int] = {}
        self._stack_req_seq = 0
        self._stack_replies: Dict[int, Dict[str, Any]] = {}
        # event plane: lifecycle events shipped on node heartbeats (same
        # cursor+dedup contract); the GCS appends its OWN node-lifecycle
        # events (register / death) here directly. Log-fetch rendezvous
        # for `rtpu logs` mirrors the stack-dump rendezvous above.
        self.lifecycle_events = deque(
            maxlen=int(config.get("gcs_max_lifecycle_events")))
        self._lifecycle_ev_seq: Dict[bytes, int] = {}
        self._log_req_seq = 0
        self._log_replies: Dict[int, Dict[str, Any]] = {}
        # metrics federation: latest [(origin_labels, records)] payload per
        # node, replaced wholesale on each carrying heartbeat (idempotent;
        # reference metrics-agent -> head pipeline role). Head /metrics
        # pulls via rpc_metrics_get at scrape time.
        self._node_metrics: Dict[bytes, list] = {}
        # device plane: latest process-entry list per node (compiled-
        # program registries + HBM census), replaced on each heartbeat
        # ride like _node_metrics — idempotent, self-healing
        self._node_devices: Dict[bytes, list] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}
        self.functions: Dict[str, bytes] = {}
        # named/global actor registry: actor_id -> record dict
        self.actors: Dict[bytes, Dict[str, Any]] = {}
        self.named_actors: Dict[str, bytes] = {}
        # placement groups (reference GcsPlacementGroupManager): pg_id ->
        # {"bundles": [res dicts], "strategy", "assignments": [node_id or
        # None per bundle], "creator": node_id}. The GCS records placement
        # decisions; the 2-phase reservation itself runs creator->daemons.
        self.pgs: Dict[bytes, Dict[str, Any]] = {}
        self.node_timeout_s = node_timeout_s
        self.server: Optional[RpcServer] = None
        self._stop = threading.Event()
        # Fault tolerance (reference: GCS tables over a Redis StoreClient,
        # gcs/store_client/redis_store_client.h): durable tables persist
        # through a pluggable StoreClient (gcs_store.py) — a file snapshot
        # by default, or an EXTERNAL sqlite database ("sqlite://<path>")
        # that survives head-node disk loss. A restarted GCS reloads them,
        # nodes re-register via heartbeat NACK, and the directory
        # repopulates as owners publish. objects/nodes are runtime state
        # and deliberately NOT persisted.
        from ray_tpu.cluster.gcs_store import make_store_client

        self.snapshot_path = snapshot_path
        self._store = make_store_client(snapshot_path)
        self._dirty = False
        if self._store is not None:
            self._load_snapshot()
            threading.Thread(target=self._snapshot_loop, daemon=True,
                             name="gcs-snapshot").start()

    def _load_snapshot(self):
        snap = self._store.load()
        if not snap:
            return
        self.kv = snap.get("kv", {})
        self.functions = snap.get("functions", {})
        self.actors = snap.get("actors", {})
        self.named_actors = snap.get("named_actors", {})
        self.pgs = snap.get("pgs", {})

    def _snapshot_loop(self):
        while not self._stop.wait(1.0):
            with self.lock:
                if not self._dirty:
                    continue
                snap = {"kv": {ns: dict(d) for ns, d in self.kv.items()},
                        "functions": dict(self.functions),
                        "actors": {a: dict(r)
                                   for a, r in self.actors.items()},
                        "named_actors": dict(self.named_actors),
                        "pgs": {p: dict(r) for p, r in self.pgs.items()}}
                self._dirty = False
            if not self._store.save(snap):
                # transient store failure (lock/IO): the snapshot was NOT
                # persisted — re-arm so the next tick retries even if no
                # new mutation arrives
                with self.lock:
                    self._dirty = True

    # ------------------------------------------------------------------
    # RPC dispatch
    # ------------------------------------------------------------------

    def handle(self, method: str, args: tuple, ctx: ServerConn) -> Any:
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise AttributeError(f"gcs: unknown method {method!r}")
        # per-method RPC count + latency (reference metric_defs.cc GCS
        # rpc metrics role); cached pre-sorted keys keep this at two
        # metric-lock hops per call
        keys = self._method_keys
        key = keys.get(method) or keys.setdefault(
            method, (("method", method),))
        t0 = time.perf_counter()
        try:
            return fn(ctx, *args)
        finally:
            self._m_rpc._inc_key(key)
            self._m_rpc_lat._observe_key(key, time.perf_counter() - t0)

    # -- nodes ----------------------------------------------------------

    def rpc_node_register(self, ctx, node_id: bytes, addr: str,
                          resources: Dict[str, float], is_head: bool,
                          labels: Optional[Dict[str, str]] = None):
        with self.lock:
            # returned to the caller: False = this GCS had no entry for
            # the node (fresh process after a restart — dead entries are
            # kept with alive=False, so a blackout re-register stays
            # True). A re-registering daemon uses it to detect GCS state
            # loss (the gcs_restart lifecycle event).
            known = node_id in self.nodes
            self.nodes[node_id] = _NodeEntry(node_id, addr, resources,
                                             is_head, labels)
        ctx.meta["node_id"] = node_id
        ctx.on_close = self._conn_closed
        self._publish("nodes", {"event": "up", "node_id": node_id,
                                "addr": addr, "resources": dict(resources),
                                "labels": dict(labels or {})})
        try:
            from ray_tpu.util import events as _events

            self._append_lifecycle(_events.record(
                "node_register", node_id=node_id.hex()[:8], addr=addr,
                is_head=bool(is_head), component="gcs"))
        except Exception:
            pass
        return known

    def rpc_node_heartbeat(self, ctx, node_id: bytes,
                           avail: Dict[str, float], queue_depth: int,
                           stats: Optional[Dict] = None,
                           metrics: Optional[list] = None):
        with self.lock:
            ent = self.nodes.get(node_id)
            if ent is None:
                return False
            # inter-heartbeat gap (nominal 0.5s): the cheapest cluster-
            # wide contention canary — a loaded sender or GCS stretches it
            self._m_hb_gap._observe_key(
                (), time.monotonic() - ent.last_seen)
            if metrics is not None:
                self._node_metrics[node_id] = metrics
            changed = ent.avail != avail
            ent.avail = dict(avail)
            if stats:
                # host utilization sample (reporter-module role) — rides
                # the heartbeat, surfaces via node_list/dashboard. The
                # timestamp lets readers spot a dead reporter (a node
                # whose sampling fails keeps heartbeating with stats
                # None, so ts stops advancing).
                ent.stats = dict(stats, ts=time.time())
            ent.last_seen = time.monotonic()
            if not ent.alive:
                ent.alive = True
        if changed:
            # streaming resource gossip (reference ray_syncer,
            # ray_syncer.h:88 role): subscribers patch their node views
            # from these deltas instead of re-polling node_list
            self._publish("nodes", {"event": "resources",
                                    "node_id": node_id,
                                    "avail": dict(avail),
                                    "depth": queue_depth})
        return True

    def rpc_node_list(self, ctx):
        with self.lock:
            return [
                {"node_id": e.node_id, "addr": e.addr, "alive": e.alive,
                 "resources": dict(e.resources), "avail": dict(e.avail),
                 "is_head": e.is_head, "labels": dict(e.labels),
                 "stats": dict(e.stats)}
                for e in self.nodes.values()
            ]

    def rpc_node_drain(self, ctx, node_id: bytes):
        self._mark_node_dead(node_id, "drained")
        return True

    def _conn_closed(self, ctx: ServerConn):
        node_id = ctx.meta.get("node_id")
        if node_id is not None:
            self._mark_node_dead(node_id, "connection lost")

    def _mark_node_dead(self, node_id: bytes, cause: str):
        with self.lock:
            ent = self.nodes.get(node_id)
            if ent is None or not ent.alive:
                return
            ent.alive = False
            # stop serving the dead node's frozen metric samples (a
            # reconnecting node reships a full snapshot on its next
            # carrying heartbeat, so nothing is lost on a blip)
            self._node_metrics.pop(node_id, None)
            self._node_devices.pop(node_id, None)
            # _task_ev_seq is deliberately NOT popped here: a node marked
            # dead by a connection blip keeps its node_id, reconnects, and
            # reships history from seq 0 — the high-water mark is what
            # dedups that reshipment (advisor r3). Entries thus live as
            # long as the node record itself (self.nodes also keeps dead
            # entries), so growth is bounded by distinct nodes per cluster
            # lifetime, not leaked beyond it.
            # objects whose only copies lived there are lost
            lost = [oid for oid, o in self.objects.items()
                    if o.status == READY and o.inline is None
                    and o.locations and o.locations <= {node_id}]
            for oid in lost:
                o = self.objects[oid]
                o.status = PENDING
                o.locations.discard(node_id)
            # a dead node's references die with it; objects it alone kept
            # alive free (after the grace) on the surviving holders
            for oid, o in self.objects.items():
                if node_id in o.pins:
                    o.pins.discard(node_id)
                    self._mark_free_candidate_locked(oid, o)
            # actors hosted there are dead (restart is the owner's call)
            dead_actors = [aid for aid, rec in self.actors.items()
                           if rec.get("node_id") == node_id
                           and rec.get("state") != "DEAD"]
            for aid in dead_actors:
                self.actors[aid]["state"] = "DEAD"
                name = self.actors[aid].get("name")
                if name:
                    self.named_actors.pop(name, None)
            # bundles reserved there are released (reference
            # gcs_placement_group_scheduler node-death bundle release);
            # the creating adapter reschedules them on live nodes
            lost_pgs: Dict[bytes, list] = {}
            for pg_id, rec in self.pgs.items():
                idxs = [i for i, nid in enumerate(rec["assignments"])
                        if nid == node_id]
                if idxs:
                    for i in idxs:
                        rec["assignments"][i] = None
                    lost_pgs[pg_id] = idxs
                    self._dirty = True
        self._publish("nodes", {"event": "down", "node_id": node_id,
                                "cause": cause, "lost_objects": lost,
                                "dead_actors": dead_actors,
                                "lost_pgs": lost_pgs})
        try:
            from ray_tpu.util import events as _events

            # the node-death postmortem is the BLAST RADIUS — there is
            # no process left to read a stderr tail from, so the useful
            # forensics are what the cluster lost with the node
            self._append_lifecycle(_events.record(
                "node_death", node_id=node_id.hex()[:8], cause=cause,
                component="gcs",
                postmortem={"cause": cause,
                            "lost_objects": len(lost),
                            "dead_actors": len(dead_actors),
                            "lost_pg_bundles": sum(
                                len(v) for v in lost_pgs.values())}))
        except Exception:
            pass

    def _health_loop(self):
        while not self._stop.wait(DEFAULT_HEARTBEAT_S):
            now = time.monotonic()
            with self.lock:
                stale = [e.node_id for e in self.nodes.values()
                         if e.alive and not e.is_head
                         and now - e.last_seen > self.node_timeout_s]
            for node_id in stale:
                self._mark_node_dead(node_id, "heartbeat timeout")
            self._sweep_free_candidates()
            self._sample_table_sizes()

    def _sample_table_sizes(self):
        """Refresh the table-size gauges once per health tick (~1s) —
        operators read growth trends, not per-mutation precision."""
        try:
            with self.lock:
                sizes = {"objects": len(self.objects),
                         "nodes": len(self.nodes),
                         "actors": len(self.actors),
                         "kv": sum(len(d) for d in self.kv.values()),
                         "functions": len(self.functions),
                         "pgs": len(self.pgs),
                         "task_events": len(self.task_events),
                         "trace_events": len(self.trace_events),
                         "profile_events": len(self.profile_events),
                         "lifecycle_events": len(self.lifecycle_events),
                         "free_candidates": len(self._free_candidates),
                         "tombstones": len(self._freed_tombstones)}
                alive = sum(1 for e in self.nodes.values() if e.alive)
            for t, n in sizes.items():
                self._m_tables.set(n, tags={"table": t})
            self._m_alive.set(alive)
        except Exception:
            pass

    # -- object directory ----------------------------------------------

    def _obj_locked(self, oid: bytes) -> _GlobalObject:
        o = self.objects.get(oid)
        if o is None:
            o = _GlobalObject()
            self.objects[oid] = o
        return o

    def rpc_obj_ready(self, ctx, oid: bytes, inline: Optional[bytes],
                      node_id: Optional[bytes], size: int = 0):
        with self.lock:
            o = self._obj_locked(oid)
            if o.status == ERROR:
                return False
            o.status = READY
            o.inline = inline
            o.size = size
            o.t_terminal = time.monotonic()
            if node_id is not None and inline is None:
                o.locations.add(node_id)
            # every ref was already dropped while the task ran
            # (fire-and-forget): mark for freeing on the terminal
            # transition — unpin alone never re-checks a then-PENDING entry
            self._mark_free_candidate_locked(oid, o)
            self._maybe_evict_locked()
        # the broadcast is a NOTIFICATION, not a payload channel: inline
        # bytes stay on the server (interested adapters fetch via
        # obj_state), so completion traffic stays O(nodes), not
        # O(nodes x payload)
        self._publish("objects", {"oid": oid, "status": READY})
        return True

    def rpc_obj_error(self, ctx, oid: bytes, err: bytes):
        with self.lock:
            o = self._obj_locked(oid)
            o.status = ERROR
            o.error = err
            o.t_terminal = time.monotonic()
            self._mark_free_candidate_locked(oid, o)
            self._maybe_evict_locked()
        self._publish("objects", {"oid": oid, "status": ERROR})
        return True

    def _maybe_evict_locked(self):
        """Bound the directory: evict old TERMINAL entries past the cap —
        but NEVER one some node still references (pins) and never one that
        turned terminal within the age floor (a consumer may be between
        its subscribe and its pin; reference reference_count.h role)."""
        if len(self.objects) <= self.max_objects:
            return
        now = time.monotonic()
        drop = []
        for oid, o in self.objects.items():  # insertion order = oldest first
            if (o.status in (READY, ERROR) and not o.pins
                    and now - o.t_terminal >= self.evict_min_age_s):
                drop.append(oid)
                if len(self.objects) - len(drop) <= self.max_objects * 0.9:
                    break
        now2 = time.monotonic()
        for oid in drop:
            del self.objects[oid]
            # same tombstone as the free sweep: a late pin on an evicted
            # entry must surface ObjectLostError, not resurrect a silent
            # empty PENDING that hangs the pinner's get()
            self._record_tombstone_locked(oid, now2)

    def _record_tombstone_locked(self, oid: bytes, now: float) -> None:
        """Record a swept/evicted/freed oid (bounded map shared by all
        three removal paths); caller holds the lock."""
        self._freed_tombstones[oid] = now
        while len(self._freed_tombstones) > 20000:
            self._freed_tombstones.pop(next(iter(self._freed_tombstones)))

    def rpc_obj_pin(self, ctx, oid: bytes, node_id: bytes):
        lost = False
        with self.lock:
            if oid not in self.objects and oid in self._freed_tombstones:
                # late pin on a SWEPT object (advisor r3): silently
                # resurrecting an empty PENDING entry would hang the
                # pinner's get() forever. Recreate it terminal-with-error
                # so waiters surface ObjectLostError (or kick lineage
                # reconstruction) instead.
                import cloudpickle

                from ray_tpu.core.exceptions import ObjectLostError

                o = self._obj_locked(oid)
                o.status = ERROR
                o.error = cloudpickle.dumps(ObjectLostError(
                    f"object {oid.hex()[:16]} was freed (refcount reached "
                    f"zero) before this reference arrived"))
                o.t_terminal = time.monotonic()
                o.pins.add(node_id)
                o.was_pinned = True
                lost = True
            else:
                o = self._obj_locked(oid)
                o.pins.add(node_id)
                o.was_pinned = True
                self._free_candidates.pop(oid, None)
        if lost:
            # the ERROR publish is the pinner's signal (obj_pin arrives as
            # a fire-and-forget cast; a return value would go unseen)
            self._publish("objects", {"oid": oid, "status": ERROR})
        return True

    def rpc_obj_unpin(self, ctx, oid: bytes, node_id: bytes):
        with self.lock:
            o = self.objects.get(oid)
            if o is None:
                return False
            o.pins.discard(node_id)
            self._mark_free_candidate_locked(oid, o)
        return True

    def _mark_free_candidate_locked(self, oid: bytes, o: _GlobalObject):
        """Refcount hit zero on a terminal, previously-referenced object:
        queue it for freeing after the grace (see free_grace_s — an
        in-flight pin on another connection may still land)."""
        if o.pins or not o.was_pinned or o.status not in (READY, ERROR):
            return
        self._free_candidates.setdefault(oid, time.monotonic())

    def _sweep_free_candidates(self):
        """Free candidates whose grace elapsed with no pin arriving: drop
        the directory entry and tell holder nodes to free their segments
        (the reference's owner-driven object free)."""
        now = time.monotonic()
        freed = []
        with self.lock:
            for oid, t in list(self._free_candidates.items()):
                if now - t < self.free_grace_s:
                    continue
                del self._free_candidates[oid]
                o = self.objects.get(oid)
                if (o is None or o.pins or not o.was_pinned
                        or o.status not in (READY, ERROR)):
                    continue
                freed.append((oid, list(o.locations)))
                del self.objects[oid]
                # bounded tombstone: lets a LATE pin distinguish "swept"
                # from "not yet created" (advisor r3)
                self._record_tombstone_locked(oid, now)
        for oid, locations in freed:
            self._publish("objects", {"oid": oid, "freed": True,
                                      "locations": locations})

    def rpc_task_events(self, ctx, node_id: bytes, events, start_seq=None):
        """Batched task events from a node runtime (reference
        TaskEventBuffer -> GcsTaskManager pipeline,
        ``core_worker/task_event_buffer.h:206`` role): bounded store
        feeding the cluster-wide state API and timeline.

        ``start_seq`` is the sender's local index of events[0]. A node
        that re-registers after a heartbeat blip rewinds its cursor to 0
        and reships history into a GCS that often still holds the earlier
        copies (advisor r3): events with seq below this store's per-node
        high-water mark are dropped as duplicates. Senders that predate
        the field (start_seq None) keep the old append-all behavior."""
        with self.lock:
            nid = node_id.hex()[:8]
            if start_seq is not None:
                seen = self._task_ev_seq.get(node_id, 0)
                skip = max(0, seen - start_seq)
                if skip >= len(events):
                    return True
                events = events[skip:]
                start_seq += skip
                self._task_ev_seq[node_id] = start_seq + len(events)
            for ev in events:
                ev = dict(ev)
                ev["node"] = nid
                self.task_events.append(ev)
        return True

    def rpc_task_events_get(self, ctx, limit: int = 10000):
        limit = int(limit)
        if limit <= 0:
            return []
        with self.lock:
            evs = list(self.task_events)
        return evs[-limit:]

    def rpc_trace_events(self, ctx, node_id: bytes, events, start_seq=None):
        """Batched spans from a node's TraceStore (trace-plane twin of
        rpc_task_events — same cursor semantics: ``start_seq`` is the
        sender's absolute index of events[0], re-registration rewinds are
        deduped against the per-node high-water mark)."""
        with self.lock:
            if start_seq is not None:
                seen = self._trace_ev_seq.get(node_id, 0)
                skip = max(0, seen - start_seq)
                if skip >= len(events):
                    return True
                events = events[skip:]
                start_seq += skip
                self._trace_ev_seq[node_id] = start_seq + len(events)
            self.trace_events.extend(events)
        return True

    def rpc_trace_events_get(self, ctx, limit: int = 10000):
        limit = int(limit)
        if limit <= 0:
            return []
        with self.lock:
            evs = list(self.trace_events)
        return evs[-limit:]

    def rpc_profile_events(self, ctx, node_id: bytes, events,
                           start_seq=None):
        """Batched profile batches from a node's ProfileStore
        (profiling-plane twin of rpc_trace_events — same acked-cursor/
        dedup contract against the per-node high-water mark)."""
        rx = time.time()
        with self.lock:
            if start_seq is not None:
                seen = self._profile_ev_seq.get(node_id, 0)
                skip = max(0, seen - start_seq)
                if skip >= len(events):
                    return True
                events = events[skip:]
                start_seq += skip
                self._profile_ev_seq[node_id] = start_seq + len(events)
            for ev in events:
                # re-stamp arrival with THIS clock: the sender's _rx is
                # its own (possibly skewed) wall clock, and the head's
                # window filter needs a receiver-side reference
                ev["_rx"] = rx
            self.profile_events.extend(events)
        return True

    def rpc_profile_events_get(self, ctx, limit: int = 2048):
        limit = int(limit)
        if limit <= 0:
            return []
        with self.lock:
            evs = list(self.profile_events)
        return evs[-limit:]

    # -- live cluster-wide stack dumps (`ray_tpu stack` py-spy role) ----

    def rpc_lifecycle_events(self, ctx, node_id: bytes, events,
                             start_seq=None):
        """Batched lifecycle events from a node's EventStore (event-plane
        twin of rpc_trace_events — same acked-cursor/dedup contract
        against the per-node high-water mark)."""
        with self.lock:
            if start_seq is not None:
                seen = self._lifecycle_ev_seq.get(node_id, 0)
                skip = max(0, seen - start_seq)
                if skip >= len(events):
                    return True
                events = events[skip:]
                start_seq += skip
                self._lifecycle_ev_seq[node_id] = start_seq + len(events)
            self.lifecycle_events.extend(events)
        return True

    def rpc_lifecycle_events_get(self, ctx, limit: int = 10000):
        limit = int(limit)
        if limit <= 0:
            return []
        with self.lock:
            evs = list(self.lifecycle_events)
        return evs[-limit:]

    def _append_lifecycle(self, rec) -> None:
        """Append a GCS-origin event record: node register/death are
        observed HERE (no daemon survives to report its own death), so
        the record skips the ring/heartbeat hop and lands in the head
        store directly with component=gcs provenance. ``rec`` is an
        ``events.record(...)`` result (None when the plane is killed)."""
        if rec is None:
            return
        with self.lock:
            self.lifecycle_events.append(rec)

    def rpc_stack_request(self, ctx):
        """Start a cluster-wide stack dump: publish the request on the
        ``profiling`` channel (every node's adapter collects its process
        + workers and calls stack_reply) and return the request id the
        caller later passes to stack_collect."""
        with self.lock:
            self._stack_req_seq += 1
            req_id = self._stack_req_seq
            self._stack_replies[req_id] = {}
            # bound: keep only the most recent requests
            while len(self._stack_replies) > 8:
                self._stack_replies.pop(min(self._stack_replies))
        self._publish("profiling", {"op": "stackdump", "req": req_id})
        return req_id

    def rpc_stack_reply(self, ctx, req_id: int, node_id: bytes, stacks):
        with self.lock:
            bucket = self._stack_replies.get(req_id)
            if bucket is not None:
                bucket[node_id.hex()[:8]] = stacks
        return True

    def rpc_stack_collect(self, ctx, req_id: int):
        """{node_id: {proc_label: {thread: collapsed_stack}}} gathered so
        far for a stack_request id (callers poll until enough nodes
        answered or their own deadline passes)."""
        with self.lock:
            return dict(self._stack_replies.get(req_id) or {})

    # -- cluster-wide log federation (`rtpu logs` rendezvous) -----------

    def rpc_log_request(self, ctx, target: dict,
                        tail_bytes: Optional[int] = None):
        """Start a cluster-wide log fetch: publish the resolution target
        on the ``events`` channel (every node's adapter resolves it
        against its own workers/session logs and calls log_reply only
        when it has rows) and return the request id the caller later
        passes to log_collect."""
        with self.lock:
            self._log_req_seq += 1
            req_id = self._log_req_seq
            self._log_replies[req_id] = {}
            # bound: keep only the most recent requests
            while len(self._log_replies) > 8:
                self._log_replies.pop(min(self._log_replies))
        self._publish("events", {"op": "logfetch", "req": req_id,
                                 "target": dict(target or {}),
                                 "tail_bytes": tail_bytes})
        return req_id

    def rpc_log_reply(self, ctx, req_id: int, node_id: bytes, rows):
        with self.lock:
            bucket = self._log_replies.get(req_id)
            if bucket is not None:
                bucket[node_id.hex()[:8]] = rows
        return True

    def rpc_log_collect(self, ctx, req_id: int):
        """{node_id: [log rows]} gathered so far for a log_request id
        (callers poll until a reply lands or their deadline passes —
        unlike stackdumps, only nodes that RESOLVED the target reply)."""
        with self.lock:
            return dict(self._log_replies.get(req_id) or {})

    def rpc_metrics_get(self, ctx, exclude_node: Optional[bytes] = None):
        """Flattened [(origin_labels, records)] across nodes for the head
        /metrics exposition. ``exclude_node``: the caller's own node id —
        its samples are already rendered locally (its registry and its
        workers' federation store live in-process). The GCS process's OWN
        registry (rpc counts/latency, pubsub fanout, table sizes, lock
        waits) rides along under component=gcs — the server has no other
        path to a scrape."""
        out = []
        with self.lock:
            for nid, payload in self._node_metrics.items():
                if nid == exclude_node:
                    continue
                out.extend(payload)
        try:
            from ray_tpu.util import metrics as _metrics

            recs = _metrics.registry_records()
            if any(r["samples"] for r in recs):
                out.append(({"component": "gcs"}, recs))
        except Exception:
            pass
        return out

    def rpc_device_report(self, ctx, node_id: bytes, entries) -> bool:
        """Replace a node's device-plane process entries (compiled-
        program registries + HBM census) — the metrics-payload pattern,
        not the acked-cursor one: registry rows are mutable state, so
        the latest snapshot is the whole truth for that node."""
        with self.lock:
            self._node_devices[node_id] = list(entries or ())
        return True

    def rpc_device_report_get(self, ctx,
                              exclude_node: Optional[bytes] = None):
        """Flattened process entries across nodes for the head's
        state.device_report(). ``exclude_node``: the caller's own node —
        its entries live in-process (local registry + DeviceStore)."""
        out = []
        with self.lock:
            for nid, entries in self._node_devices.items():
                if nid == exclude_node:
                    continue
                out.extend(entries)
        return out

    def rpc_obj_info(self, ctx, oids):
        """Batch (size, locations) for READY segment objects — the
        scheduler's dependency-locality signal (reference scorer.h role).
        Pending/inline/error entries are omitted: they carry no locality."""
        out = {}
        with self.lock:
            for oid in oids:
                o = self.objects.get(oid)
                if (o is not None and o.status == READY
                        and o.inline is None and o.locations):
                    out[oid] = (o.size, list(o.locations))
        return out

    def rpc_obj_state(self, ctx, oid: bytes):
        with self.lock:
            o = self.objects.get(oid)
            if o is None:
                return None
            return {"status": o.status, "inline": o.inline, "error": o.error,
                    "size": o.size, "locations": list(o.locations)}

    def rpc_obj_list(self, ctx, limit: int = 10000):
        """Object-directory dump for ``ray_tpu memory`` (reference
        ``ray memory`` refcount-dump role, ``scripts.py:1941``): per-object
        status, size, pin count (distributed refcount holders), and
        location count."""
        out = []
        with self.lock:
            for oid, o in list(self.objects.items())[:limit]:
                out.append({
                    "object_id": oid.hex(),
                    "status": o.status,
                    "size": o.size,
                    "inline": o.inline is not None,
                    "pins": len(o.pins),
                    "locations": len(o.locations),
                })
        return out

    def rpc_obj_drop(self, ctx, oid: bytes):
        """Explicit owner-driven free (``ray_tpu.free``): unlike the
        refcount sweep there is no grace — the caller asserts the object
        is fully consumed. Holder nodes must free their segments (and
        spill files) too, or every free()d exchange intermediate leaks on
        the node that produced it."""
        with self.lock:
            o = self.objects.pop(oid, None)
            locations = list(o.locations) if o is not None else []
            self._free_candidates.pop(oid, None)
            self._record_tombstone_locked(oid, time.monotonic())
        if o is not None:
            self._publish("objects", {"oid": oid, "freed": True,
                                      "locations": locations})
        return True

    def rpc_obj_forget_location(self, ctx, oid: bytes, node_id: bytes):
        """A pull found the segment missing (evicted/deleted behind the
        directory's back): drop the stale location so re-execution can run."""
        with self.lock:
            o = self.objects.get(oid)
            if o is None:
                return False
            o.locations.discard(node_id)
            if not o.locations and o.inline is None and o.status == READY:
                o.status = PENDING
        return True

    # -- KV / functions -------------------------------------------------

    def rpc_kv_put(self, ctx, key: str, value: bytes, namespace: str,
                   overwrite: bool):
        with self.lock:
            ns = self.kv.setdefault(namespace, {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._dirty = True
            return True

    def rpc_kv_get(self, ctx, key: str, namespace: str):
        with self.lock:
            return self.kv.get(namespace, {}).get(key)

    def rpc_kv_del(self, ctx, key: str, namespace: str):
        with self.lock:
            self._dirty = True
            return self.kv.get(namespace, {}).pop(key, None) is not None

    def rpc_kv_keys(self, ctx, prefix: str, namespace: str):
        with self.lock:
            return [k for k in self.kv.get(namespace, {})
                    if k.startswith(prefix)]

    def rpc_fn_put(self, ctx, h: str, blob: bytes):
        with self.lock:
            self.functions.setdefault(h, blob)
            self._dirty = True
        return True

    def rpc_fn_get(self, ctx, h: str):
        with self.lock:
            return self.functions.get(h)

    # -- actors ---------------------------------------------------------

    def rpc_actor_register(self, ctx, actor_id: bytes, node_id: bytes,
                           name: str):
        with self.lock:
            if name and name in self.named_actors:
                existing = self.actors.get(self.named_actors[name])
                if existing is not None and existing.get("state") != "DEAD":
                    raise ValueError(f"actor name {name!r} already taken")
            self.actors[actor_id] = {"node_id": node_id, "name": name,
                                     "state": "PENDING"}
            if name:
                self.named_actors[name] = actor_id
            self._dirty = True
        return True

    def rpc_actor_update(self, ctx, actor_id: bytes, state: str,
                         node_id: Optional[bytes] = None):
        with self.lock:
            rec = self.actors.get(actor_id)
            if rec is None:
                return False
            rec["state"] = state
            if node_id is not None:
                rec["node_id"] = node_id
            if state == "DEAD" and rec.get("name"):
                if self.named_actors.get(rec["name"]) == actor_id:
                    self.named_actors.pop(rec["name"], None)
            self._dirty = True
        return True

    def rpc_actor_get(self, ctx, actor_id: bytes):
        with self.lock:
            rec = self.actors.get(actor_id)
            return dict(rec) if rec else None

    def rpc_actor_lookup(self, ctx, name: str):
        with self.lock:
            return self.named_actors.get(name)

    def rpc_actor_list(self, ctx):
        with self.lock:
            return {aid: dict(rec) for aid, rec in self.actors.items()}

    # -- placement groups ------------------------------------------------

    def rpc_pg_register(self, ctx, pg_id: bytes, bundles, strategy: str,
                        assignments, creator: bytes):
        with self.lock:
            self.pgs[pg_id] = {"bundles": [dict(b) for b in bundles],
                               "strategy": strategy,
                               "assignments": list(assignments),
                               "creator": creator}
            self._dirty = True
        self._publish("pgs", {"event": "update", "pg_id": pg_id,
                              "assignments": list(assignments)})
        return True

    def rpc_pg_get(self, ctx, pg_id: bytes):
        with self.lock:
            rec = self.pgs.get(pg_id)
            return dict(rec) if rec else None

    def rpc_pg_update_assignment(self, ctx, pg_id: bytes, updates):
        """``updates``: {bundle_idx: node_id} after a reschedule."""
        with self.lock:
            rec = self.pgs.get(pg_id)
            if rec is None:
                return False
            for i, nid in updates.items():
                rec["assignments"][int(i)] = nid
            assignments = list(rec["assignments"])
            self._dirty = True
        self._publish("pgs", {"event": "update", "pg_id": pg_id,
                              "assignments": assignments})
        return True

    def rpc_pg_remove(self, ctx, pg_id: bytes):
        with self.lock:
            rec = self.pgs.pop(pg_id, None)
            self._dirty = True
        if rec is not None:
            self._publish("pgs", {"event": "removed", "pg_id": pg_id})
        return True

    def rpc_pg_list(self, ctx):
        with self.lock:
            return {p: dict(r) for p, r in self.pgs.items()}

    # -- pubsub ---------------------------------------------------------

    def rpc_subscribe(self, ctx, channel: str):
        ctx.subscriptions.add(channel)
        return True

    def rpc_publish(self, ctx, channel: str, payload):
        self._publish(channel, payload)
        return True

    def _publish(self, channel: str, payload):
        if self.server is not None:
            n = self.server.broadcast(channel, payload)
            if n:
                keys = self._channel_keys
                key = keys.get(channel) or keys.setdefault(
                    channel, (("channel", channel),))
                self._m_pubsub._inc_key(key, n)

    def rpc_ping(self, ctx):
        return "pong"

    # -- chaos plane ----------------------------------------------------

    def rpc_fp_arm(self, ctx, spec: str):
        """Arm failpoints in the GCS SERVER process itself (sites like
        rpc.server.dispatch live here); cluster-wide distribution rides
        the ``failpoints`` pubsub channel + KV, not this call."""
        from ray_tpu.util import failpoints

        failpoints.apply_spec(spec)
        return True

    def rpc_fp_disarm(self, ctx):
        from ray_tpu.util import failpoints

        failpoints.clear()
        return True

    # ------------------------------------------------------------------

    def serve(self, host: str, port: int, authkey: bytes) -> RpcServer:
        self.server = RpcServer(host, port, authkey, self.handle)
        threading.Thread(target=self._health_loop, daemon=True,
                         name="gcs-health").start()
        return self.server

    def stop(self):
        self._stop.set()
        if self.server is not None:
            self.server.close()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--authkey", required=True)
    p.add_argument("--node-timeout", type=float,
                   default=DEFAULT_NODE_TIMEOUT_S)
    p.add_argument("--snapshot", default=None,
                   help="snapshot file for durable-table fault tolerance")
    args = p.parse_args(argv)

    svc = GcsService(node_timeout_s=args.node_timeout,
                     snapshot_path=args.snapshot)
    svc.serve(args.host, args.port, args.authkey.encode())
    print(f"gcs listening on {args.host}:{args.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
