"""Per-node daemon: a runtime (worker pool + scheduler + store) as a process.

Role analog: the raylet (``src/ray/raylet/main.cc:123`` /
``node_manager.h:119``) — per-node worker pool, local task dispatch, local
shared-memory store, object serving to peers, heartbeats to the GCS. The
execution engine is the same ``DriverRuntime`` the single-node path uses;
the :class:`~ray_tpu.cluster.adapter.ClusterAdapter` provides the
cluster-facing RPC service and directory wiring.

Daemons never spill tasks (``is_scheduler=False``): whatever the head
forwards here runs here, mirroring the reference's lease semantics at MVP
fidelity.
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import os
import signal
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--gcs", required=True, help="GCS address host:port")
    p.add_argument("--authkey", required=True)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="{}",
                   help="extra resources as JSON, e.g. '{\"worker\": 1}'")
    p.add_argument("--labels", default="{}",
                   help="node labels as JSON, e.g. "
                        "'{\"tpu-generation\": \"v5e\"}'")
    p.add_argument("--listen-host", default="127.0.0.1")
    args = p.parse_args(argv)

    from ray_tpu.cluster.adapter import ClusterAdapter
    from ray_tpu.core.runtime import DriverRuntime

    rt = DriverRuntime(
        num_cpus=int(args.num_cpus) if args.num_cpus else None,
        num_tpus=0,
        resources=json.loads(args.resources),
        log_to_driver=False,  # daemon stdout goes nowhere useful
        labels=json.loads(args.labels),
    )
    adapter = ClusterAdapter(args.gcs, args.authkey.encode(),
                             is_scheduler=False,
                             listen_host=args.listen_host)
    adapter.attach(rt)
    # daemon uptime, refreshed whenever this process's registry snapshots
    # (heartbeat federation payloads) — a reset on the head /metrics
    # reveals a silently restarted daemon
    try:
        from ray_tpu.util import metric_defs, metrics

        started = time.monotonic()
        uptime = metric_defs.get("rtpu_daemon_uptime_seconds")
        metrics.register_collector(
            lambda: uptime.set(time.monotonic() - started))
    except Exception:
        pass
    # `kill -USR1 <daemon pid>` dumps every thread's stack — into the
    # session's log dir, NOT the daemon's stdout (spawners routinely point
    # that at /dev/null, which used to lose daemon dumps and blind
    # hung-cluster debugging; workers/pytest already log theirs).
    dump_path = os.path.join(rt.session_dir, "logs",
                             f"daemon-{rt.node_id.hex()[:8]}.log")
    try:
        dump_file = open(dump_path, "a")  # held open for process lifetime
        faulthandler.register(signal.SIGUSR1, file=dump_file,
                              all_threads=True)
    except (AttributeError, ValueError, OSError):
        dump_path = "(unavailable)"
    print(f"node daemon {rt.node_id.hex()[:8]} serving on "
          f"{adapter.server.addr} (gcs {args.gcs}); "
          f"USR1 stack dumps -> {dump_path}", flush=True)

    stop = []

    def _sig(*_):
        stop.append(True)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    while not stop:
        time.sleep(0.2)
    rt.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
