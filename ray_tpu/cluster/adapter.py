"""Cluster adapter: wires a local ``DriverRuntime`` into the GCS cluster.

Role analog: the reference core worker's GCS client + raylet client +
object directory stack (``src/ray/gcs/gcs_client/gcs_client.h:66``,
``ownership_based_object_directory.h``). One adapter per process that hosts
a runtime (the user driver and every node daemon). Responsibilities:

- register this runtime as a node; heartbeat resources;
- publish local object readiness/errors to the global directory;
- watch remote objects and pull their bytes on demand (owner-directed
  fetch: directory -> location -> node daemon pull RPC);
- route task submissions that this node cannot satisfy to a feasible peer
  (driver-side spillback; the reference's raylet lease/spillback role);
- route actor calls to the hosting node;
- react to node death: retry forwarded tasks elsewhere, fail forwarded
  actor calls (``ActorDiedError``), re-execute lost objects' producers
  when lineage allows.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set, Tuple

import cloudpickle

from ray_tpu import config
from ray_tpu.cluster.rpc import RpcClient, RpcServer
from ray_tpu.core import task_spec as ts
from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError
from ray_tpu.core.ids import ActorID, ObjectID

logger = logging.getLogger(__name__)

HEARTBEAT_S = 0.5
# resource-gossip pushes keep the node view fresh between polls; the TTL
# is only the staleness bound when pushes are lost (reconnect windows)
NODE_VIEW_TTL_S = 3.0

# sentinel: "could not reach the GCS" — distinct from "GCS says gone"
GCS_UNAVAILABLE = object()

# node-to-node object transfer (reference push_manager.h / pull_manager.h
# roles): objects above PULL_CHUNK_BYTES stream in chunks straight into a
# preallocated segment — neither end ever materializes the whole blob —
# and at most PULL_CONCURRENCY big pulls run at once (pull admission).
PULL_CHUNK_BYTES = int(config.get("pull_chunk_bytes"))
PULL_CONCURRENCY = int(config.get("pull_concurrency"))
# chunk-fetch threads per big pull (r14 data plane): chunks of ONE object
# stream concurrently over the peer RPC (the client demuxes replies by
# request id, so concurrent calls share the connection) into disjoint
# offsets of the preallocated segment.
PULL_PARALLEL = max(1, int(config.get("pull_parallel")))

# dependency-locality scheduling (reference hybrid_scheduling_policy.h:50
# + scorer.h roles): ship the task to its data when the data is big.
# Below this many dependency bytes, moving the data is cheaper than
# disturbing placement.
LOCALITY_MIN_BYTES = int(config.get("locality_min_bytes"))
# hybrid pack/spread: pack onto busier feasible nodes while their CPU
# utilization is below this, then spread to the least-loaded
HYBRID_PACK_THRESHOLD = float(config.get("hybrid_threshold"))

#: node-to-node transfer + spillback instrumentation, defined centrally
#: in ``util/metric_defs.py`` (reference pull/push manager metrics in
#: ``src/ray/stats/metric_defs.cc``). Lazy: adapters live in daemons and
#: drivers alike; only processes that record/scrape pay for it
#: (metric_defs.get caches and survives clear_registry).

_FWD_KEYS = {r: (("reason", r),) for r in (
    "resources", "locality", "strategy", "pg", "actor_route")}


def _transfer_metrics():
    from ray_tpu.util import metric_defs as md

    return {
        "pulled": md.get("rtpu_cluster_object_pull_bytes_total"),
        "served": md.get("rtpu_cluster_object_serve_bytes_total"),
        "forwarded": md.get("rtpu_cluster_tasks_forwarded_total"),
        "heartbeats": md.get("rtpu_cluster_heartbeats_total"),
        "hb_rtt": md.get("rtpu_cluster_heartbeat_rtt_seconds"),
    }


#: oid -> (stride, payload_bytes) hint for pull_chunks (block-batch
#: framing, ISSUE 13): a consumer that KNOWS an object is a batch of
#: fixed-size records (KV blocks) registers the record stride + total
#: record-payload size before fetching, and the chunked pull aligns
#: chunk boundaries to record boundaries — every chunk past the
#: serialized header carries whole records, so a partially-failed pull
#: can never tear a record across an aborted boundary and receivers can
#: consume chunk-granular. payload_bytes matters because the stored
#: layout is ``header | pickle | pad | record body``: the records start
#: at ``size - payload_bytes``, not at offset 0.
#: Bounded: entries are popped on first use and capped defensively.
_pull_align_hints: Dict[bytes, Tuple[int, int]] = {}
_PULL_ALIGN_MAX = 4096


def hint_pull_align(oid_b: bytes, stride: int,
                    payload_bytes: int = 0) -> None:
    """Register a frame stride (+ record-payload size) for one object's
    next chunked pull."""
    if stride > 1 and len(_pull_align_hints) < _PULL_ALIGN_MAX:
        _pull_align_hints[bytes(oid_b)] = (int(stride),
                                           int(payload_bytes))


def pull_chunks(call, oid_b: bytes, size: int, writer, *,
                chunk: int = 4 << 20, parallel: int = 1,
                timeout: float = 60.0, align: int = 1,
                align_base: int = 0) -> bool:
    """Fetch one object's chunks through ``call("pull_chunk", ...)`` into
    an offset-addressed ``writer`` (``IncomingObject`` shape), up to
    ``parallel`` chunks in flight. Standalone so tests can drive it with
    a stub peer; the RpcClient's request-id demux makes concurrent
    ``call``s on one connection safe. Returns False on any short/missing
    chunk (the caller aborts the receive).

    ``align`` > 1 rounds the chunk size DOWN to a multiple of it and
    anchors every chunk boundary at ``align_base + k * chunk``
    (block-batch framing: records start at ``align_base`` — after the
    serialized header — and each chunk then covers whole fixed-size
    records; the first chunk additionally carries the header, the final
    chunk takes the tail). An align larger than the chunk size degrades
    to one record per chunk."""
    if align > 1 and 0 <= align_base < size:
        chunk = max((chunk // align) * align, align)
        spans = []
        end = min(align_base + chunk, size)
        spans.append((0, end))
        while end < size:
            nxt = min(end + chunk, size)
            spans.append((end, nxt - end))
            end = nxt
        spans = [(off, ln) for off, ln in spans if ln > 0]
    else:
        spans = [(off, min(chunk, size - off))
                 for off in range(0, size, chunk)]

    def fetch(span) -> bool:
        off, ln = span
        blob = call("pull_chunk", oid_b, off, ln, timeout=timeout)
        if blob is None or len(blob) != ln:
            return False
        writer.write(off, blob)
        _transfer_metrics()["pulled"].inc(ln)
        return True

    try:
        if parallel <= 1 or len(spans) <= 1:
            return all(fetch(s) for s in spans)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(parallel, len(spans)),
                                thread_name_prefix="pull-chunk") as pool:
            return all(pool.map(fetch, spans))
    except Exception:
        return False


class ClusterAdapter:
    def __init__(self, gcs_addr: str, authkey: bytes, *,
                 is_scheduler: bool, listen_host: str = "127.0.0.1"):
        self.gcs_addr = gcs_addr
        self.authkey = authkey
        self.is_scheduler = is_scheduler  # only the driver/head spills tasks
        self.listen_host = listen_host
        self.rt = None  # DriverRuntime, set by attach()
        self.node_id: bytes = b""
        self.gcs = RpcClient(gcs_addr, authkey, on_push=self._on_push,
                             reconnect=True,
                             on_reconnect=self._on_gcs_reconnect)
        self._peers: Dict[bytes, RpcClient] = {}
        self._peer_addrs: Dict[bytes, str] = {}
        self._peers_lock = threading.Lock()
        # oid -> fetch flag (True: pull the value; False: state-only)
        self._watched: Dict[bytes, bool] = {}
        self._watch_lock = threading.Lock()
        # node lifecycle fan-out (elastic training, r20): registered
        # callbacks get every "nodes" up/down pubsub payload, invoked on
        # the io pool AFTER the adapter's own failure handling so a
        # subscriber probing the node view sees the dead peer removed.
        self._node_event_subs: List[Any] = []
        self._node_event_lock = threading.Lock()
        self._fetching: Set[bytes] = set()
        # forwarded work for failure handling: node_id -> {task_id: spec}
        self._forwarded: Dict[bytes, Dict[bytes, dict]] = {}
        # first return-id -> (node_id, task_id): completion of that object
        # retires the forwarded entry so node death doesn't retry done work
        self._fwd_by_oid: Dict[bytes, tuple] = {}
        self._forwarded_lock = threading.Lock()
        # forward-attempt tokens seen from peers (token -> [done_event,
        # committed]): a forwarder whose reply was lost re-sends the SAME
        # attempt to the SAME peer, and this dedupe makes the second
        # delivery a no-op instead of a double execution. Keyed on a
        # per-attempt token — NOT task_id — because legitimate
        # re-executions (max_retries resubmit, lineage reconstruction)
        # reuse the task_id and must be accepted.
        self._accepted_specs: "OrderedDict[bytes, list]" = OrderedDict()
        self._accepted_lock = threading.Lock()
        self._remote_actors: Dict[bytes, bytes] = {}  # actor_id -> node_id
        # streaming tasks forwarded with backpressure: task_id -> executing
        # node, so consumer-side acks relay to where the producer parks
        self._stream_routes: Dict[bytes, bytes] = {}
        # owner hints: return oid -> node the producing task was forwarded
        # to. Unlike _fwd_by_oid (popped when delivery STARTS), hints live
        # until the object is terminal LOCALLY — the locality scheduler
        # consults them while a result exists only on its producer's node
        self._result_hints: Dict[bytes, bytes] = {}
        # pull admission: big (chunked) fetches run on their own bounded
        # pool — its size IS the concurrent-pull cap. Blocking admission
        # inside the shared _io pool would let queued pulls starve
        # stream-consumed relays / node-down handling / state queries.
        self._pull_io = ThreadPoolExecutor(max_workers=PULL_CONCURRENCY,
                                           thread_name_prefix="cluster-pull")
        self._task_ev_cursor = 0  # next local task event to ship to GCS
        self._trace_ev_cursor = 0  # next TraceStore span to ship to GCS
        self._profile_ev_cursor = 0  # next ProfileStore batch to ship
        self._event_ev_cursor = 0  # next EventStore lifecycle event to ship
        # set by the first successful _register(): a later register that
        # the GCS answers "unknown node" is then a restart observation
        self._had_registered = False
        # (size, locations) cache for dependency-locality scoring: fan-outs
        # of one big ref to N tasks pay one directory lookup, not N.
        # _obj_info_down_until: circuit breaker — while the GCS is not
        # answering, placement proceeds without locality instead of taxing
        # every submit with a timed-out RPC
        self._obj_info: Dict[bytes, tuple] = {}
        self._obj_info_down_until = 0.0
        # placement groups: cached assignment maps (pg_id -> {idx: node}),
        # full meta for groups THIS adapter created (it owns rescheduling),
        # bundles lost to node death awaiting re-placement, and task specs
        # parked on a lost bundle
        self._pg_nodes: Dict[bytes, Dict[int, Optional[bytes]]] = {}
        self._pg_meta: Dict[bytes, dict] = {}
        self._my_pgs: Dict[bytes, dict] = {}
        self._pg_pending: Dict[bytes, Set[int]] = {}
        self._pg_parked: Dict[bytes, List[dict]] = {}
        self._pg_lock = threading.Lock()
        self._pg_rr = 0
        self._node_view: List[dict] = []
        self._node_view_ts = 0.0
        self._spread_rr = 0
        self._stop = threading.Event()
        self.server: Optional[RpcServer] = None
        # All watch/deliver/fetch work runs here, NEVER on the RpcClient
        # reader thread (a blocking gcs.call from the reader thread can
        # never see its own reply) and never on a worker-pipe receiver
        # thread (which must keep demuxing results).
        self._io = ThreadPoolExecutor(max_workers=8,
                                      thread_name_prefix="cluster-io")
        # fn publishes get their own lane: queued behind saturated fetch
        # work they could exceed the consumer's fetch_fn poll window
        self._publish_io = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cluster-publish")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def attach(self, rt) -> None:
        """Register ``rt`` as a cluster node and start serving peers."""
        self.rt = rt
        self.node_id = rt.node_id.binary()
        rt.cluster = self
        rt.gcs.on_object_ready = self._publish_ready
        rt.gcs.on_object_error = self._publish_error
        self.server = RpcServer(self.listen_host, 0, self.authkey,
                                self._serve_peer)
        self._register()
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name="cluster-heartbeat").start()

    def close(self) -> None:
        self._stop.set()
        try:
            self.gcs.cast("node_drain", self.node_id)
        except Exception:
            pass
        if self.server is not None:
            self.server.close()
        with self._peers_lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for p in peers:
            p.close()
        self.gcs.close()
        self._io.shutdown(wait=False)
        self._publish_io.shutdown(wait=False)
        self._pull_io.shutdown(wait=False)

    def _heartbeat_loop(self):
        from ray_tpu.util.host_stats import host_stats

        from ray_tpu.util import failpoints

        beat = 0
        while not self._stop.wait(HEARTBEAT_S):
            try:
                if failpoints.hit("gcs.heartbeat"):
                    # chaos: heartbeat blackout ≈ network partition — the
                    # GCS will declare this node dead after node_timeout;
                    # when beats resume, the heartbeat NACK re-registers
                    continue
                self.rt.reap_stale_pg_stages()
                with self.rt.lock:
                    avail = dict(self.rt.avail)
                    depth = len(self.rt.ready_tasks)
                # host sample every ~2s, not every beat: consumers read
                # at dashboard cadence and sub-second cpu_percent
                # windows are noise
                beat += 1
                stats = host_stats() if beat % 4 == 1 else None
                # metrics federation rides the same ~2s beats: this
                # process's registry plus its workers' ingested samples,
                # as a full (small) snapshot the GCS replaces per node —
                # idempotent, so a dropped heartbeat self-heals
                mpayload = (self._metrics_payload()
                            if beat % 4 == 1 else None)
                t0 = time.perf_counter()
                known = self.gcs.call("node_heartbeat", self.node_id, avail,
                                      depth, stats, mpayload, timeout=5)
                try:
                    # guarded on its own: a metrics failure must never
                    # abort the beat (the loop's blanket except would
                    # drop the RPC and get this node declared dead)
                    m = _transfer_metrics()
                    m["heartbeats"]._inc_key(())
                    m["hb_rtt"]._observe_key((), time.perf_counter() - t0)
                except Exception:
                    pass
                if known is False:
                    # a restarted GCS lost the (non-durable) node table:
                    # re-register + re-subscribe (GCS FT path; _register
                    # itself records the gcs_restart lifecycle event)
                    self._register()
                # ship NEW task events (reference TaskEventBuffer flush,
                # task_event_buffer.h:206 role): batched + bounded, so
                # the cluster state API sees every node's tasks. Acked
                # call, not cast: the cursor only advances on receipt
                evs = self.rt.timeline_events
                cur = self._task_ev_cursor
                if len(evs) > cur:
                    batch = evs[cur:cur + 1000]
                    # cursor rides along so a post-re-register rewind can
                    # be deduped server-side (advisor r3: duplicate events)
                    if self.gcs.call("task_events", self.node_id, batch,
                                     cur, timeout=5):
                        self._task_ev_cursor = cur + len(batch)
                # trace plane rides the same beats: this node's span ring
                # (driver/daemon process) + its workers' pushed batches,
                # shipped as acked deltas from the TraceStore cursor
                self.rt.collect_trace_spans()
                tb, tstart = self.rt.trace_store.since(
                    self._trace_ev_cursor)
                if tb:
                    if self.gcs.call("trace_events", self.node_id, tb,
                                     tstart, timeout=5):
                        self._trace_ev_cursor = tstart + len(tb)
                        from ray_tpu.util import tracing as _tracing

                        _tracing.note_push()
                # profiling plane rides the same beats: this node's
                # sampler window (driver/daemon process) + its workers'
                # pushed batches, shipped as acked ProfileStore deltas
                self.rt.collect_profile_batches()
                pb, pstart = self.rt.profile_store.since(
                    self._profile_ev_cursor)
                if pb:
                    if self.gcs.call("profile_events", self.node_id, pb,
                                     pstart, timeout=5):
                        self._profile_ev_cursor = pstart + len(pb)
                        from ray_tpu.util import profiling as _profiling

                        _profiling.note_push()
                # event plane rides the same beats: this node's lifecycle
                # ring (driver/daemon process) + its workers' pushed
                # batches, shipped as acked EventStore deltas
                self.rt.collect_lifecycle_events()
                eb, estart = self.rt.event_store.since(
                    self._event_ev_cursor)
                if eb:
                    if self.gcs.call("lifecycle_events", self.node_id, eb,
                                     estart, timeout=5):
                        self._event_ev_cursor = estart + len(eb)
                        from ray_tpu.util import events as _events

                        _events.note_push()
                # device plane rides the ~2s beats: this node's compiled-
                # program registries (this process + its workers' pushed
                # snapshots), shipped like metrics as an idempotent
                # per-node payload the GCS replaces — registry rows are
                # mutable state, so a dropped beat self-heals
                if beat % 4 == 1:
                    from ray_tpu.util import device_plane as _dp

                    if _dp.device_plane_enabled():
                        dents = _dp.node_processes(
                            self.rt,
                            component=("driver" if self.is_scheduler
                                       else "raylet"))
                        if dents:
                            nid = self.node_id.hex()[:8]
                            for ent in dents:
                                ent.setdefault("node_id", nid)
                            self.gcs.call("device_report", self.node_id,
                                          dents, timeout=5)
            except Exception:
                pass

    def _metrics_payload(self):
        """[(origin_labels, records)] for this node: the local registry
        (driver/daemon process) plus every federated origin it ingested
        (its workers). None when federation is disabled or empty."""
        try:
            if not config.get("metrics_federation"):
                return None
            from ray_tpu.util import metrics as _metrics

            labels = {"node_id": self.node_id.hex()[:8],
                      "component": ("driver" if self.is_scheduler
                                    else "raylet")}
            recs = _metrics.registry_records()
            origins = _metrics.federation.export()
            if not origins and not any(r["samples"] for r in recs):
                return None  # nothing recorded anywhere yet: skip the ride
            return [(labels, recs)] + origins
        except Exception:
            return None

    def _note_gcs_restart(self) -> None:
        """THE gcs_restart emit site: a re-registration found the GCS
        had no entry for this node — it came back without its
        (non-durable) node table, so record the outage as a lifecycle
        event. (A heartbeat blackout does NOT land here: dead entries
        stay in the table with alive=False, so node_register still
        reports the node as known.)"""
        try:
            from ray_tpu.util import events as _events

            _events.emit("gcs_restart", node_id=self.node_id.hex()[:8])
        except Exception:
            pass

    def _register(self):
        self.gcs.call("subscribe", "nodes", timeout=10)
        self.gcs.call("subscribe", "objects", timeout=10)
        self.gcs.call("subscribe", "pgs", timeout=10)
        self.gcs.call("subscribe", "failpoints", timeout=10)
        self.gcs.call("subscribe", "tracing", timeout=10)
        self.gcs.call("subscribe", "profiling", timeout=10)
        self.gcs.call("subscribe", "events", timeout=10)
        known = self.gcs.call(
            "node_register", self.node_id, self.server.addr,
            self.rt.resources("total"), self.is_scheduler,
            dict(getattr(self.rt, "labels", {})), timeout=10)
        if known is False and self._had_registered:
            # the GCS forgot a node it once accepted: state loss —
            # whether we got here via the reconnect callback (GCS
            # process restart) or a heartbeat NACK
            self._note_gcs_restart()
        self._had_registered = True
        self._node_view_ts = 0.0
        # a (re)registered GCS starts with an empty task-event store:
        # reship our full local history
        self._task_ev_cursor = 0
        # chaos plane, late-joiner path: pull the cluster-wide failpoint
        # spec (durable in the GCS KV) so daemons booted or re-registered
        # after failpoints.arm() are armed too
        from ray_tpu.util import failpoints

        failpoints.sync_from_kv(
            lambda k, ns: self.gcs.call("kv_get", k, ns, timeout=10))
        # trace plane, late-joiner path: daemons booted or re-registered
        # after enable_tracing() pull the arming payload from the KV
        from ray_tpu.util import tracing

        tracing.sync_from_kv(
            lambda k, ns: self.gcs.call("kv_get", k, ns, timeout=10))
        self._trace_ev_cursor = 0
        # profiling plane, late-joiner path: same contract as tracing
        from ray_tpu.util import profiling

        profiling.sync_from_kv(
            lambda k, ns: self.gcs.call("kv_get", k, ns, timeout=10))
        self._profile_ev_cursor = 0
        # event plane, late-joiner path: same contract as tracing
        from ray_tpu.util import events

        events.sync_from_kv(
            lambda k, ns: self.gcs.call("kv_get", k, ns, timeout=10))
        self._event_ev_cursor = 0
        # GCS restart recovery (chaos: kill -9 mid-submit): the object
        # directory is NOT durable and obj_ready is a cast, so anything
        # that turned terminal during the outage is unknown to the rebuilt
        # directory and its notification died with the old process.
        # Re-advertise every locally terminal object (repopulates the
        # directory + re-publishes), then re-query our watched set —
        # subscription is already re-established above, so either the
        # re-query or the re-published push delivers each result.
        self._io.submit(self._readvertise_terminal)
        with self._watch_lock:
            watched = list(self._watched)
        for b in watched:
            self._io.submit(self._initial_query, b)

    def _on_gcs_reconnect(self):
        try:
            self._register()
        except Exception:
            pass

    def _readvertise_terminal(self) -> None:
        """Rebuild the (restarted) GCS directory's view of this node:
        re-cast obj_ready/obj_error for every locally terminal object we
        can still serve — inline values (we hold the bytes), store-held
        segments, and errors."""
        try:
            items = self.rt.gcs.all_objects()
        except Exception:
            return
        for oid, st in items:
            try:
                if st.status == "READY":
                    if st.inline is not None:
                        self.gcs.cast("obj_ready", oid.binary(), st.inline,
                                      None, st.size)
                    elif self.rt.store.contains(oid):
                        self.gcs.cast("obj_ready", oid.binary(), None,
                                      self.node_id, st.size)
                elif st.status == "ERROR" and st.error is not None:
                    self.gcs.cast("obj_error", oid.binary(), st.error)
            except Exception:
                return  # connection dropped again: the next NACK retries

    # ------------------------------------------------------------------
    # peer RPC service (what other nodes may ask of this one)
    # ------------------------------------------------------------------

    def _serve_peer(self, method: str, args: tuple, ctx) -> Any:
        if method in ("submit_spec", "submit_actor_spec"):
            # chaos site: this node accepting forwarded work IS the lease
            # grant (raise -> the head re-places; kill -> daemon death
            # mid-gang-schedule)
            from ray_tpu.util import failpoints

            failpoints.hit("daemon.lease_grant", method)
        if method == "submit_spec":
            dup, tok = self._begin_attempt(args[0])
            if not dup:
                try:
                    self.rt.submit_spec(args[0])
                except BaseException:
                    # nothing was enqueued: release the token so the
                    # error reply is authoritative (a later fresh forward
                    # may still succeed) — never report a failed submit
                    # as an accepted one
                    self._abort_attempt(tok)
                    raise
                self._commit_attempt(tok)
            return True
        if method == "submit_actor_spec":
            dup, tok = self._begin_attempt(args[0])
            if not dup:
                try:
                    self.rt.submit_actor_task(args[0])
                except BaseException:
                    self._abort_attempt(tok)
                    raise
                self._commit_attempt(tok)
            return True
        if method == "pull_object":
            return self._serve_pull(args[0])
        if method == "pull_chunk":
            return self._serve_pull_chunk(args[0], args[1], args[2])
        if method == "bcast_fetch":
            # relay work must not block the peer-RPC thread
            self._pull_io.submit(self._bcast_fetch, args[0], args[1],
                                 args[2], args[3])
            return True
        if method == "stream_consumed":
            self.rt.stream_consumed(args[0], args[1])
            return True
        if method == "kill_actor":
            self.rt.kill_actor(args[0], args[1])
            return True
        if method == "cancel_task":
            force = args[1] if len(args) > 1 else False
            self.rt.cancel_task(ObjectID(args[0]), force)
            return True
        if method == "pg_prepare":
            return self.rt.pg_prepare(args[0], args[1])
        if method == "pg_commit":
            return self.rt.pg_commit(args[0])
        if method == "pg_abort":
            self.rt.pg_abort(args[0])
            return True
        if method == "pg_release":
            self.rt.pg_release_local(args[0])
            return True
        if method == "ping":
            return "pong"
        raise AttributeError(f"node: unknown method {method!r}")

    def _serve_pull(self, oid_b: bytes):
        oid = ObjectID(oid_b)
        st = self.rt.gcs.object_state(oid)
        if st is not None and st.status == "ERROR":
            return ("e", st.error)
        if st is not None and st.status == "READY" and st.inline is not None:
            return ("i", st.inline)
        # Spilled holder: restore into shm first when headroom allows
        # (reference raylet restore-for-remote-pull,
        # ``local_object_manager.h:110``); get_raw reads the spill file
        # directly either way, so a failed restore still serves the pull.
        if self.rt.store.contains_spilled(oid):
            self.rt.store.restore_spilled(oid)
        raw = self.rt.store.get_raw(oid)
        if raw is not None:
            _transfer_metrics()["served"].inc(len(raw))
            return ("s", raw)
        # segment gone (evicted/deleted behind the directory's back)
        self.gcs.cast("obj_forget_location", oid_b, self.node_id)
        return None

    def _serve_pull_chunk(self, oid_b: bytes, offset: int, length: int):
        """One chunk of a segment; only ``length`` bytes leave the store."""
        oid = ObjectID(oid_b)
        if offset == 0 and self.rt.store.contains_spilled(oid):
            # restore at stream start so the remaining chunks read shm,
            # not disk. First chunk ONLY: restore_spilled's headroom gate
            # scans /dev/shm, which must not run once per chunk of a
            # multi-GB pull. A refused restore just means every chunk
            # reads from the spill file — still correct.
            self.rt.store.restore_spilled(oid)
        blob = self.rt.store.get_raw_chunk(oid, offset, length)
        if blob is None:
            self.gcs.cast("obj_forget_location", oid_b, self.node_id)
        else:
            _transfer_metrics()["served"].inc(len(blob))
        return blob

    # ------------------------------------------------------------------
    # object directory: publish + watch + fetch
    # ------------------------------------------------------------------

    def _publish_ready(self, oid: ObjectID, inline: Optional[bytes],
                       size: int):
        self.gcs.cast("obj_ready", oid.binary(), inline, self.node_id, size)

    def _publish_error(self, oid: ObjectID, err: bytes):
        self.gcs.cast("obj_error", oid.binary(), err)

    def pin_object(self, oid_b: bytes) -> None:
        """First live reference on this node: the directory must keep the
        entry (and holders their segments) until we unpin."""
        self.gcs.cast("obj_pin", oid_b, self.node_id)

    def unpin_object(self, oid_b: bytes) -> None:
        self.gcs.cast("obj_unpin", oid_b, self.node_id)

    def watch_many(self, oids, fetch: bool = True) -> None:
        """Subscribe to global terminal state for objects not yet terminal
        locally; delivery marks them ready/error in the local gcs (pulling
        segment bytes from the owning node when needed). Non-blocking: the
        initial state query runs on the adapter's io pool so hot dispatch
        paths (worker-pipe receivers) never wait on the network.

        ``fetch=False`` is a STATE-ONLY watch (forwarded-result tracking):
        completion retires bookkeeping but segment bytes are NOT pulled —
        eagerly copying every forwarded result to the watcher both wastes
        bandwidth and destroys the ship-task-to-data locality signal. A
        later value watch on the same object upgrades it."""
        fresh = []
        with self._watch_lock:
            for o in oids:
                b = o.binary() if isinstance(o, ObjectID) else o
                cur = self._watched.get(b)
                if cur is None:
                    self._watched[b] = fetch
                    fresh.append(b)
                elif fetch and not cur:
                    self._watched[b] = True
                    fresh.append(b)  # re-query: may already be terminal
        for b in fresh:
            # subscribe-then-query closes the race where the object turned
            # terminal between our local check and the subscription
            self._io.submit(self._initial_query, b)

    def _initial_query(self, b: bytes):
        try:
            state = self.gcs.call("obj_state", b, timeout=30)
        except Exception:
            return  # the push subscription remains our signal
        if state is not None and state["status"] in ("READY", "ERROR"):
            self._deliver(b, state)

    def _on_push(self, channel: str, payload):
        # runs on the RpcClient reader thread: hand everything that might
        # issue RPCs to the io pool. Object pushes are notifications only
        # (no payload bytes); interested adapters fetch the state.
        if channel == "objects":
            b = payload["oid"]
            if payload.get("freed"):
                # global refcount hit zero: free our segment copy (the
                # reference's owner-driven object free)
                if self.node_id in (payload.get("locations") or ()):
                    self._io.submit(self._free_local_copy, b)
                # and release any pins this owner held for refs nested in
                # the freed object's bytes (their lifetime was tied to it)
                self._io.submit(self.rt._release_result_ref_pins, b)
                # freed objects must stop attracting dependency-locality
                # placement (advisor r3: stale cache forwarded tasks to
                # nodes that no longer hold the data)
                self._obj_info.pop(b, None)
                return
            with self._watch_lock:
                interested = b in self._watched
            if interested:
                self._io.submit(self._initial_query, b)
        elif channel == "nodes":
            if payload.get("event") == "resources":
                # ray_syncer-style gossip: patch the cached view in place
                # (no node_list round-trip on the scheduling path)
                nid = payload["node_id"]
                for n in self._node_view:
                    if n["node_id"] == nid:
                        n["avail"] = dict(payload["avail"])
                        break
                return
            if payload.get("event") == "down":
                # notify subscribers on the SAME io task, after the
                # adapter's own failure handling: a subscriber probing
                # the node view / resubmitting work must see the dead
                # peer's tasks failed and its pg bundles released first
                self._io.submit(self._node_down_and_notify, payload)
            elif payload.get("event") == "up":
                # a fresh node may make pending pg bundles placeable
                self._io.submit(self._pg_reschedule_pending)
                self._io.submit(self._notify_node_event, dict(payload))
            self._node_view_ts = 0.0  # invalidate the scheduler view
        elif channel == "pgs":
            self._io.submit(self._on_pg_event, payload)
        elif channel == "failpoints":
            self._io.submit(self._on_failpoints, payload)
        elif channel == "tracing":
            self._io.submit(self._on_tracing, payload)
        elif channel == "profiling":
            self._io.submit(self._on_profiling, payload)
        elif channel == "events":
            self._io.submit(self._on_events, payload)

    def _on_profiling(self, payload: dict) -> None:
        """Cluster-wide profiler arm/disarm AND live stack-dump requests
        (the `ray_tpu stack` py-spy role, cluster-wide): a ``stackdump``
        op collects this node's live stacks (its process + its workers)
        and replies to the GCS; an arming payload applies here and
        relays to this runtime's workers over their control pipes."""
        from ray_tpu.util import profiling

        try:
            if payload.get("op") == "stackdump":
                stacks = self.rt.dump_stacks(timeout=2.0)
                self.gcs.call("stack_reply", payload.get("req"),
                              self.node_id, stacks, timeout=10)
                return
            profiling.apply_remote(payload)
            profiling.broadcast_local(self.rt, payload)
        except Exception:
            pass

    def _on_events(self, payload: dict) -> None:
        """Cluster-wide event-plane arm/disarm AND log-fetch requests
        (the `rtpu logs` federation, cluster-wide): a ``logfetch`` op
        resolves the target against this node's workers/session logs and
        replies to the GCS rendezvous (only when it has rows — the
        collector counts replies, not nodes); an arming payload applies
        here and relays to this runtime's workers over their pipes."""
        from ray_tpu.util import events

        try:
            if payload.get("op") == "logfetch":
                rows = self.rt.fetch_local_logs(
                    payload.get("target") or {},
                    tail_bytes=payload.get("tail_bytes"))
                if rows:
                    self.gcs.call("log_reply", payload.get("req"),
                                  self.node_id, rows, timeout=10)
                return
            events.apply_remote(payload)
            events.broadcast_local(self.rt, payload)
        except Exception:
            pass

    def _on_tracing(self, payload: dict) -> None:
        """Cluster-wide tracing arm/disarm: apply in this process and
        relay to this runtime's workers over their control pipes (the
        enable_tracing() mid-session path for remote nodes)."""
        from ray_tpu.util import tracing

        try:
            tracing.apply_remote(payload)
            tracing.broadcast_local(self.rt, payload)
        except Exception:
            pass

    def _on_failpoints(self, payload: dict) -> None:
        """Cluster-wide chaos arming: apply in this process and relay to
        this runtime's workers over their control pipes."""
        from ray_tpu.util import failpoints

        try:
            if payload.get("op") == "disarm":
                failpoints.clear()
                failpoints._broadcast_local(self.rt, None)
            else:
                failpoints.apply_spec(payload["spec"])
                failpoints._broadcast_local(self.rt, payload["spec"])
        except Exception:
            pass

    def _deliver(self, oid_b: bytes, state: dict):
        """Apply a terminal global state to the local gcs (fetch if big)."""
        with self._forwarded_lock:
            ent = self._fwd_by_oid.pop(oid_b, None)
            if ent is not None:
                self._forwarded.get(ent[0], {}).pop(ent[1], None)
                self._stream_routes.pop(ent[1], None)
        oid = ObjectID(oid_b)
        st = self.rt.gcs.object_state(oid)
        if st is not None and st.status in ("READY", "ERROR"):
            self._unwatch(oid_b)
            return
        if state["status"] == "ERROR":
            self.rt.gcs.mark_error(oid, state["error"], _local_only=True)
            self._unwatch(oid_b)
            return
        if state["inline"] is not None:
            self.rt.gcs.mark_ready(oid, inline=state["inline"],
                                   _local_only=True)
            self._unwatch(oid_b)
            return
        if self.node_id in state["locations"]:
            # we hold the segment already (e.g. worker-produced locally)
            self.rt.gcs.mark_ready(oid, size=state["size"], _local_only=True)
            self._unwatch(oid_b)
            return
        with self._watch_lock:
            fetch = self._watched.get(oid_b, True)
        if not fetch:
            # state-only watch: completion bookkeeping done above; the
            # bytes stay with their producer (a value watch pulls later)
            self._unwatch(oid_b)
            return
        if int(state.get("size") or 0) > PULL_CHUNK_BYTES:
            # big pulls move to the dedicated bounded pool (admission):
            # a minutes-long stream must not occupy an _io thread
            self._pull_io.submit(self._fetch_guarded, oid_b, state)
            return
        self._fetch_guarded(oid_b, state)

    def _fetch_guarded(self, oid_b: bytes, state: dict):
        oid = ObjectID(oid_b)
        st = self.rt.gcs.object_state(oid)
        if st is not None and st.status in ("READY", "ERROR"):
            self._unwatch(oid_b)  # resolved while queued behind other pulls
            return
        with self._watch_lock:
            if oid_b in self._fetching:
                return
            self._fetching.add(oid_b)
        try:
            self._fetch(oid, state)
        finally:
            with self._watch_lock:
                self._fetching.discard(oid_b)
            st = self.rt.gcs.object_state(oid)
            if st is not None and st.status in ("READY", "ERROR"):
                # terminal here now: the owner hint served its purpose
                with self._forwarded_lock:
                    self._result_hints.pop(oid_b, None)

    def _fetch(self, oid: ObjectID, state: dict):
        """Owner-directed pull: try each advertised location. Big segments
        stream in chunks (bounded memory on both ends + pull admission)."""
        size = int(state.get("size") or 0)
        for node_id in state["locations"]:
            peer = self._peer(node_id)
            if peer is None:
                continue
            if size > PULL_CHUNK_BYTES:
                if self._fetch_chunked(oid, peer, size):
                    self._unwatch(oid.binary())
                    return
                continue
            try:
                payload = peer.call("pull_object", oid.binary(), timeout=60)
            except Exception:
                continue
            if payload is None:
                continue
            kind, blob = payload
            if kind == "e":
                self.rt.gcs.mark_error(oid, blob, _local_only=True)
            elif kind == "i":
                self.rt.gcs.mark_ready(oid, inline=blob, _local_only=True)
            else:
                _transfer_metrics()["pulled"].inc(len(blob))
                if not self.rt.store.contains(oid):
                    self.rt.store.put_serialized(oid, blob)
                # local copy now exists: advertise it so future readers
                # have a second source (reference push-on-pull behavior)
                self.rt.gcs.mark_ready(oid, size=len(blob))
            self._unwatch(oid.binary())
            return
        # no location answered: wait for re-execution/another location via
        # the still-active subscription (lineage reconstruction path)
        logger.warning("fetch of %s found no live location", oid.hex()[:8])

    def _fetch_chunked(self, oid: ObjectID, peer: RpcClient,
                       size: int) -> bool:
        """Stream one object in PULL_CHUNK_BYTES pieces straight into a
        preallocated segment, PULL_PARALLEL chunks in flight (disjoint
        offsets; the receive writer is offset-addressed so concurrent
        writers never overlap). Peak extra memory per end is one chunk
        per fetch thread. Runs on _pull_io, whose size is the
        concurrent-pull admission cap."""
        # pop the hint BEFORE the already-local return: a hinted object
        # that never needs pulling (same-host store fallback) must not
        # strand its entry until the bounded registry jams shut
        stride, payload = _pull_align_hints.pop(oid.binary(), (1, 0))
        w = self.rt.store.begin_receive(oid, size)
        if w is None:  # already present locally
            self.rt.gcs.mark_ready(oid, size=size)
            return True
        if not pull_chunks(peer.call, oid.binary(), size, w,
                           chunk=PULL_CHUNK_BYTES, parallel=PULL_PARALLEL,
                           align=stride,
                           # records start AFTER the serialized header
                           align_base=(size - payload) if payload else 0):
            w.abort()
            return False
        try:
            w.seal()
        except Exception:
            w.abort()
            return False
        self.rt.gcs.mark_ready(oid, size=size)
        return True

    def _unwatch(self, oid_b: bytes):
        with self._watch_lock:
            self._watched.pop(oid_b, None)

    # ------------------------------------------------------------------
    # push-based broadcast (reference PushManager, push_manager.h:30 role)
    # ------------------------------------------------------------------

    def broadcast_object(self, oid_b: bytes,
                         node_ids: Optional[List[bytes]] = None) -> int:
        """Proactively replicate an object to ``node_ids`` (default: every
        alive node not already holding it) via a BINARY RELAY TREE: this
        node seeds two branch roots; each receiver re-relays to its own
        subtree after sealing. Every node uploads to at most two others, so
        a 1-object-to-N broadcast moves N copies in O(log N) rounds instead
        of N serial pulls off one owner (the reference's chunked
        PushManager fan-out, receiver-driven here because the chunk
        machinery already streams puller-side with bounded memory).

        Returns the number of target nodes. The caller typically holds a
        live ref; replicas advertise themselves in the directory and are
        freed by the normal refcount path."""
        st = self.gcs.call("obj_state", oid_b, timeout=30)
        if st is None or st["status"] != "READY":
            raise ValueError("broadcast: object not READY in the directory")
        if st.get("inline") is not None:
            return 0  # inline values ride the directory itself
        size = int(st.get("size") or 0)
        holders = set(st.get("locations") or ())
        if node_ids is None:
            targets = [n["node_id"] for n in self._nodes()
                       if n["alive"] and n["node_id"] not in holders
                       and n["node_id"] != self.node_id]
        else:
            targets = [b for b in node_ids
                       if b not in holders and b != self.node_id]
        if not targets:
            return 0
        src = (self.node_id if self.node_id in holders
               else next(iter(holders)))
        self._relay_bcast(oid_b, size, src, targets)
        return len(targets)

    def _relay_bcast(self, oid_b: bytes, size: int, from_node: bytes,
                     targets: List[bytes]) -> None:
        """Seed up to two subtree roots with the rest of their branch."""
        if not targets:
            return
        mid = (len(targets) + 1) // 2
        for branch in (targets[:mid], targets[mid:]):
            if not branch:
                continue
            root, rest = branch[0], branch[1:]
            peer = self._peer(root)
            if peer is None:
                # unreachable root: promote the rest of its branch
                self._relay_bcast(oid_b, size, from_node, rest)
                continue
            try:
                peer.cast("bcast_fetch", oid_b, size, from_node, rest)
            except Exception:
                self._relay_bcast(oid_b, size, from_node, rest)

    def _bcast_fetch(self, oid_b: bytes, size: int, from_node: bytes,
                     targets: List[bytes]) -> None:
        """Receiver side: fetch from the designated source (falling back
        to any directory location), then relay to our subtree — WE are the
        source for our children, which is what makes the tree scale."""
        oid = ObjectID(oid_b)
        have = (self.rt.store.contains(oid)
                or (self.rt.gcs.object_state(oid) or
                    type("s", (), {"status": ""})).status == "READY")
        if not have:
            ok = False
            peer = self._peer(from_node)
            if peer is not None:
                if size > PULL_CHUNK_BYTES:
                    ok = self._fetch_chunked(oid, peer, size)
                else:
                    try:
                        payload = peer.call("pull_object", oid_b,
                                            timeout=60)
                        if payload and payload[0] == "s":
                            if not self.rt.store.contains(oid):
                                self.rt.store.put_serialized(oid,
                                                             payload[1])
                            self.rt.gcs.mark_ready(oid, size=len(payload[1]))
                            ok = True
                    except Exception:
                        ok = False
            if not ok:
                st = self.gcs.call("obj_state", oid_b, timeout=30)
                if st and st["status"] == "READY":
                    self._fetch(oid, st)
                ok = self.rt.store.contains(oid)
            if not ok:
                logger.warning("bcast_fetch of %s failed; subtree of %d "
                               "nodes falls back to owner pulls",
                               oid.hex()[:8], len(targets))
                # children can still pull from the original holders
                self._relay_bcast(oid_b, size, from_node, targets)
                return
        self._relay_bcast(oid_b, size, self.node_id, targets)

    def _free_local_copy(self, oid_b: bytes):
        oid = ObjectID(oid_b)
        try:
            self.rt.store.delete(oid)
        except Exception:
            pass
        self.rt.gcs.drop_object(oid)

    # ------------------------------------------------------------------
    # scheduling (driver/head only)
    # ------------------------------------------------------------------

    def _nodes(self) -> List[dict]:
        now = time.monotonic()
        if now - self._node_view_ts > NODE_VIEW_TTL_S:
            try:
                self._node_view = self.gcs.call("node_list", timeout=5)
                self._node_view_ts = now
            except Exception:
                pass
        return self._node_view

    def maybe_forward_task(self, spec: dict) -> bool:
        """Decide placement for a task/actor-create spec. Returns True when
        the spec was forwarded to a peer node (caller only tracks refs).
        Placement is resource-feasibility first-fit with spillback;
        NodeAffinity / SPREAD strategies are honored (reference
        scheduling_strategies.py); dependency locality is future work
        (the reference's hybrid policy weighs both)."""
        if spec.get("pg") is not None:
            # bundle-pinned work routes to the node that reserved the
            # bundle — on head AND daemons (stale forwards re-route)
            return self._route_pg(spec)
        if not self.is_scheduler:
            # daemons execute what they're given — EXCEPT nested
            # submissions this node can never satisfy, which would queue
            # forever; those spill to a feasible peer (reference raylet
            # spillback, hybrid_scheduling_policy.h:50 role). Node
            # affinity binds nested submissions too.
            strat = spec.get("strategy")
            if strat is not None and strat[0] == "node_affinity":
                out = self._place_node_affinity(spec, strat[1], strat[2])
                if out is not None:
                    return out
            if strat is not None and strat[0] == "node_labels":
                out = self._place_node_labels(spec, strat[1], strat[2])
                if out is not None:
                    return out
            return self._spill_if_infeasible(spec)
        res = spec.get("resources") or {}
        strat = spec.get("strategy")
        if strat is not None and strat[0] == "node_affinity":
            out = self._place_node_affinity(spec, strat[1], strat[2])
            if out is not None:
                return out
            # soft affinity to a dead node: fall through to normal placement
        elif strat is not None and strat[0] == "node_labels":
            out = self._place_node_labels(spec, strat[1], strat[2])
            if out is not None:
                return out
        elif strat is not None and strat[0] == "spread":
            return self._place_spread(spec, res)
        elif strat is not None and strat[0] == "random":
            return self._place_random(spec, res)
        with self.rt.lock:
            local_total_ok = all(
                self.rt.total.get(k, 0.0) >= v for k, v in res.items())
            local_avail_ok = all(
                self.rt.avail.get(k, 0.0) >= v for k, v in res.items())
        dep_bytes = self._dep_bytes_by_node(spec)
        if local_avail_ok:
            # local fast path — UNLESS the task's big dependencies live on
            # a peer that could also run it: ship the task to the data
            # rather than the data to the task (reference hybrid policy's
            # locality scoring, scorer.h)
            if dep_bytes:
                best = max(dep_bytes, key=dep_bytes.get)
                gain = dep_bytes[best] - dep_bytes.get(self.node_id, 0)
                if best != self.node_id and gain >= LOCALITY_MIN_BYTES:
                    # require TOTAL feasibility, not instantaneous avail:
                    # the dep's producer often just finished there, so the
                    # heartbeat view still shows its slot taken — queueing
                    # at the data beats shipping the data
                    target = next(
                        (n for n in self._nodes()
                         if n["node_id"] == best and n["alive"]
                         and all(n["resources"].get(k, 0.0) >= v
                                 for k, v in res.items())), None)
                    if target is not None and self._forward(
                            best, spec, reason="locality"):
                        return True
            return False
        candidates, with_avail = self._feasible_peers(res)
        if not candidates:
            return False  # infeasible everywhere -> queue locally
        if local_total_ok and not with_avail:
            return False  # locally feasible soon; nobody free now anyway
        return self._forward_to_best(with_avail or candidates, res, spec,
                                     dep_bytes)

    def _feasible_peers(self, res: Dict[str, float]):
        """(feasible-by-total, also-free-now) peer views for ``res``."""
        candidates = [
            n for n in self._nodes()
            if n["alive"] and n["node_id"] != self.node_id
            and all(n["resources"].get(k, 0.0) >= v for k, v in res.items())
        ]
        with_avail = [
            n for n in candidates
            if all(n["avail"].get(k, 0.0) >= v for k, v in res.items())
        ]
        return candidates, with_avail

    def _forward_to_best(self, picks, res: Dict[str, float],
                         spec: dict, dep_bytes=None,
                         reason: str = "resources") -> bool:
        """Rank feasible peers: dependency bytes first, then hybrid
        pack-until-threshold-then-spread on CPU utilization (reference
        hybrid_scheduling_policy.h:50 — pack onto busy-but-not-saturated
        nodes to keep the cluster compact, spread past the threshold)."""

        def key(n):
            total = n["resources"].get("CPU", 0.0)
            avail = n["avail"].get("CPU", 0.0)
            util = 1.0 - (avail / total) if total else 0.0
            packing = util < HYBRID_PACK_THRESHOLD
            return (-(dep_bytes or {}).get(n["node_id"], 0),
                    0 if packing else 1,
                    -util if packing else util)

        for target in sorted(picks, key=key):
            # decrement the cached view so a burst of submissions spreads
            # across peers instead of piling onto one node until the next
            # heartbeat
            for k, v in res.items():
                target["avail"][k] = target["avail"].get(k, 0.0) - v
            if self._forward(target["node_id"], spec, reason=reason):
                return True
            # handoff failed (peer died mid-lease-grant, chaos
            # daemon.lease_grant): NEVER strand the spec on one dead pick —
            # refresh the view and try the next candidate
            self._node_view_ts = 0.0
        return False

    def _dep_bytes_by_node(self, spec: dict) -> Dict[bytes, int]:
        """READY-segment bytes of the spec's direct ref args, per holder
        node. Pending deps contribute nothing (their location is unknown at
        submit time — the reference schedules those by owner hint, future
        work here). Served from a local cache; misses cost one batched
        directory lookup."""
        all_refs = ts.arg_refs(spec["args"], spec["kwargs"])[:16]
        if not all_refs:
            return {}
        # hot-path guard: the local view already knows most args (driver
        # puts, delivered results). Only refs that are locally unknown or
        # locally big are worth a directory round-trip.
        refs = []
        for o in all_refs:
            st = self.rt.gcs.object_state(o)
            if st is None or st.status == "PENDING":
                # Locally pending may be READY in the global directory —
                # but ONLY if its producer was forwarded to a peer. A
                # locally-produced pending ref (the f.remote(g.remote())
                # chain hot path) cannot be remote: skip the round-trip,
                # every submit would pay it (review r3 finding).
                with self._forwarded_lock:
                    fwd = o.binary() in self._result_hints
                if fwd:
                    refs.append(o)
            elif (st.status == "READY" and st.inline is None
                    and st.size >= LOCALITY_MIN_BYTES):
                refs.append(o)
        if not refs:
            return {}
        missing = [o.binary() for o in refs
                   if o.binary() not in self._obj_info]
        if missing and time.monotonic() >= self._obj_info_down_until:
            try:
                infos = self.gcs.call("obj_info", missing, timeout=5)
            except Exception:
                infos = {}
                self._obj_info_down_until = time.monotonic() + 5.0
            if len(self._obj_info) > 4096:
                self._obj_info.clear()
            for b, inf in (infos or {}).items():
                self._obj_info[b] = inf
        out: Dict[bytes, int] = {}
        for o in refs:
            inf = self._obj_info.get(o.binary())
            if not inf:
                continue
            size, locs = inf
            for nid in locs:
                out[nid] = out.get(nid, 0) + int(size)
        return out

    def _spill_if_infeasible(self, spec: dict) -> bool:
        res = spec.get("resources") or {}
        with self.rt.lock:
            if all(self.rt.total.get(k, 0.0) >= v for k, v in res.items()):
                return False  # feasible here: run/queue locally
        candidates, with_avail = self._feasible_peers(res)
        picks = (with_avail or candidates)
        if not picks:
            return False  # nowhere feasible: queue locally (matches head)
        return self._forward_to_best(picks, res, spec)

    @staticmethod
    def _labels_match(labels: Dict[str, str], preds) -> bool:
        for key, op, vals in preds:
            v = labels.get(key)
            if op == "in":
                ok = v in vals
            elif op == "not_in":
                ok = v is not None and v not in vals
            elif op == "exists":
                ok = v is not None
            elif op == "does_not_exist":
                ok = v is None
            else:
                ok = False
            if not ok:
                return False
        return True

    def _place_node_labels(self, spec: dict, hard, soft):
        """NodeLabelSchedulingStrategy (reference
        node_label_scheduling_policy.h role): hard predicates filter the
        candidate set (no match anywhere -> fail the task loudly); soft
        predicates rank it. Returns False to run/queue locally, True when
        handled (forwarded or failed) — never None: falling through to
        generic placement could forward to a node violating the hard
        predicates."""
        res = spec.get("resources") or {}
        my_labels = dict(getattr(self.rt, "labels", {}))
        nodes = [n for n in self._nodes() if n["alive"]]
        candidates = [
            n for n in nodes
            if self._labels_match(n.get("labels", {}), hard)
            and all(n["resources"].get(k, 0.0) >= v for k, v in res.items())
        ]
        local_ok = (self._labels_match(my_labels, hard)
                    and all(self.rt.total.get(k, 0.0) >= v
                            for k, v in res.items()))
        if not candidates and not local_ok:
            self._fail_returns(spec, ValueError(
                f"no alive node matches label predicates {hard} with "
                f"resources {res}"))
            return True
        if soft:
            preferred = [n for n in candidates
                         if self._labels_match(n.get("labels", {}), soft)]
            local_preferred = local_ok and self._labels_match(my_labels,
                                                              soft)
            if preferred and not local_preferred:
                if self._forward_to_best(preferred, res, spec,
                                         reason="strategy"):
                    return True
            if local_preferred:
                return False  # run locally (soft + hard match here)
        if local_ok:
            return False  # run locally (hard match here)
        others = [n for n in candidates if n["node_id"] != self.node_id]
        if others and self._forward_to_best(others, res, spec,
                                            reason="strategy"):
            return True
        self._fail_returns(spec, ValueError(
            f"no reachable node matches label predicates {hard}"))
        return True

    def _place_node_affinity(self, spec: dict, node_id: bytes, soft: bool):
        """Pin to a node (reference NodeAffinitySchedulingStrategy). Hard
        affinity to a dead/unknown node fails the task; soft falls back to
        normal placement (``None`` = caller continues the normal path)."""
        if node_id == self.node_id:
            return False  # pinned here: run locally
        target = next((n for n in self._nodes()
                       if n["node_id"] == node_id and n["alive"]), None)
        if target is None:
            if soft:
                return None  # soft: let normal placement handle it
            self._fail_returns(spec, WorkerCrashedError(
                f"node affinity target {node_id.hex()[:8]} is not alive"))
            return True
        return self._forward(node_id, spec, reason="strategy")

    def _feasible_slots(self, res: Dict[str, float]) -> List[dict]:
        """Candidate slot list for spread/random placement: this node first
        (when feasible by total), then every alive feasible peer."""
        feasible = [n for n in self._nodes() if n["alive"] and all(
            n["resources"].get(k, 0.0) >= v for k, v in res.items())]
        with self.rt.lock:
            local_ok = all(self.rt.total.get(k, 0.0) >= v
                           for k, v in res.items())
        return ([{"node_id": self.node_id}] if local_ok else []) + [
            n for n in feasible if n["node_id"] != self.node_id]

    def _place_spread(self, spec: dict, res: Dict[str, float]) -> bool:
        """Round-robin over feasible nodes including this one (reference
        SPREAD strategy)."""
        slots = self._feasible_slots(res)
        if not slots:
            return False
        start = self._spread_rr % len(slots)
        self._spread_rr += 1
        # a failed handoff (peer died mid-lease-grant) rotates to the next
        # feasible slot instead of stranding the spec in the local queue
        for off in range(len(slots)):
            pick = slots[(start + off) % len(slots)]
            if pick["node_id"] == self.node_id:
                return False
            if self._forward(pick["node_id"], spec, reason="strategy"):
                return True
            self._node_view_ts = 0.0
        return False

    def _place_random(self, spec: dict, res: Dict[str, float]) -> bool:
        """Uniform over feasible nodes including this one (reference
        ``random_scheduling_policy.h`` role; together with the strategy
        dispatch in ``maybe_forward_task`` — hybrid default, spread,
        node-affinity, node-label — this completes the reference's
        ``composite_scheduling_policy.h`` policy set)."""
        import random as _random

        slots = self._feasible_slots(res)
        _random.shuffle(slots)
        # same failed-handoff fallback as spread: walk the (shuffled)
        # feasible slots until one accepts, stop at a local slot
        for pick in slots:
            if pick["node_id"] == self.node_id:
                return False
            if self._forward(pick["node_id"], spec, reason="strategy"):
                return True
            self._node_view_ts = 0.0
        return False

    def _record_forward(self, node_id: bytes, spec: dict) -> None:
        """Bookkeeping after handing a spec to a peer: failure-retry map,
        completion retirement, owner hints for locality, the permit-relay
        route for backpressured streams, and a state-only watch on the
        returns (bytes stay with the producer)."""
        with self._forwarded_lock:
            self._forwarded.setdefault(node_id, {})[spec["task_id"]] = spec
            if spec["return_ids"]:
                self._fwd_by_oid[spec["return_ids"][0]] = (node_id,
                                                           spec["task_id"])
            if len(self._result_hints) > 100000:
                self._result_hints.clear()
            for rid in spec["return_ids"]:
                self._result_hints[rid] = node_id
            if spec.get("stream_backpressure"):
                # the producer parks on the EXECUTING node's permit
                # counter; consumer acks arriving here must relay there
                self._stream_routes[spec["task_id"]] = node_id
        self.watch_many([ObjectID(b) for b in spec["return_ids"]],
                        fetch=False)

    def _begin_attempt(self, spec: dict):
        """Receiver half of the lost-reply handshake: pop the forwarder's
        per-attempt token and claim it, so a re-sent delivery (reply lost,
        spec possibly already enqueued) is a no-op. A duplicate arriving
        while the first delivery's submit is STILL RUNNING (the forwarder
        timed out with the original queued behind it on the RPC pool)
        waits for that outcome instead of guessing: committed -> report
        duplicate, aborted -> re-claim and run the submit itself — a
        duplicate must never acknowledge a submit that then fails.
        Token-less specs (direct actor routing) always accept. Returns
        ``(duplicate, token)``."""
        tok = spec.pop("_fwd_attempt", None)
        if tok is None:
            return False, None
        while True:
            with self._accepted_lock:
                ent = self._accepted_specs.get(tok)
                if ent is None:
                    ent = [threading.Event(), False]  # [done, committed]
                    self._accepted_specs[tok] = ent
                    if len(self._accepted_specs) > 4096:
                        # trim SETTLED entries only (oldest first): an
                        # in-flight entry evicted here would let a parked
                        # duplicate re-claim mid-submit (double enqueue)
                        # or orphan its commit; settled ones are safe —
                        # their re-send window (≤10s) is long gone by the
                        # time 4096 newer attempts have arrived
                        for k in list(self._accepted_specs):
                            if len(self._accepted_specs) <= 4096:
                                break
                            if self._accepted_specs[k][0].is_set():
                                del self._accepted_specs[k]
                    return False, tok
            ent[0].wait(60)
            with self._accepted_lock:
                if self._accepted_specs.get(tok) is not ent:
                    continue  # aborted: re-claim on the next pass
                if ent[1]:
                    return True, tok  # first delivery enqueued it
            if ent[0].is_set():
                continue  # abort raced the get: re-claim
            # still in flight after 60s: a local enqueue stuck that long
            # means the node is melted — report the near-certain outcome
            return True, tok

    def _commit_attempt(self, tok) -> None:
        if tok is None:
            return
        with self._accepted_lock:
            ent = self._accepted_specs.get(tok)
        if ent is not None:
            ent[1] = True
            ent[0].set()

    def _abort_attempt(self, tok) -> None:
        if tok is None:
            return
        with self._accepted_lock:
            ent = self._accepted_specs.pop(tok, None)
        if ent is not None:
            ent[0].set()

    def _call_with_attempt(self, peer, method: str, spec: dict) -> bool:
        """Deliver a spec to a peer under the lost-reply handshake.

        A TRANSPORT failure is ambiguous (never delivered vs delivered-
        but-reply-lost), so re-send the SAME per-attempt token to the
        SAME peer once — the receiver's dedupe (:meth:`_begin_attempt`)
        makes the re-send safe either way. A remote handler exception is
        a definite reply (nothing enqueued: the receiver releases the
        token on failure) and connection-refused means nothing was
        delivered — neither re-sends. A partitioned-but-alive peer can
        still double-execute after False is returned; without leases that
        window is inherent, and the GCS declares such a node dead (and
        evicts it from the candidate view) at node_timeout anyway. The
        re-send timeout is short: the common re-send target is a dead or
        wedged peer, and the candidate-walk callers pay this cost per
        candidate."""
        wire = dict(spec)
        wire["_fwd_attempt"] = os.urandom(8)
        try:
            peer.call(method, wire, timeout=30)
            return True
        except ConnectionRefusedError:
            return False  # never delivered
        except (TimeoutError, ConnectionError, EOFError, OSError):
            try:
                peer.call(method, wire, timeout=10)
                return True
            except Exception:
                return False
        except Exception:
            return False  # peer replied with an error: nothing enqueued

    def _forward(self, node_id: bytes, spec: dict,
                 reason: str = "resources") -> bool:
        peer = self._peer(node_id)
        if peer is None:
            return False
        if not self._call_with_attempt(peer, "submit_spec", spec):
            return False
        try:
            # spillback decision record (reference scheduler spillback
            # metrics role): WHY work left this node
            _transfer_metrics()["forwarded"]._inc_key(
                _FWD_KEYS.get(reason) or _FWD_KEYS["resources"])
        except Exception:
            pass
        self._record_forward(node_id, spec)
        aid = spec.get("actor_id")
        if aid:
            self._remote_actors[aid] = node_id
        return True

    # ------------------------------------------------------------------
    # placement groups: cross-node gang scheduling
    #
    # Role analog: GcsPlacementGroupManager + GcsPlacementGroupScheduler
    # (``src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:111``,
    # bundle policies ``bundle_scheduling_policy.h``): 2-phase bundle
    # reservation (prepare on every node, then commit — abort-all on any
    # failure, so reservation is all-or-nothing), strategy-driven
    # placement, release + reschedule on node death. The CREATING adapter
    # owns the protocol; the GCS records decisions and broadcasts updates.
    # ------------------------------------------------------------------

    def create_pg(self, pg_id: bytes, bundles: List[Dict[str, float]],
                  strategy: str) -> None:
        bmap = {i: b for i, b in enumerate(bundles)}
        last_err = None
        for attempt in range(3):  # avail races: re-place with a fresh view
            try:
                assignment = self._assign_bundles(bmap, strategy)
            except ValueError as e:
                raise ValueError(
                    f"placement group infeasible under {strategy}: {e}"
                ) from None
            committed = self._reserve_assignment(pg_id, bmap, assignment)
            if committed:
                break
            last_err = "reservation failed"
            self._node_view_ts = 0.0  # force-refresh the resource view
            time.sleep(0.2 * (attempt + 1))
        else:
            raise ValueError(
                f"placement group infeasible under {strategy}: {last_err}")
        failed = [i for i in range(len(bundles)) if i not in committed]
        # registration is retried through a GCS outage (chaos: kill -9 in
        # the reserve->commit window): the bundles are already committed
        # on their nodes, and an unregistered group would strand those
        # reservations until the stage reaper — never park them forever
        reg_err = None
        for attempt in range(5):
            try:
                self.gcs.call("pg_register", pg_id, bundles, strategy,
                              [committed.get(i) for i in range(len(bundles))],
                              self.node_id, timeout=30)
                reg_err = None
                break
            except Exception as e:
                reg_err = e
                time.sleep(0.5 * (attempt + 1))
        if reg_err is not None:
            for nid in set(committed.values()):
                try:
                    self._pg_call(nid, "pg_release", pg_id)
                except Exception:
                    pass
            raise OSError(
                f"placement group registration failed: {reg_err}")
        with self._pg_lock:
            self._pg_nodes[pg_id] = {i: committed.get(i)
                                     for i in range(len(bundles))}
            self._pg_meta[pg_id] = {"bundles": bundles, "strategy": strategy}
            self._my_pgs[pg_id] = {"bundles": bundles, "strategy": strategy}
            if failed:
                # commit failed on a live-but-unreachable node: its stage
                # was aborted — re-place those bundles like a node death
                self._pg_pending.setdefault(pg_id, set()).update(failed)
        if failed:
            self._io.submit(self._pg_reschedule_pending)

    def remove_pg(self, pg_id: bytes) -> None:
        amap = self._pg_assignment(pg_id)
        if not isinstance(amap, dict):
            amap = {}
        nodes = {nid for nid in amap.values() if nid is not None}
        nodes.add(self.node_id)
        for nid in nodes:
            try:
                self._pg_call(nid, "pg_release", pg_id)
            except Exception:
                pass
        try:
            self.gcs.call("pg_remove", pg_id, timeout=10)
        except Exception:
            pass
        with self._pg_lock:
            self._pg_nodes.pop(pg_id, None)
            self._pg_meta.pop(pg_id, None)
            self._my_pgs.pop(pg_id, None)
            self._pg_pending.pop(pg_id, None)
            parked = self._pg_parked.pop(pg_id, [])
        for spec in parked:
            self._fail_returns(spec, ValueError("placement group removed"))

    def _assign_bundles(self, bundles: Dict[int, Dict[str, float]],
                        strategy: str,
                        used_nodes: frozenset = frozenset()
                        ) -> Dict[int, bytes]:
        """Pick a node per bundle against the cluster resource view.
        ``used_nodes``: nodes already holding OTHER bundles of this group
        (partial reschedule) — STRICT_PACK must join them, STRICT_SPREAD
        must avoid them. Raises ValueError when infeasible."""
        nodes = [n for n in self._nodes() if n["alive"]]
        avail = {n["node_id"]: dict(n["avail"]) for n in nodes}
        if self.node_id in avail:
            with self.rt.lock:  # our own view is fresher than heartbeats
                avail[self.node_id] = dict(self.rt.avail)
        if not avail:
            raise ValueError("no alive nodes")

        def fits(nid, res):
            return all(avail[nid].get(k, 0.0) >= v for k, v in res.items())

        def take(nid, res):
            for k, v in res.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v

        order = sorted(avail, key=lambda nid: -sum(avail[nid].values()))
        out: Dict[int, bytes] = {}
        if strategy == "STRICT_PACK":
            candidates = [n for n in used_nodes if n in avail] or order
            for nid in candidates:
                scratch = dict(avail[nid])
                ok = True
                for _, res in sorted(bundles.items()):
                    if not all(scratch.get(k, 0.0) >= v
                               for k, v in res.items()):
                        ok = False
                        break
                    for k, v in res.items():
                        scratch[k] -= v
                if ok:
                    return {i: nid for i in bundles}
            raise ValueError("no single node fits all bundles")
        if strategy in ("STRICT_SPREAD", "SLICE_PACK"):
            # one bundle per DISTINCT node, all-or-nothing (a multi-host
            # TPU slice: one bundle per host, SLICE_PACK semantics)
            for i, res in sorted(bundles.items()):
                pick = next(
                    (nid for nid in order
                     if nid not in used_nodes and nid not in out.values()
                     and fits(nid, res)), None)
                if pick is None:
                    raise ValueError(
                        f"bundle {i} has no distinct feasible node")
                out[i] = pick
                take(pick, res)
            return out
        if strategy == "SPREAD":
            for i, res in sorted(bundles.items()):
                fresh = [nid for nid in order
                         if nid not in out.values() and fits(nid, res)]
                anyn = [nid for nid in order if fits(nid, res)]
                pick = (fresh or anyn or [None])[0]
                if pick is None:
                    raise ValueError(f"bundle {i} fits no node")
                out[i] = pick
                take(pick, res)
            return out
        # PACK (default): minimize node count — prefer nodes already used
        for i, res in sorted(bundles.items()):
            cur = [nid for nid in dict.fromkeys(out.values())
                   if fits(nid, res)]
            pick = (cur or [nid for nid in order if fits(nid, res)]
                    or [None])[0]
            if pick is None:
                raise ValueError(f"bundle {i} fits no node")
            out[i] = pick
            take(pick, res)
        return out

    def _pg_call(self, node_id: bytes, method: str, *args):
        if node_id == self.node_id:
            return {
                "pg_prepare": self.rt.pg_prepare,
                "pg_commit": self.rt.pg_commit,
                "pg_abort": self.rt.pg_abort,
                "pg_release": self.rt.pg_release_local,
            }[method](*args)
        peer = self._peer(node_id)
        if peer is None:
            raise OSError(f"peer {node_id.hex()[:8]} unreachable")
        return peer.call(method, *args, timeout=30)

    def _reserve_assignment(self, pg_id: bytes,
                            bundles: Dict[int, Dict[str, float]],
                            assignment: Dict[int, bytes]
                            ) -> Optional[Dict[int, bytes]]:
        """2-phase: prepare on every target node; abort ALL on any prepare
        failure (atomicity — an infeasible group reserves nothing). Commit
        is retried; a node whose commit still fails is aborted and its
        bundles left out of the result so the caller reschedules them —
        swallowing the failure would let the 30s stage reaper release
        resources a registered assignment still points at, hanging every
        task pinned to that bundle. Returns the committed
        ``{bundle_idx: node_id}`` or None when nothing was reserved."""
        per_node: Dict[bytes, Dict[int, dict]] = {}
        for i, nid in assignment.items():
            per_node.setdefault(nid, {})[i] = bundles[i]
        prepared: List[bytes] = []
        ok = True
        for nid, bmap in per_node.items():
            try:
                r = self._pg_call(nid, "pg_prepare", pg_id, bmap)
            except Exception:
                r = False
            if not r:
                ok = False
                break
            prepared.append(nid)
        if not ok:
            for nid in prepared:
                try:
                    self._pg_call(nid, "pg_abort", pg_id)
                except Exception:
                    pass
            return None
        # chaos site: the window between phase 1 (resources staged on every
        # node) and phase 2 (commit) — a GCS/creator death here is what the
        # 2-phase protocol + stage reaper must absorb
        from ray_tpu.util import failpoints

        failpoints.hit("adapter.pg.before_commit")
        committed: Dict[int, bytes] = {}
        for nid, bmap in per_node.items():
            done = False
            for attempt in range(3):
                try:
                    self._pg_call(nid, "pg_commit", pg_id)
                    done = True
                    break
                except Exception:
                    time.sleep(0.2 * (attempt + 1))
            if done:
                committed.update({i: nid for i in bmap})
            else:
                try:
                    self._pg_call(nid, "pg_abort", pg_id)
                except Exception:
                    pass  # dead node: its daemon's state died with it
        return committed or None

    def _pg_assignment(self, pg_id: bytes, refresh: bool = False
                       ) -> Optional[Dict[int, Optional[bytes]]]:
        """None = the GCS says the group does not exist; ``GCS_UNAVAILABLE``
        = could not ask (transient) — callers must NOT treat the latter as
        removal (a cold-cache daemon routing during a GCS restart would
        terminally fail live work)."""
        if not refresh:
            with self._pg_lock:
                m = self._pg_nodes.get(pg_id)
            if m is not None:
                return dict(m)
        rec = None
        for attempt in range(3):
            try:
                rec = self.gcs.call("pg_get", pg_id, timeout=10)
                break
            except Exception:
                time.sleep(0.3 * (attempt + 1))
        else:
            return GCS_UNAVAILABLE
        if rec is None:
            return None
        amap = {i: nid for i, nid in enumerate(rec["assignments"])}
        with self._pg_lock:
            self._pg_nodes[pg_id] = dict(amap)
            self._pg_meta[pg_id] = {"bundles": rec["bundles"],
                                    "strategy": rec["strategy"]}
        return amap

    def _route_pg(self, spec: dict) -> bool:
        """Route a bundle-pinned spec to the node holding its bundle.
        Returns False to run locally; True when forwarded, parked (bundle
        lost, awaiting reschedule), or terminally failed."""
        pg_id = spec["pg"]
        idx = spec.get("bundle_index", -1)
        amap = self._pg_assignment(pg_id)
        if amap is GCS_UNAVAILABLE:
            # transient GCS outage, not removal: retry shortly
            t = threading.Timer(2.0, lambda: self.rt.submit_spec(spec))
            t.daemon = True
            t.start()
            return True
        if amap is None:
            with self.rt.lock:
                if pg_id in self.rt.pgs:
                    return False  # locally-known group (pre-cluster)
            self._fail_returns(spec, ValueError(
                "placement group not found (removed?)"))
            return True
        if idx >= 0:
            target = amap.get(idx)
            if target is None:
                self._park_pg_spec(pg_id, spec)  # lost bundle: reschedule
                return True
            if target == self.node_id:
                return False
            if self._forward(target, spec, reason="pg"):
                return True
            self._park_pg_spec(pg_id, spec)
            return True
        # any-bundle: round-robin over nodes whose bundle TOTALS fit the
        # request (live availability is enforced by the executing node)
        with self._pg_lock:
            meta = self._pg_meta.get(pg_id) or {}
        bundles = meta.get("bundles") or []
        res = spec.get("resources") or {}
        cands = []
        for i, nid in sorted(amap.items()):
            if nid is None or i >= len(bundles) or nid in cands:
                continue
            if all(bundles[i].get(k, 0.0) >= v for k, v in res.items()):
                cands.append(nid)
        if not cands:
            self._fail_returns(spec, ValueError(
                "no bundle in the placement group fits the request"))
            return True
        self._pg_rr += 1
        pick = cands[self._pg_rr % len(cands)]
        if pick == self.node_id:
            return False
        if self._forward(pick, spec, reason="pg"):
            return True
        for nid in cands:  # fallback sweep
            if nid == self.node_id:
                return False
            if self._forward(nid, spec, reason="pg"):
                return True
        self._park_pg_spec(pg_id, spec)
        return True

    def _park_pg_spec(self, pg_id: bytes, spec: dict) -> None:
        with self._pg_lock:
            self._pg_parked.setdefault(pg_id, []).append(spec)

    def _on_pg_event(self, payload: dict) -> None:
        pg_id = payload["pg_id"]
        if payload.get("event") == "removed":
            with self._pg_lock:
                self._pg_nodes.pop(pg_id, None)
                self._pg_meta.pop(pg_id, None)
                self._my_pgs.pop(pg_id, None)
                self._pg_pending.pop(pg_id, None)
                parked = self._pg_parked.pop(pg_id, [])
            for spec in parked:
                self._fail_returns(spec, ValueError("placement group removed"))
            self.rt.pg_release_local(pg_id)  # idempotent local cleanup
            return
        amap = {i: nid for i, nid in enumerate(payload["assignments"])}
        with self._pg_lock:
            if pg_id in self._pg_nodes or pg_id in self._pg_parked:
                self._pg_nodes[pg_id] = dict(amap)
            parked = self._pg_parked.pop(pg_id, [])
        back = []
        for spec in parked:
            idx = spec.get("bundle_index", -1)
            if idx >= 0 and amap.get(idx) is None:
                back.append(spec)  # still unplaced
            else:
                self.rt.submit_spec(spec)  # re-enters routing
        if back:
            with self._pg_lock:
                self._pg_parked.setdefault(pg_id, []).extend(back)

    def _pg_reschedule_pending(self) -> None:
        """Re-place bundles lost to node death for groups WE created."""
        with self._pg_lock:
            pending = {pg: set(idxs)
                       for pg, idxs in self._pg_pending.items() if idxs}
        for pg_id, idxs in pending.items():
            meta = self._my_pgs.get(pg_id)
            if meta is None:
                continue
            bundles = {i: meta["bundles"][i] for i in idxs}
            amap = self._pg_assignment(pg_id, refresh=True)
            if not isinstance(amap, dict):
                continue  # GCS unreachable or group gone: next trigger
            used = frozenset(nid for i, nid in amap.items()
                             if nid is not None and i not in idxs)
            self._node_view_ts = 0.0
            try:
                newa = self._assign_bundles(bundles, meta["strategy"],
                                            used_nodes=used)
            except ValueError:
                continue  # infeasible now; retried on the next node-up
            committed = self._reserve_assignment(pg_id, bundles, newa)
            if not committed:
                continue
            try:
                self.gcs.call("pg_update_assignment", pg_id,
                              {i: nid for i, nid in committed.items()},
                              timeout=30)
            except Exception:
                pass
            with self._pg_lock:
                m = self._pg_nodes.setdefault(pg_id, {})
                m.update(committed)
                rem = self._pg_pending.get(pg_id)
                if rem:
                    rem.difference_update(committed)
            logger.info("rescheduled %d bundle(s) of pg %s",
                        len(committed), pg_id.hex()[:8])

    def route_actor_call(self, spec: dict) -> bool:
        """Forward an actor method call to the hosting node. Returns True
        when handled (including terminal failure)."""
        aid = spec["actor_id"]
        node_id = self._remote_actors.get(aid)
        if node_id is None:
            rec = None
            try:
                rec = self.gcs.call("actor_get", aid, timeout=5)
            except Exception:
                pass
            if rec is None:
                return False
            if rec["state"] == "DEAD":
                self._fail_returns(spec, ActorDiedError("actor is dead"))
                return True
            node_id = rec["node_id"]
            if node_id == self.node_id:
                return False  # ours after all (race with registration)
            self._remote_actors[aid] = node_id
        for rid in spec["return_ids"]:
            self.rt.gcs.ensure_object(ObjectID(rid))
        peer = self._peer(node_id)
        # lost-reply handshake matters most here: actor calls are
        # non-idempotent, so an ambiguous transport failure re-sends the
        # SAME attempt token (the receiver dedupes) instead of failing a
        # call the peer may already be executing
        ok = (peer is not None
              and self._call_with_attempt(peer, "submit_actor_spec", spec))
        if not ok:
            self._fail_returns(spec, ActorDiedError(
                f"actor's node {node_id.hex()[:8]} unreachable"))
            return True
        try:
            _transfer_metrics()["forwarded"]._inc_key(
                _FWD_KEYS["actor_route"])
        except Exception:
            pass
        self._record_forward(node_id, spec)
        return True

    def relay_stream_consumed(self, task_id: bytes, n: int,
                              owner: Optional[bytes] = None) -> None:
        """Consumer-side ack for a stream whose producer runs on a peer:
        forward the absolute consumed count (idempotent, monotonic) to the
        node holding the parked producer. Chains across multi-hop
        forwarding: each hop relays to the next. A consumer on a node with
        NO route (the generator was handed to a third node) relays to the
        stream's OWNER, which does hold the route."""
        with self._forwarded_lock:
            node_id = self._stream_routes.get(task_id)
        if node_id is None:
            if owner is not None and owner != self.node_id:
                node_id = owner
            else:
                return
        self._io.submit(self._relay_sc, node_id, task_id, n)

    def _relay_sc(self, node_id: bytes, task_id: bytes, n: int) -> None:
        peer = self._peer(node_id)
        if peer is None:
            return
        try:
            peer.cast("stream_consumed", task_id, n)
        except Exception:
            pass  # producer unthrottles via its permit-wait timeout valve

    def _fail_returns(self, spec: dict, exc: Exception):
        err = cloudpickle.dumps(exc)
        for rid in spec["return_ids"]:
            self.rt.gcs.mark_error(ObjectID(rid), err, _local_only=True)

    # ------------------------------------------------------------------
    # actor + name + fn + kv global mirrors
    # ------------------------------------------------------------------

    def cancel_remote(self, oid_b: bytes, force: bool = False) -> bool:
        """Route a cancel to the node actually running the task (it was
        forwarded there). True when delivered — the peer's normal
        done(error) path resolves the refs globally."""
        with self._forwarded_lock:
            ent = self._fwd_by_oid.get(oid_b)
        if ent is None:
            return False
        node_id, _task_id = ent
        peer = self._peer(node_id)
        if peer is None:
            return False
        try:
            peer.call("cancel_task", oid_b, force, timeout=10)
            return True
        except Exception:
            return False

    def kill_remote_actor(self, actor_id: bytes, no_restart: bool):
        node_id = self._remote_actors.get(actor_id)
        if node_id is None:
            try:
                rec = self.gcs.call("actor_get", actor_id, timeout=5)
            except Exception:
                return
            if rec is None:
                return
            node_id = rec["node_id"]
        peer = self._peer(node_id)
        if peer is not None:
            try:
                peer.call("kill_actor", actor_id, no_restart, timeout=10)
            except Exception:
                pass

    def publish_actor(self, actor_id: bytes, name: str):
        self.gcs.cast("actor_register", actor_id, self.node_id, name or "")

    def publish_actor_state(self, actor_id: bytes, state: str):
        self.gcs.cast("actor_update", actor_id, state)

    def lookup_named(self, name: str) -> Optional[bytes]:
        try:
            return self.gcs.call("actor_lookup", name, timeout=5)
        except Exception:
            return None

    def publish_fn(self, h: str, blob: bytes):
        # synchronous: the blob must be globally visible BEFORE any spec
        # referencing it can be forwarded (an async cast races the forward
        # and a remote worker's fn_get can observe not-found)
        try:
            self.gcs.call("fn_put", h, blob, timeout=30)
        except Exception:
            self.gcs.cast("fn_put", h, blob)  # best effort under outage

    def publish_fn_async(self, h: str, blob: bytes):
        """For worker-pipe receiver threads (must not block): a dedicated
        single-thread lane bounds the publish delay under io-pool
        saturation; remote consumers' fetch_fn poll covers the gap."""
        self._publish_io.submit(self.publish_fn, h, blob)

    def fetch_fn(self, h: str, timeout_s: float = 15.0) -> Optional[bytes]:
        """Poll: the publishing driver may still be mid-flight (blobs are
        immutable, so waiting is safe)."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                blob = self.gcs.call("fn_get", h, timeout=10)
            except Exception:
                blob = None
            if blob is not None or time.monotonic() >= deadline:
                return blob
            time.sleep(0.1)

    def kv_op(self, op: str, *args):
        """Cluster KV is globally consistent: always through the GCS.

        Pads the optional trailing args (namespace / overwrite) that the
        local ``Gcs`` signatures default.
        """
        full = list(args)
        if op == "put":
            full += ["default", True][len(full) - 2:] if len(full) < 4 else []
        elif op in ("get", "del"):
            if len(full) < 2:
                full.append("default")
        elif op == "keys":
            if len(full) == 0:
                full.append("")
            if len(full) < 2:
                full.append("default")
        return self.gcs.call("kv_" + op, *full, timeout=30)

    def node_info(self) -> List[dict]:
        return [
            {"NodeID": n["node_id"].hex(),
             "Alive": n["alive"], "Resources": dict(n["resources"]),
             "alive": n["alive"],
             "stats": dict(n.get("stats") or {})}
            for n in self._nodes()
        ]

    # ------------------------------------------------------------------
    # node lifecycle fan-out (elastic membership, r20)
    # ------------------------------------------------------------------

    def subscribe_node_events(self, cb) -> None:
        """Register ``cb(payload)`` for node up/down pubsub payloads
        (``{"event": "down"|"up", "node_id": ..., "cause": ..., ...}``).
        Callbacks run on the adapter io pool; down-events are delivered
        AFTER the adapter's own cleanup for the dead node. Subscribers
        must be quick and exception-safe — the elastic BackendExecutor
        just records the payload and pokes an event."""
        with self._node_event_lock:
            if cb not in self._node_event_subs:
                self._node_event_subs.append(cb)

    def unsubscribe_node_events(self, cb) -> None:
        with self._node_event_lock:
            try:
                self._node_event_subs.remove(cb)
            except ValueError:
                pass

    def _notify_node_event(self, payload: dict) -> None:
        with self._node_event_lock:
            subs = list(self._node_event_subs)
        for cb in subs:
            try:
                cb(payload)
            except Exception:
                logger.exception("node-event subscriber failed")

    def _node_down_and_notify(self, payload: dict) -> None:
        try:
            self._node_down(payload)
        finally:
            self._notify_node_event(dict(payload))

    # ------------------------------------------------------------------
    # failure handling
    # ------------------------------------------------------------------

    def _node_down(self, payload: dict):
        node_id = payload["node_id"]
        # locality entries naming the dead node would keep steering tasks
        # at it (advisor r3); drop any whose location set includes it.
        # Whole block guarded: _obj_info is mutated unlocked by the
        # scheduler thread, and a surprise here must not abort the peer
        # close / forwarded-task retry cleanup below.
        try:
            for b, inf in list(self._obj_info.items()):
                if inf and node_id in (inf[1] or ()):
                    self._obj_info.pop(b, None)
        except Exception:
            pass
        with self._peers_lock:
            peer = self._peers.pop(node_id, None)
            self._peer_addrs.pop(node_id, None)
        if peer is not None:
            peer.close()
        dead_actors = set(payload.get("dead_actors", []))
        with self._forwarded_lock:
            lost = self._forwarded.pop(node_id, {})
            for tid in lost:
                self._stream_routes.pop(tid, None)
        for task_id, spec in lost.items():
            if spec.get("actor_id") and spec["type"] != ts.ACTOR_CREATE:
                self._fail_returns(spec, ActorDiedError(
                    "actor's node died"))
                continue
            if spec.get("retries_left", 0) > 0 or spec["type"] == ts.ACTOR_CREATE:
                spec = dict(spec)
                if spec.get("retries_left", 0) > 0:
                    spec["retries_left"] -= 1
                logger.info("retrying task %s from dead node %s",
                            task_id.hex()[:8], node_id.hex()[:8])
                self.rt.submit_spec(spec)
            else:
                self._fail_returns(spec, WorkerCrashedError(
                    f"node {node_id.hex()[:8]} died running task"))
        for aid in dead_actors:
            self._remote_actors.pop(aid, None)
        lost_pgs = payload.get("lost_pgs") or {}
        mine = False
        with self._pg_lock:
            for pg_id, idxs in lost_pgs.items():
                m = self._pg_nodes.get(pg_id)
                if m is not None:
                    for i in idxs:
                        m[i] = None
                if pg_id in self._my_pgs:
                    self._pg_pending.setdefault(pg_id, set()).update(idxs)
                    mine = True
        if mine:
            self._pg_reschedule_pending()

    # ------------------------------------------------------------------

    def _peer(self, node_id: bytes) -> Optional[RpcClient]:
        with self._peers_lock:
            peer = self._peers.get(node_id)
        if peer is not None:
            return peer
        addr = self._peer_addrs.get(node_id)
        if addr is None:
            for n in self._nodes():
                if n["node_id"] == node_id and n["alive"]:
                    addr = n["addr"]
                    break
        if not addr:
            return None
        try:
            peer = RpcClient(addr, self.authkey)
        except Exception:
            return None
        with self._peers_lock:
            existing = self._peers.get(node_id)
            if existing is not None:
                peer.close()
                return existing
            self._peers[node_id] = peer
            self._peer_addrs[node_id] = addr
        return peer
